"""Task-queue master: dataset sharding with fault tolerance.

Reference: go/master/service.go — partition dataset chunks into tasks
(:106), todo/pending/done queues (:89-106), GetTask (:368) hands out work
with a timeout, TaskFinished (:411) retires it, TaskFailed (:455) re-queues
with a per-task failure budget (failureMax :140), state snapshots (:207).

TPU-native deployment: ``Master`` is the thread-safe queue object;
``MasterServer`` serves it over TCP (newline-framed JSON-RPC — the Go
master's net/rpc role) so trainers in OTHER processes/hosts consume tasks
through ``MasterClient``, which duck-types the in-process API.  A trainer
that dies mid-task simply stops renewing: the task deadline lapses and the
chunk re-queues for a surviving trainer — elasticity comes from the queue
contract, not from process supervision (design doc:
doc/design/cluster_train/master_server.md)."""
from __future__ import annotations

import dataclasses
import json
import logging
import socket
import socketserver
import threading
import time
from typing import Callable, List, Optional

from ..faults import RetryPolicy, classify
from ..testing import faultinject as _fi

logger = logging.getLogger("paddle_tpu")


@dataclasses.dataclass
class Task:
    task_id: int
    chunks: List            # opaque work units (e.g. file shards)
    epoch: int = 0
    num_failures: int = 0


class Master:
    def __init__(self, chunks_per_task: int = 1, timeout_s: float = 60.0,
                 failure_max: int = 3, snapshot_path: Optional[str] = None,
                 num_epochs: int = 1):
        self.chunks_per_task = chunks_per_task
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.snapshot_path = snapshot_path
        self.num_epochs = num_epochs
        self._lock = threading.Lock()
        self.todo: List[Task] = []
        self.pending = {}           # task_id -> (Task, deadline)
        self.done: List[Task] = []
        self.epoch = 0
        self._next_id = 0
        self._saving_trainer = ""
        self._saving_until = 0.0

    # -- dataset -----------------------------------------------------------
    def set_dataset(self, chunks: List):
        """Partition chunks into tasks (service.go partition :106)."""
        with self._lock:
            self._set_dataset_locked(chunks)

    def _set_dataset_locked(self, chunks: List):
        self.todo = []
        for i in range(0, len(chunks), self.chunks_per_task):
            self.todo.append(Task(self._next_id,
                                  chunks[i:i + self.chunks_per_task],
                                  self.epoch))
            self._next_id += 1
        self.done = []
        self.pending = {}

    # -- trainer RPCs ------------------------------------------------------
    def get_task(self) -> Optional[Task]:
        with self._lock:
            self._requeue_timeouts()
            if not self.todo:
                if not self.pending and self.done \
                        and self.epoch + 1 < self.num_epochs:
                    # epoch finished: recycle for the next pass
                    self.epoch += 1
                    for t in self.done:
                        t.epoch = self.epoch
                        t.num_failures = 0
                    self.todo, self.done = self.done, []
                else:
                    return None
            t = self.todo.pop(0)
            self.pending[t.task_id] = (t, time.time() + self.timeout_s)
            return t

    def task_finished(self, task_id: int):
        with self._lock:
            ent = self.pending.pop(task_id, None)
            if ent:
                self.done.append(ent[0])
            self._snapshot()

    def stats(self) -> dict:
        """Queue counters (the Go master's /debug status view)."""
        with self._lock:
            return {"todo": len(self.todo), "pending": len(self.pending),
                    "done": len(self.done), "epoch": self.epoch}

    def task_failed(self, task_id: int):
        """Re-queue unless failure budget exhausted (service.go:455-472)."""
        with self._lock:
            ent = self.pending.pop(task_id, None)
            if not ent:
                return
            t = ent[0]
            t.num_failures += 1
            if t.num_failures >= self.failure_max:
                self.done.append(t)     # dropped from training this pass
            else:
                self.todo.append(t)

    def task_returned(self, task_id: int):
        """Politely hand an in-flight task back (a reader stopped early,
        not a crash): requeue WITHOUT burning the failure budget."""
        with self._lock:
            ent = self.pending.pop(task_id, None)
            if ent:
                self.todo.append(ent[0])

    def set_dataset_if_empty(self, chunks: List) -> bool:
        """Atomic queue priming for concurrent trainers: the first caller
        partitions the dataset, later callers no-op (a client-side
        stats-then-set would race and re-issue in-flight tasks)."""
        with self._lock:
            if self.todo or self.pending or self.done:
                return False
            self._set_dataset_locked(chunks)
            return True

    def request_save_model(self, trainer_id: str,
                           block_dur_s: float = 60.0) -> bool:
        """Elect ONE trainer to checkpoint the model (service.go:481
        RequestSaveModel): the first requester within a window wins and
        re-asking by the winner stays true; everyone else gets False until
        ``block_dur_s`` elapses.  Prevents N trainers racing on the same
        checkpoint directory."""
        if not trainer_id:
            raise ValueError("trainer id is empty")
        with self._lock:
            now = time.time()
            if now >= self._saving_until:
                self._saving_trainer = ""
            need = (self._saving_trainer == "" or
                    self._saving_trainer == trainer_id)
            if need:
                self._saving_trainer = trainer_id
                self._saving_until = now + block_dur_s
            return need

    def _requeue_timeouts(self):
        now = time.time()
        for tid in list(self.pending):
            t, deadline = self.pending[tid]
            if now > deadline:
                del self.pending[tid]
                t.num_failures += 1
                if t.num_failures < self.failure_max:
                    self.todo.append(t)
                else:
                    self.done.append(t)

    def snapshot(self):
        """Write the queue state to ``snapshot_path`` NOW (public, locked
        form of the per-``task_finished`` snapshot — the etcd snapshot of
        go/master/service.go:207)."""
        with self._lock:
            self._snapshot()

    def state_dict(self) -> dict:
        """JSON-serializable queue state (locked).  The trainer embeds
        this in its checkpoint's TrainState so the queue position commits
        ATOMICALLY with the model (a separate snapshot file can be
        durably newer than the checkpoint it belongs to — restoring it
        would mark chunks done that the restored model never trained on).
        Pending tasks serialize into todo: a lease held at snapshot time
        must be re-served after a restore."""
        with self._lock:
            return {"epoch": self.epoch,
                    "todo": [dataclasses.asdict(t) for t in self.todo],
                    "pending": [dataclasses.asdict(t)
                                for t, _ in self.pending.values()],
                    "done": [dataclasses.asdict(t) for t in self.done]}

    def load_state_dict(self, state: dict):
        """Restore queue state captured by :meth:`state_dict` (locked)."""
        with self._lock:
            self.epoch = state["epoch"]
            self.todo = [Task(**t) for t in
                         state["todo"] + state["pending"]]
            self.pending = {}
            self.done = [Task(**t) for t in state["done"]]
            self._next_id = max(
                [t.task_id for t in self.todo + self.done] + [-1]) + 1

    def _snapshot(self):
        if not self.snapshot_path:
            return
        state = {"epoch": self.epoch,
                 "todo": [dataclasses.asdict(t) for t in self.todo],
                 "pending": [dataclasses.asdict(t)
                             for t, _ in self.pending.values()],
                 "done": [dataclasses.asdict(t) for t in self.done]}
        with open(self.snapshot_path, "w") as f:
            json.dump(state, f)

    def restore_snapshot(self):
        if not self.snapshot_path:
            return
        with open(self.snapshot_path) as f:
            state = json.load(f)
        self.epoch = state["epoch"]
        self.todo = [Task(**t) for t in
                     state["todo"] + state["pending"]]
        self.done = [Task(**t) for t in state["done"]]


class MasterServer:
    """Serve a Master over TCP (go/master RPC server analog).

    Wire protocol: one JSON object per line, ``{"method": m, "params": {...}}``
    -> ``{"result": ...}`` or ``{"error": "..."}``.  Threaded: each trainer
    connection gets its own handler thread; Master methods are internally
    locked.
    """

    METHODS = ("get_task", "task_finished", "task_failed", "task_returned",
               "set_dataset", "set_dataset_if_empty", "stats", "ping",
               "request_save_model")

    def __init__(self, master: Master, host: str = "127.0.0.1",
                 port: int = 0):
        self.master = master
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    try:
                        req = json.loads(line)
                        resp = {"result": outer._dispatch(
                            req.get("method"), req.get("params") or {})}
                        payload = json.dumps(resp)
                    except Exception as e:  # noqa: BLE001 — report to client
                        # includes result-serialization failures (chunks
                        # must be JSON-encodable: paths/ids, not payloads)
                        payload = json.dumps(
                            {"error": f"{type(e).__name__}: {e}"})
                    self.wfile.write((payload + "\n").encode())
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def _dispatch(self, method, params):
        if method not in self.METHODS:
            raise ValueError(f"unknown method {method!r}")
        if method == "ping":
            return "pong"
        if method == "get_task":
            t = self.master.get_task()
            return dataclasses.asdict(t) if t is not None else None
        if method == "set_dataset":
            return self.master.set_dataset(params["chunks"])
        if method == "set_dataset_if_empty":
            return self.master.set_dataset_if_empty(params["chunks"])
        if method == "stats":
            return self.master.stats()
        if method == "request_save_model":
            return self.master.request_save_model(
                params["trainer_id"], params.get("block_dur_s", 60.0))
        return getattr(self.master, method)(params["task_id"])

    def start(self) -> "MasterServer":
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    @property
    def address(self):
        return f"{self.host}:{self.port}"


class MasterClient:
    """Trainer-side RPC stub with the Master's duck-typed API, so
    ``TaskQueueClient`` works unchanged against a remote master (the Go
    master_client / v2 master.client analog)."""

    def __init__(self, address: str, timeout_s: float = 30.0,
                 retries: int = 3, retry_wait_s: float = 0.5,
                 retry_policy: Optional[RetryPolicy] = None):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout_s
        # exponential backoff + deterministic jitter between reconnect
        # attempts (a flat retry_wait hammers a restarting master); the
        # default derives from the legacy knobs so existing callers keep
        # their first-retry latency.  An explicit policy owns BOTH the
        # delays and the attempt count.
        self._retry_policy = retry_policy or RetryPolicy(
            max_attempts=max(retries, 1), backoff_base_s=retry_wait_s,
            backoff_max_s=8.0, jitter=0.1, seed=0)
        self._retries = self._retry_policy.max_attempts
        self._sock = None
        self._file = None
        self._lock = threading.Lock()

    def _connect(self, timeout=None):
        self._sock = socket.create_connection(
            self._addr, timeout=self._timeout if timeout is None
            else timeout)
        self._file = self._sock.makefile("rwb")

    def _call(self, method, _retries=None, _timeout=None,
              _sock_deadline=None, **params):
        retries = self._retries if _retries is None else _retries
        with self._lock:
            # The socket deadline is mutated (and restored) only while the
            # lock is held, so a concurrent RPC can never observe the
            # shortened timeout mid-read.
            sock, old = self._sock, None
            if _sock_deadline is not None and sock is not None:
                try:               # bound reads on the live socket too
                    old = sock.gettimeout()
                    sock.settimeout(_sock_deadline)
                except OSError:
                    pass
            try:
                last = None
                for attempt in range(retries):
                    try:
                        if _fi.ENABLED:
                            action = _fi.check("master.call")
                            if action == "drop":
                                self.close()   # the wire really went away
                            if action is not None:
                                _fi.raise_for(action, "master.call")
                        if self._file is None:
                            self._connect(_timeout)
                        self._file.write((json.dumps(
                            {"method": method, "params": params}) +
                            "\n").encode())
                        self._file.flush()
                        line = self._file.readline()
                        if not line:
                            raise ConnectionError("master closed connection")
                        resp = json.loads(line)
                        if "error" in resp:
                            raise RuntimeError(f"master: {resp['error']}")
                        return resp["result"]
                    except (OSError, ConnectionError,
                            json.JSONDecodeError) as e:
                        last = e
                        self.close()
                        if attempt + 1 < retries:
                            d = self._retry_policy.delay(attempt)
                            from ..observability import (emit_event,
                                                         inc_counter)
                            inc_counter("fault/retries")
                            emit_event(
                                "fault", event="retry", site="master.call",
                                attempt=attempt + 1,
                                delay_s=round(d, 4),
                                error=f"{type(e).__name__}: {e}")
                            time.sleep(d)
                raise ConnectionError(
                    f"master at {self._addr} unreachable: {last}")
            finally:
                # restore the configured deadline on whatever socket is
                # live afterwards — the original, or a short-deadline
                # reconnect — so later RPCs don't inherit it
                if _sock_deadline is not None:
                    cur = self._sock
                    if cur is not None:
                        try:
                            cur.settimeout(
                                old if (cur is sock and old is not None)
                                else self._timeout)
                        except OSError:
                            pass

    # -- Master duck-type --------------------------------------------------
    def get_task(self) -> Optional[Task]:
        d = self._call("get_task")
        return Task(**d) if d is not None else None

    def task_finished(self, task_id: int):
        return self._call("task_finished", task_id=task_id)

    def task_failed(self, task_id: int):
        return self._call("task_failed", task_id=task_id)

    def task_returned(self, task_id: int):
        return self._call("task_returned", task_id=task_id)

    def task_returned_nowait(self, task_id: int):
        """Single-attempt, <=2 s best-effort ``task_returned`` for
        generator-close paths: the default retry loop (3 x 30 s connect
        timeout) can stall a ``cloud_reader`` close ~90 s when the
        master is dead, and the caller is about to discard the result
        anyway — the task's lease times out and requeues regardless."""
        return self._call("task_returned", _retries=1, _timeout=2.0,
                          _sock_deadline=2.0, task_id=task_id)

    def set_dataset(self, chunks: List):
        return self._call("set_dataset", chunks=chunks)

    def set_dataset_if_empty(self, chunks: List) -> bool:
        return self._call("set_dataset_if_empty", chunks=chunks)

    def stats(self) -> dict:
        return self._call("stats")

    def ping(self) -> str:
        return self._call("ping")

    def request_save_model(self, trainer_id: str,
                           block_dur_s: float = 60.0) -> bool:
        return self._call("request_save_model", trainer_id=trainer_id,
                          block_dur_s=block_dur_s)

    def close(self):
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._file = None


class TaskQueueClient:
    """Trainer-side helper (go/master client + v2 master.client analog):
    iterate data via master tasks with automatic finish/fail reporting."""

    def __init__(self, master: Master, chunk_reader: Callable):
        self.master = master
        self.chunk_reader = chunk_reader

    def reader(self):
        return task_loop_reader(self.master, self.chunk_reader,
                                swallow_failures=True)


def task_loop_reader(client, chunk_reader: Callable,
                     swallow_failures: bool = False):
    """The shared task-pull loop (go/master client semantics) used by
    both in-process ``TaskQueueClient`` and ``reader.creator.cloud_reader``:
    finish on success; FAIL (budget-burning) on real exceptions; RETURN
    without burning the budget on polite early-stop (GeneratorExit from
    ``firstn``/loop breaks — the task requeues immediately for peers).
    ``swallow_failures`` keeps iterating past bad chunks (the elastic
    in-process behavior) instead of re-raising."""

    def _r():
        from ..observability import inc_counter

        # ONE budget-free return per task (the documented exactly-once
        # contract): the first retryable failure hands the task back
        # without burning budget; any further failure of the same task
        # burns real failure budget (and drops it at failure_max) — a
        # chunk that fails every time can never ping-pong through todo
        # forever.  `fails` counts every retryable failure per task and
        # drives the escalating swallow-mode backoff.
        free_returns = {}
        fails = {}

        while True:
            task = client.get_task()
            if task is None:
                return
            try:
                for chunk in task.chunks:
                    yield from chunk_reader(chunk)
            except GeneratorExit:
                # best-effort: finalization must not raise or stall hard
                # if the master died (the task times out and requeues
                # anyway, at the cost of one budget tick).  Remote clients
                # take the single-attempt <=2 s path — the default retry
                # loop would hold the closing generator ~90 s.
                ret = getattr(client, "task_returned_nowait",
                              client.task_returned)
                try:
                    ret(task.task_id)
                    inc_counter("fault/tasks_returned")
                except Exception:
                    pass
                raise
            except Exception as e:
                n = free_returns.get(task.task_id, 0)
                nf = fails.get(task.task_id, 0)
                if classify(e) == "retryable":
                    fails[task.task_id] = nf + 1
                if classify(e) == "retryable" and n < 1:
                    # Transient failure mid-chunk: the work is NOT
                    # idempotent from here (records already yielded), so
                    # the task goes back to the master EXACTLY ONCE —
                    # budget-free — before anyone retries it; re-serving
                    # from the top is the retry.
                    free_returns[task.task_id] = n + 1
                    try:
                        client.task_returned(task.task_id)
                        inc_counter("fault/tasks_returned")
                    except Exception as re:  # noqa: BLE001
                        logger.warning(
                            "could not return task %s after transient "
                            "failure (%s); its lease will lapse",
                            task.task_id, re)
                    if swallow_failures:
                        time.sleep(0.05 * (2 ** min(nf, 4)))   # escalate
                        continue
                    raise
                client.task_failed(task.task_id)
                if swallow_failures:
                    if classify(e) == "retryable":
                        time.sleep(0.05 * (2 ** min(nf, 4)))
                    continue
                raise
            client.task_finished(task.task_id)

    return _r
