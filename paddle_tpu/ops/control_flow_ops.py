"""Control-flow op lowerings: while, conditional_block, tensor arrays,
IfElse split/merge, dynamic-RNN plumbing.

Reference: while_op.cc:35-102 and recurrent_op.cc:39-335 run a sub-block with
a nested Executor over StepScopes; conditional_block_op, split_lod_tensor_op/
merge_lod_tensor_op implement IfElse by *physically partitioning* the batch.

TPU-native redesign:
* ``while`` lowers to ``lax.while_loop`` interpreting the sub-block as the
  body — compiled control flow, zero host round-trips per iteration.
* Tensor arrays are fixed-capacity [T_max, ...] buffers updated with
  ``lax.dynamic_update_slice`` (static shapes; capacity from the time dim).
* IfElse keeps static shapes by computing both branches on the full batch and
  selecting by mask (split_lod_tensor -> mask pass-through, merge_lod_tensor
  -> where), instead of data-dependent batch partitioning.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


@register_op("while")
def _while(ctx, ins, attrs):
    """attrs: sub_block (int).  inputs: Condition ([1] bool var), X (loop
    vars read).  outputs: Out (parent-declared vars written by the body —
    the loop carry).  The body must recompute Condition."""
    sub_idx = attrs["sub_block"]
    cond_name = ctx.op.inputs["Condition"][0]
    carry_names = list(ctx.op.outputs["Out"])
    env = ctx.env
    init = {n: env.get(n) for n in carry_names}
    init_cond = env.get(cond_name).reshape(())

    def cond_fn(state):
        c, _ = state
        return c

    def body_fn(state):
        _, vals = state
        benv = ctx.child_env(sub_idx, env)
        # shadow carried vars with loop state (write-through targets parent,
        # so bind locally first)
        for n, v in vals.items():
            benv.local[n] = v
        ctx.interpret_block(sub_idx, benv)
        new_vals = {n: benv.get(n) for n in carry_names}
        new_cond = benv.get(cond_name).reshape(())
        return new_cond, new_vals

    _, final = lax.while_loop(cond_fn, body_fn, (init_cond, init))
    return {"Out": [final[n] for n in carry_names]}


@register_op("conditional_block")
def _conditional_block(ctx, ins, attrs):
    """Run sub-block iff Cond is true; else outputs keep current values.
    Outputs must already have values (initialize with fill_constant)."""
    sub_idx = attrs["sub_block"]
    cond = ins["Cond"][0].reshape(())
    out_names = list(ctx.op.outputs.get("Out", []))
    env = ctx.env
    current = {n: env.get(n) for n in out_names}

    def true_fn(vals):
        benv = ctx.child_env(sub_idx, env)
        ctx.interpret_block(sub_idx, benv)
        return {n: benv.get(n) for n in out_names}

    def false_fn(vals):
        return vals

    final = lax.cond(cond, true_fn, false_fn, current)
    return {"Out": [final[n] for n in out_names]}


@register_op("split_lod_tensor")
def _split_lod_tensor(ctx, ins, attrs):
    """IfElse entry: both branches get the full tensor; Mask rides along
    (static-shape deviation from split_lod_tensor_op.cc, documented above)."""
    x, mask = ins["X"][0], ins["Mask"][0]
    return {"OutTrue": x, "OutFalse": x}


@register_op("merge_lod_tensor")
def _merge_lod_tensor(ctx, ins, attrs):
    x_true, x_false, mask = ins["InTrue"][0], ins["InFalse"][0], ins["Mask"][0]
    m = mask.reshape((-1,) + (1,) * (x_true.ndim - 1)).astype(bool)
    return {"Out": jnp.where(m, x_true, x_false)}


# ---------------------------------------------------------------------------
# tensor arrays (lod_tensor_array, tensor_array_read_write_op)
# ---------------------------------------------------------------------------
@register_op("write_to_array")
def _write_to_array(ctx, ins, attrs):
    """array[i] = x.  The array buffer is a [cap, ...] tensor; created on
    first write with capacity attr ``capacity`` (default 128)."""
    x = ins["X"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    out_name = ctx.op.outputs["Out"][0]
    if ctx.env.has(out_name):
        buf = ctx.env.get(out_name)
    else:
        cap = int(attrs.get("capacity", 128))
        buf = jnp.zeros((cap,) + x.shape, x.dtype)
    buf = lax.dynamic_update_slice(buf, x[None], (i,) + (0,) * x.ndim)
    return {"Out": buf}


@register_op("read_from_array")
def _read_from_array(ctx, ins, attrs):
    buf = ins["X"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    return {"Out": lax.dynamic_index_in_dim(buf, i, axis=0, keepdims=False)}


@register_op("lod_array_length")
def _lod_array_length(ctx, ins, attrs):
    return {"Out": jnp.asarray(ins["X"][0].shape[0], jnp.int64)}


@register_op("lod_tensor_to_array")
def _lod_tensor_to_array(ctx, ins, attrs):
    """[B,T,...] -> [T,B,...] time-major buffer (the reference instead
    builds per-step shrinking batches via the rank table)."""
    x = ins["X"][0]
    return {"Out": jnp.swapaxes(x, 0, 1)}


@register_op("array_to_lod_tensor")
def _array_to_lod_tensor(ctx, ins, attrs):
    x = ins["X"][0]
    out = jnp.swapaxes(x, 0, 1)
    rt = ctx.op.inputs.get("RankTable")
    if rt:
        lens = ctx.get_len(rt[0])
        if lens is not None:
            ctx.set_len(ctx.op.outputs["Out"][0], lens)
    return {"Out": out}


@register_op("lod_rank_table")
def _lod_rank_table(ctx, ins, attrs):
    """lod_rank_table_op: descending-length order of sequences.  Returns the
    permutation as int32 [B]; lengths companion is forwarded."""
    x = ins["X"][0]
    name = ctx.op.inputs["X"][0]
    lens = ctx.get_len(name)
    if lens is None:
        lens = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    order = jnp.argsort(-lens)
    ctx.set_len(ctx.op.outputs["Out"][0], lens[order])
    return {"Out": order.astype(jnp.int32)}


@register_op("reorder_lod_tensor_by_rank")
def _reorder_by_rank(ctx, ins, attrs):
    x, rank = ins["X"][0], ins["RankTable"][0]
    out = jnp.take(x, rank.astype(jnp.int32), axis=0)
    lens = ctx.get_len(ctx.op.inputs["X"][0])
    if lens is not None:
        ctx.set_len(ctx.op.outputs["Out"][0],
                    jnp.take(lens, rank.astype(jnp.int32)))
    return {"Out": out}


@register_op("shrink_rnn_memory")
def _shrink_rnn_memory(ctx, ins, attrs):
    """shrink_rnn_memory_op: the reference shrinks the live batch as short
    sequences finish; with static shapes we freeze finished rows instead
    (mask applied by the RNN step), so this is identity."""
    return {"Out": ins["X"][0]}


@register_op("rnn_memory_helper")
def _rnn_memory_helper(ctx, ins, attrs):
    return {"Out": ins["X"][0]}


@register_op("rnn")
def _rnn(ctx, ins, attrs):
    """StaticRNN/DynamicRNN lowering: run the step sub-block under lax.scan.

    The reference RecurrentOp runs the sub-block once per step with a nested
    Executor and StepScopes (recurrent_op.cc:222-335); here the step block is
    traced ONCE and scanned — XLA pipelines the loop and the recurrence is
    differentiable (the reference needed a hand-written RecurrentGradOp).
    Finished sequences freeze their memories via the length mask.
    """
    sub_idx = attrs["sub_block"]
    step_in_names = attrs["step_inputs"]          # sub-block per-step vars
    mem_names = attrs["mem_step_names"]           # sub-block memory vars
    mem_update_names = attrs["mem_update_names"]  # vars holding new memory
    out_step_names = attrs["step_output_names"]
    seqs = ins.get("Inputs", [])                  # [B,T,...] each
    inits = ins.get("InitStates", [])
    env = ctx.env

    T = seqs[0].shape[1]
    B = seqs[0].shape[0]
    seq_parent_names = ctx.op.inputs.get("Inputs", [])
    lens = None
    for nm in seq_parent_names:
        lens = ctx.get_len(nm)
        if lens is not None:
            break
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    step_mask = (jnp.arange(T)[None, :] < lens[:, None]).astype(
        seqs[0].dtype).T                          # [T, B]
    xs = [jnp.swapaxes(s, 0, 1) for s in seqs]    # time-major
    # NESTED sequences: an input [B, S, T', ...] with an @LEN2 companion
    # [B, S] is a sequence OF sequences — each outer step's slice is itself
    # a padded sequence, so the inner lengths scan along and land in the
    # step env as the slice's @LEN (the LoD level-2 analog)
    nested_names = []
    nested_l2 = []                                       # [B, S] each
    nested_scan = []
    for step_nm, parent_nm in zip(step_in_names, seq_parent_names):
        l2 = ctx.get_len2(parent_nm)
        if l2 is not None:
            nested_names.append(step_nm)
            nested_l2.append(l2)
            nested_scan.append(jnp.swapaxes(l2, 0, 1))   # [S, B]

    def step(carry, inp):
        mems = carry
        m_t = inp[0]
        n_seq = len(step_in_names)
        slices = inp[1:1 + n_seq]
        l2_slices = inp[1 + n_seq:]
        benv = ctx.child_env(sub_idx, env)
        for nm, v in zip(step_in_names, slices):
            benv.local[nm] = v
        for nm, l2 in zip(nested_names, l2_slices):
            benv.local[nm + "@LEN"] = l2
        for nm, v in zip(mem_names, mems):
            benv.local[nm] = v
        ctx.interpret_block(sub_idx, benv)
        new_mems = tuple(
            jnp.where(m_t.reshape((B,) + (1,) * (old.ndim - 1)) > 0,
                      benv.get(un), old) if un else old
            for un, old in zip(mem_update_names, mems))
        outs = tuple(benv.get(nm) * m_t.reshape((B,) + (1,) * (benv.get(nm).ndim - 1))
                     for nm in out_step_names)
        return new_mems, outs

    init_mems = tuple(inits)
    _, outs = lax.scan(step, init_mems,
                       tuple([step_mask] + xs + nested_scan))
    results = [jnp.swapaxes(o, 0, 1) for o in outs]
    sub_vars = ctx.block(sub_idx).vars
    for nm, step_nm in zip(ctx.op.outputs.get("Outputs", []),
                           out_step_names):
        ctx.set_len(nm, lens)
        # a stacked output is a sequence OF sequences only when the step
        # output was itself a sequence (e.g. the inner group's output);
        # per-step vectors stack to [B, S, H] and must NOT carry @LEN2
        sv = sub_vars.get(step_nm)
        if nested_l2 and sv is not None and sv.lod_level >= 1:
            ctx.set_len2(nm, nested_l2[0])
    return {"Outputs": results}


@register_op("print")
def _print(ctx, ins, attrs):
    x = ins.get("In", ins.get("X", [None]))[0]
    msg = attrs.get("message", "")
    jax.debug.print(msg + " {x}", x=x)
    return {"Out": x} if ctx.op.outputs.get("Out") else {}


@register_op("assert")
def _assert(ctx, ins, attrs):
    return {}


# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer).  The structured control
# flow ops (while/conditional_block/rnn) are allowlisted — their outputs are
# whatever the sub-block binds — but the tensor-array plumbing around them
# is statically knowable.
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import (VarInfo, first, no_outputs,  # noqa: E402
                                    passthrough, same_as)
from ..core.registry import register_shape_fn  # noqa: E402

register_shape_fn("shrink_rnn_memory", "rnn_memory_helper")(same_as("X"))
register_shape_fn("split_lod_tensor")(
    same_as("X", out="OutTrue", also=("OutFalse",)))
register_shape_fn("merge_lod_tensor")(same_as("InTrue"))
register_shape_fn("reorder_lod_tensor_by_rank")(same_as("X"))
register_shape_fn("print")(passthrough("In", "X"))
register_shape_fn("assert")(no_outputs())


@register_shape_fn("read_from_array")
def _read_from_array_shape(op, ins, attrs):
    buf = first(ins, "X")
    if buf.shape is None:
        return {"Out": buf}
    return {"Out": buf.with_shape(buf.shape[1:])}


@register_shape_fn("lod_array_length")
def _lod_array_length_shape(op, ins, attrs):
    return {"Out": VarInfo((), "int64")}


@register_shape_fn("lod_tensor_to_array", "array_to_lod_tensor")
def _swap01_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None or len(x.shape) < 2:
        return {"Out": VarInfo(None, x.dtype)}
    return {"Out": x.with_shape((x.shape[1], x.shape[0]) + x.shape[2:])}


@register_shape_fn("lod_rank_table")
def _lod_rank_table_shape(op, ins, attrs):
    x = first(ins, "X")
    b = x.shape[0] if x.shape is not None else -1
    return {"Out": VarInfo((b,), "int32")}


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop): memory helpers are
# shape-preserving; print/assert are transparent; the tensor-array and
# lod-rank machinery is data-dependent (deliberately unregistered — a
# sharded value reaching it is a real planner blind spot worth a PT042).
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import shard_noop, shard_same_as  # noqa: E402
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("shrink_rnn_memory", "rnn_memory_helper")(
    shard_same_as("X"))
register_shard_fn("print", "assert")(shard_noop())
