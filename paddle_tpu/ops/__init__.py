"""Op library: importing this package registers every lowering.

The analog of the reference's paddle/operators/ (342 files, ~170 ops —
SURVEY §2.2), with each op implemented as a JAX lowering rather than paired
CPU/CUDA kernels.  Grad ops do not exist: jax.vjp differentiates lowerings.
"""

from ..core.registry import register_op, registered_ops

from . import math_ops        # noqa: F401
from . import activation_ops  # noqa: F401
from . import tensor_ops      # noqa: F401
from . import nn_ops          # noqa: F401
from . import loss_ops        # noqa: F401
from . import metric_ops      # noqa: F401
from . import optimizer_ops   # noqa: F401
from . import sequence_ops    # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import embedding_ops   # noqa: F401
from . import io_ops          # noqa: F401
from . import detection_ops   # noqa: F401
from . import crf_ops         # noqa: F401
from . import generation_ops  # noqa: F401
from . import pallas_kernels  # noqa: F401
from . import moe_ops         # noqa: F401


@register_op("backward")
def _backward_stub(ctx, ins, attrs):
    raise RuntimeError(
        "the `backward` pseudo-op must appear at the top level of the global "
        "block; it is lowered specially by the Executor "
        "(core/executor.py interpret_block_with_backward)")
