"""Hand-written Pallas TPU kernels for the 1x1-conv hot path.

RESULTS.md's corrected roofline (round 5) identifies XLA's conv emitters
as the binding constraint on ResNet training: the 1x1-conv/gradient
shapes run at ~51 TFLOP/s against a 57-115 TFLOP/s bandwidth-corrected
ceiling.  The reference framework answered the same problem by hand-
writing its hot kernels (paddle/cuda/src/hl_cuda_matrix.cu); the
TPU-native analog is this module: an im2col-free dot-based kernel pair
for 1x1 convolutions.

A 1x1 conv IS a matmul over the pixel dimension — x [N,C,H,W] viewed as
[P, C] (P = N*H*W) against the filter [M, C] — so all three passes
(forward, dgrad, wgrad) are instances of ONE blocked Pallas matmul with
transpose options:

    forward:  out[P, M] = x[P, C]    @ w[M, C]^T
    dgrad:    dx[P, C]  = gout[P, M] @ w[M, C]
    wgrad:    dw[M, C]  = gout[P, M]^T @ x[P, C]     (K = P, streamed)

The wgrad is the worst measured shape (deep-K reduction over every
pixel); its kernel streams P through VMEM in ``block_k`` slabs with an
f32 accumulator resident in VMEM — the flash-kernel pattern
(``pallas_kernels._flash_kernel``) applied to convolution.  Fused
epilogues ride the streams for free (the data is already in VMEM):

* forward can emit per-channel sum/sum-of-squares partials (the
  batch-norm statistics reduction — saves BN's separate HBM pass over
  the conv output);
* wgrad can emit the per-channel gout sum (the bias/BN-beta gradient).

``pallas_matmul`` carries a custom VJP whose backward runs the same
kernels, so ``conv2d_1x1`` is fully differentiable end-to-end and the
executor's autodiff pass routes conv gradients through the hand-written
path automatically.  Everything here is opt-in behind the
``conv1x1_pallas`` flag / ``Executor(conv1x1_pallas=True)`` — see
``ops/nn_ops._conv2d`` for the routing and ``benchmark/conv_kernel.py``
for the per-op A/B against XLA's emitters.

On non-TPU backends the kernels run only under ``interpret=True`` (the
CPU tests); eligibility gating lives in ``conv1x1_eligible``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
    # renamed TPUCompilerParams -> CompilerParams across jax versions
    # (this container's jax 0.4.37 has only the old name); resolve at
    # import so the drift fails loudly here, not at first on-TPU trace
    _CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

__all__ = ["pallas_matmul", "conv2d_1x1", "conv2d_1x1_with_bn_stats",
           "conv2d_1x1_grad_fused", "conv1x1_eligible"]


# ---------------------------------------------------------------------------
# generic blocked matmul kernel (the one kernel all three conv passes use)
# ---------------------------------------------------------------------------
def _mm_kernel(a_ref, b_ref, *refs, nk, ta, tb, out_stats, a_colsum):
    """Grid (m_blocks, n_blocks, k_blocks), k innermost/sequential: the
    f32 accumulator lives in VMEM scratch across the K stream; operands
    feed the MXU in their native dtype (bf16 in, f32 accumulate).

    ``out_stats``: also emit per-N-column sum / sum-of-squares of the
    finished output block (per-M-block partials) — the fused BN-
    statistics epilogue for the forward conv.
    ``a_colsum``: also emit the column sums of logical-A (requires
    ``ta``; K-streamed in scratch) — the fused bias/BN-beta gradient
    epilogue for the wgrad, where A is gout.
    """
    outs = list(refs)
    o_ref = outs.pop(0)
    sum_ref = outs.pop(0) if out_stats else None
    sq_ref = outs.pop(0) if out_stats else None
    csum_ref = outs.pop(0) if a_colsum else None
    acc_ref = outs.pop(0)
    csum_acc = outs.pop(0) if a_colsum else None

    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    a = a_ref[...]
    b = b_ref[...]
    ca = 0 if ta else 1            # storage axis holding K
    cb = 1 if tb else 0
    acc_ref[...] += lax.dot_general(
        a, b, (((ca,), (cb,)), ((), ())),
        preferred_element_type=jnp.float32)

    if a_colsum:
        # gout column sums: accumulate only on the first N sweep (every j
        # sees the same A blocks; one sweep suffices)
        @pl.when(jnp.logical_and(j == 0, kb == 0))
        def _cs_init():
            csum_acc[...] = jnp.zeros_like(csum_acc)

        @pl.when(j == 0)
        def _cs_acc():
            csum_acc[...] += jnp.sum(a.astype(jnp.float32), axis=0,
                                     keepdims=True)

    @pl.when(kb == nk - 1)
    def _write():
        out = acc_ref[...]
        o_ref[...] = out.astype(o_ref.dtype)
        if out_stats:
            sum_ref[...] = jnp.sum(out, axis=0, keepdims=True)
            sq_ref[...] = jnp.sum(out * out, axis=0, keepdims=True)
        if a_colsum:
            @pl.when(j == 0)
            def _cs_write():
                csum_ref[...] = csum_acc[...]


def _pick_block(dim: int, target: int):
    """Largest multiple of 128 <= target that divides ``dim`` (None when
    dim itself is not 128-divisible — the caller gates on that)."""
    b = min(target, dim)
    b -= b % 128
    while b >= 128:
        if dim % b == 0:
            return b
        b -= 128
    return None


def _mm(a, b, ta, tb, block_m, block_n, block_k, interpret,
        out_stats=False, a_colsum=False, out_dtype=None):
    M, K = (a.shape[1], a.shape[0]) if ta else (a.shape[0], a.shape[1])
    N = b.shape[0] if tb else b.shape[1]
    bm, bn, bk = (_pick_block(M, block_m), _pick_block(N, block_n),
                  _pick_block(K, block_k))
    if bm is None or bn is None or bk is None:
        raise ValueError(
            f"pallas_matmul needs 128-divisible dims, got M={M} N={N} K={K}")
    nm, nn, nk = M // bm, N // bn, K // bk
    out_dtype = out_dtype or a.dtype

    a_spec = pl.BlockSpec((bk, bm), lambda i, j, k: (k, i)) if ta \
        else pl.BlockSpec((bm, bk), lambda i, j, k: (i, k))
    b_spec = pl.BlockSpec((bn, bk), lambda i, j, k: (j, k)) if tb \
        else pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
    out_shape = [jax.ShapeDtypeStruct((M, N), out_dtype)]
    out_specs = [pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))]
    if out_stats:
        # per-M-block partials of the per-column output sums; the caller
        # finishes the tiny [nm, N] reduction (BN statistics)
        out_shape += [jax.ShapeDtypeStruct((nm, N), jnp.float32)] * 2
        out_specs += [pl.BlockSpec((1, bn), lambda i, j, k: (i, j))] * 2
    if a_colsum:
        assert ta, "a_colsum epilogue is the wgrad (gout^T) path"
        out_shape.append(jax.ShapeDtypeStruct((1, M), jnp.float32))
        out_specs.append(pl.BlockSpec((1, bm), lambda i, j, k: (0, i)))
    scratch = [pltpu.VMEM((bm, bn), jnp.float32)]
    if a_colsum:
        scratch.append(pltpu.VMEM((1, bm), jnp.float32))

    kwargs = {}
    if not interpret:
        # The a_colsum epilogue writes csum_ref (mapped to block (0, i)
        # for EVERY j) only under pl.when(j == 0): if Mosaic partitioned a
        # "parallel" j across megacore, a core whose j-range excludes 0
        # would copy its uninitialized VMEM output block over the result.
        # Keep j sequential whenever the epilogue is on.
        nsem = "arbitrary" if a_colsum else "parallel"
        kwargs["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", nsem, "arbitrary"))
    res = pl.pallas_call(
        functools.partial(_mm_kernel, nk=nk, ta=ta, tb=tb,
                          out_stats=out_stats, a_colsum=a_colsum),
        out_shape=out_shape,
        grid=(nm, nn, nk),
        in_specs=[a_spec, b_spec],
        out_specs=out_specs,
        scratch_shapes=scratch,
        interpret=interpret,
        **kwargs,
    )(a, b)
    return res if (out_stats or a_colsum) else res[0]


# Autotuner knob declaration (paddle_tpu.tuning), next to the kernel it
# tunes: the blocked-matmul tile shape every conv1x1 pass instantiates.
# Search needs the chip (benchmark/conv_kernel.py is the measurement
# driver); until an on-chip run commits a winner the 512/512/1024
# defaults below stand, per the pre-registered rule.
from ..core.registry import register_tunable  # noqa: E402

register_tunable(
    "pallas/conv1x1_blocks", side="device",
    space={"block_m": (256, 512, 1024), "block_n": (256, 512, 1024),
           "block_k": (512, 1024, 2048)},
    default={"block_m": 512, "block_n": 512, "block_k": 1024},
    description="blocked-matmul tile shape for the Pallas 1x1-conv "
                "kernel family (fwd/dgrad/K-streaming wgrad share it).",
    pending_hardware=True,
    decision_rule="adopt a non-default tile only when the on-chip "
                  "conv_kernel A/B shows >= 1.10x geomean over the "
                  "512/512/1024 default across the ResNet-50 eligible "
                  "shapes, with no per-shape regression > 5%")


# ---------------------------------------------------------------------------
# differentiable matmul: backward runs the same kernels (dgrad/wgrad)
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def pallas_matmul(a, b, trans_a=False, trans_b=False, block_m=512,
                  block_n=512, block_k=1024, interpret=False):
    """O = A_logical @ B_logical with A stored transposed when
    ``trans_a`` (likewise B).  Differentiable: the VJP lowers da/db to
    the same blocked kernel, so the wgrad (db with K = the big pixel
    dimension) is the hand-written K-streaming gradient kernel."""
    return _mm(a, b, trans_a, trans_b, block_m, block_n, block_k, interpret)


def _pm_fwd(a, b, trans_a, trans_b, block_m, block_n, block_k, interpret):
    return _mm(a, b, trans_a, trans_b, block_m, block_n, block_k,
               interpret), (a, b)


def _pm_bwd(trans_a, trans_b, block_m, block_n, block_k, interpret, res, g):
    a, b = res
    ta, tb = trans_a, trans_b
    if not ta:      # da_storage [M, K] = g @ B_logical^T
        da = _mm(g, b, False, not tb, block_m, block_n, block_k, interpret)
    else:           # da_storage [K, M] = B_logical @ g^T
        da = _mm(b, g, tb, True, block_m, block_n, block_k, interpret)
    if not tb:      # db_storage [K, N] = A_logical^T @ g
        db = _mm(a, g, not ta, False, block_m, block_n, block_k, interpret)
    else:           # db_storage [N, K] = g^T @ A_logical  (the deep-K wgrad)
        db = _mm(g, a, True, ta, block_m, block_n, block_k, interpret)
    return da.astype(a.dtype), db.astype(b.dtype)


pallas_matmul.defvjp(_pm_fwd, _pm_bwd)


# ---------------------------------------------------------------------------
# 1x1 convolution on the matmul view
# ---------------------------------------------------------------------------
def _to_pixel_major(x):
    """[N, C, H, W] -> [N*H*W, C] (the im2col of a 1x1 filter is a
    reshape)."""
    N, C, H, W = x.shape
    return jnp.transpose(x.reshape(N, C, H * W), (0, 2, 1)).reshape(-1, C), \
        (N, H, W)


def _from_pixel_major(om, dims, M):
    N, H, W = dims
    return jnp.transpose(om.reshape(N, H * W, M), (0, 2, 1)) \
        .reshape(N, M, H, W)


def conv2d_1x1(x, w, strides=(1, 1), block_m=512, block_n=512,
               block_k=1024, interpret=False):
    """NCHW 1x1 convolution (pad 0, dil 1, groups 1) through the Pallas
    dot kernel; fully differentiable (strided input gradients scatter
    through the slice like any jnp op)."""
    sh, sw = int(strides[0]), int(strides[1])
    if (sh, sw) != (1, 1):
        x = x[:, :, ::sh, ::sw]
    xm, dims = _to_pixel_major(x)
    M = w.shape[0]
    wm = w.reshape(M, -1)
    om = pallas_matmul(xm, wm, False, True, block_m, block_n, block_k,
                       interpret)
    return _from_pixel_major(om, dims, M)


def conv2d_1x1_with_bn_stats(x, w, strides=(1, 1), block_m=512,
                             block_n=512, block_k=1024, interpret=False):
    """Forward 1x1 conv with the fused BN-statistics epilogue: returns
    (out [N,M,H,W], csum [M], csumsq [M]) where csum/csumsq are the
    per-out-channel sum and sum-of-squares over N,H,W — computed from
    the output blocks while they are still in VMEM, saving batch-norm's
    separate reduction pass over the conv output in HBM."""
    sh, sw = int(strides[0]), int(strides[1])
    if (sh, sw) != (1, 1):
        x = x[:, :, ::sh, ::sw]
    xm, dims = _to_pixel_major(x)
    M = w.shape[0]
    wm = w.reshape(M, -1)
    om, psum, psq = _mm(xm, wm, False, True, block_m, block_n, block_k,
                        interpret, out_stats=True)
    return (_from_pixel_major(om, dims, M),
            jnp.sum(psum, axis=0), jnp.sum(psq, axis=0))


def conv2d_1x1_grad_fused(x, w, gout, strides=(1, 1), block_m=512,
                          block_n=512, block_k=1024, interpret=False):
    """The hand-written 1x1-conv gradient pass: (dx, dw, dsum) from one
    dgrad kernel and one K-streaming wgrad kernel whose epilogue fuses
    dsum = sum_{N,H,W} gout (the bias / BN-beta gradient) into the gout
    stream.  ``gout`` is [N, M, OH, OW] in the conv's output geometry."""
    sh, sw = int(strides[0]), int(strides[1])
    xs = x[:, :, ::sh, ::sw] if (sh, sw) != (1, 1) else x
    xm, dims = _to_pixel_major(xs)
    gm, _ = _to_pixel_major(gout)
    M, C = w.shape[0], w.shape[1]
    wm = w.reshape(M, C)
    # dgrad: dx [P, C] = gout [P, M] @ w [M, C]
    dxm = _mm(gm, wm, False, False, block_m, block_n, block_k, interpret)
    dx = _from_pixel_major(dxm, dims, C)
    if (sh, sw) != (1, 1):
        dx = jnp.zeros(x.shape, x.dtype).at[:, :, ::sh, ::sw].set(dx)
    # wgrad (+ fused dsum): dw [M, C] = gout^T @ x, K = P streamed
    dw, dsum = _mm(gm, xm, True, False, block_m, block_n, block_k,
                   interpret, a_colsum=True)
    return dx, dw.reshape(w.shape).astype(w.dtype), dsum.reshape(M)


def conv1x1_eligible(x_shape, w_shape, strides, pads, dils, groups) -> bool:
    """Static routing gate for ``ops.nn_ops._conv2d``: the kernel covers
    1x1 / groups-1 / pad-0 / dil-1 convs whose matmul-view dims are
    128-divisible (MXU lane tiles; ResNet's 1x1 shapes qualify from the
    256-channel stages up — the 64-channel stage-1 blocks stay on XLA)."""
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    if tuple(w_shape[2:]) != (1, 1) or int(groups or 1) != 1:
        return False
    if tuple(pads) != (0, 0) or tuple(dils) != (1, 1):
        return False
    N, C, H, W = x_shape
    M = w_shape[0]
    sh, sw = int(strides[0]), int(strides[1])
    P = N * ((H - 1) // sh + 1) * ((W - 1) // sw + 1)
    return C % 128 == 0 and M % 128 == 0 and P % 128 == 0
