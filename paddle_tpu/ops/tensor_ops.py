"""Tensor creation / data-movement op lowerings.

Reference category (SURVEY §2.2 Data/layout + I/O): reshape, transpose,
concat, split, pad, crop, expand, gather/scatter, multiplex, top_k,
fill_constant(_batch_size_like), fill_zeros_like, gaussian_random,
uniform_random, assign, one_hot, shape.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.types import convert_dtype


@register_op("feed", "fetch")
def _feed_fetch(ctx, ins, attrs):
    """Kept for program parity (feed_op.cc/fetch_op.cc); the executor feeds
    and fetches by name directly, so these are identity/no-ops."""
    if "X" in ins and ins["X"]:
        return {"Out": ins["X"][0]}
    return {}


@register_op("assign")
def _assign(ctx, ins, attrs):
    return {"Out": ins["X"][0]}


@register_op("shape")
def _shape(ctx, ins, attrs):
    return {"Out": jnp.asarray(ins["X"][0].shape, dtype=jnp.int64)}


@register_op("fill_constant")
def _fill_constant(ctx, ins, attrs):
    dt = convert_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs.get("shape", []))
    return {"Out": jnp.full(shape, attrs.get("value", 0.0), dtype=dt)}


@register_op("fill_constant_batch_size_like")
def _fill_cbsl(ctx, ins, attrs):
    """Shape copied from Input except the batch dim (fill_constant_batch_
    size_like_op.cc) — used to seed decoder states."""
    ref = ins["Input"][0]
    dt = convert_dtype(attrs.get("dtype", "float32"))
    shape = list(attrs.get("shape", []))
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    return {"Out": jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=dt)}


@register_op("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    return {"Out": jnp.zeros_like(ins["X"][0])}


@register_op("fill_any_like")
def _fill_any_like(ctx, ins, attrs):
    return {"Out": jnp.full_like(ins["X"][0], attrs.get("value", 0.0))}


@register_op("gaussian_random")
def _gaussian_random(ctx, ins, attrs):
    dt = convert_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs.get("shape", []))
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    return {"Out": mean + std * jax.random.normal(ctx.rng(), shape, dtype=dt)}


@register_op("uniform_random")
def _uniform_random(ctx, ins, attrs):
    dt = convert_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs.get("shape", []))
    return {"Out": jax.random.uniform(ctx.rng(), shape, dtype=dt,
                                      minval=attrs.get("min", -1.0),
                                      maxval=attrs.get("max", 1.0))}


@register_op("truncated_gaussian_random")
def _truncated_gaussian_random(ctx, ins, attrs):
    dt = convert_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs.get("shape", []))
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    return {"Out": mean + std * jax.random.truncated_normal(
        ctx.rng(), -2.0, 2.0, shape, dtype=dt)}


@register_op("assign_value")
def _assign_value(ctx, ins, attrs):
    vals = attrs["values"]
    dt = convert_dtype(attrs.get("dtype", "float32"))
    arr = jnp.asarray(vals, dtype=dt)
    if "shape" in attrs and attrs["shape"]:
        arr = arr.reshape(tuple(attrs["shape"]))
    return {"Out": arr}


@register_op("reshape")
def _reshape(ctx, ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    # fluid: 0 means copy input dim, -1 infers
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return {"Out": x.reshape(tuple(shape))}


@register_op("squeeze")
def _squeeze(ctx, ins, attrs):
    axes = attrs.get("axes", None)
    return {"Out": jnp.squeeze(ins["X"][0],
                               axis=tuple(axes) if axes else None)}


@register_op("unsqueeze")
def _unsqueeze(ctx, ins, attrs):
    return {"Out": jnp.expand_dims(ins["X"][0], tuple(attrs["axes"]))}


@register_op("transpose")
def _transpose(ctx, ins, attrs):
    return {"Out": jnp.transpose(ins["X"][0], tuple(attrs["axis"]))}


@register_op("concat")
def _concat(ctx, ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))}


@register_op("split")
def _split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    sections = attrs.get("sections")
    if sections:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, attrs["num"], axis=axis)
    return {"Out": list(parts)}


@register_op("pad")
def _pad(ctx, ins, attrs):
    """pad_op: paddings = [before0, after0, before1, after1, ...]"""
    x = ins["X"][0]
    p = attrs["paddings"]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))}


@register_op("crop")
def _crop(ctx, ins, attrs):
    x = ins["X"][0]
    offsets = attrs["offsets"]
    shape = attrs["shape"]
    # a negative size is a symbolic dim (e.g. batch -1): keep to the end
    slices = tuple(slice(o, o + s if s >= 0 else None)
                   for o, s in zip(offsets, shape))
    return {"Out": x[slices]}


@register_op("expand")
def _expand(ctx, ins, attrs):
    """expand_op: tile each dim by expand_times."""
    return {"Out": jnp.tile(ins["X"][0], tuple(attrs["expand_times"]))}


@register_op("tile")
def _tile(ctx, ins, attrs):
    return {"Out": jnp.tile(ins["X"][0], tuple(attrs["repeat_times"]))}


@register_op("slice")
def _slice(ctx, ins, attrs):
    # fluid's slice_op names its input slot "Input"; accept both spellings
    x = ins.get("Input", ins.get("X"))[0]
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    sl = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        sl[ax] = slice(st, en)
    return {"Out": x[tuple(sl)]}


@register_op("gather")
def _gather(ctx, ins, attrs):
    """gather_op: rows of X by Index (gather.h)."""
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": jnp.take(x, idx.astype(jnp.int32),
                            axis=attrs.get("axis", 0))}


@register_op("scatter")
def _scatter(ctx, ins, attrs):
    """scatter_op: write Updates rows into X at Ids (scatter.h).
    overwrite=False accumulates (the SelectedRows-merge behavior)."""
    x, ids, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    ids = ids.astype(jnp.int32).reshape(-1)
    if attrs.get("overwrite", True):
        return {"Out": x.at[ids].set(upd)}
    return {"Out": x.at[ids].add(upd)}


@register_op("multiplex")
def _multiplex(ctx, ins, attrs):
    """multiplex_op: per-row select among candidate tensors by Ids."""
    ids = ins["Ids"][0].astype(jnp.int32).reshape(-1)
    stack = jnp.stack(ins["X"], axis=0)  # [K, N, ...]
    return {"Out": stack[ids, jnp.arange(stack.shape[1])]}


@register_op("top_k")
def _top_k(ctx, ins, attrs):
    vals, idx = jax.lax.top_k(ins["X"][0], attrs["k"])
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op("sampling_id")
def _sampling_id(ctx, ins, attrs):
    """sampling_id_op (SamplingIdLayer.cpp): sample one id per row from
    the row's probability distribution; per-step PRNG key from ctx."""
    x = ins["X"][0]                  # [B, V] probabilities
    logp = jnp.log(jnp.clip(x.astype(jnp.float32), 1e-20, None))
    ids = jax.random.categorical(ctx.rng(), logp, axis=-1)
    return {"Out": ids.astype(jnp.int64)}


@register_op("argmax", "arg_max", "max_ids")
def _argmax(ctx, ins, attrs):
    return {"Out": jnp.argmax(ins["X"][0], axis=attrs.get("axis", -1))
            .astype(jnp.int64)}


@register_op("argsort")
def _argsort(ctx, ins, attrs):
    axis = attrs.get("axis", -1)
    x = ins["X"][0]
    idx = jnp.argsort(x, axis=axis, descending=attrs.get("descending", False))
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(jnp.int64)}


@register_op("one_hot")
def _one_hot(ctx, ins, attrs):
    x = ins["X"][0].astype(jnp.int32)
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x.squeeze(-1)
    return {"Out": jax.nn.one_hot(x, attrs["depth"], dtype=jnp.float32)}


@register_op("range")
def _range(ctx, ins, attrs):
    return {"Out": jnp.arange(attrs["start"], attrs["end"],
                              attrs.get("step", 1),
                              dtype=convert_dtype(attrs.get("dtype", "int64")))}


@register_op("flatten")
def _flatten(ctx, ins, attrs):
    x = ins["X"][0]
    ax = attrs.get("axis", 1)
    lead = 1
    for s in x.shape[:ax]:
        lead *= s
    return {"Out": x.reshape((lead, -1))}


@register_op("stack")
def _stack(ctx, ins, attrs):
    return {"Out": jnp.stack(ins["X"], axis=attrs.get("axis", 0))}


@register_op("unstack")
def _unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    return {"Y": [jnp.squeeze(p, axis)
                  for p in jnp.split(x, x.shape[axis], axis=axis)]}


@register_op("where", "select")
def _where(ctx, ins, attrs):
    return {"Out": jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])}


@register_op("is_empty")
def _is_empty(ctx, ins, attrs):
    """is_empty_op.cc — static under XLA (shapes are compile-time)."""
    return {"Out": jnp.asarray(ins["X"][0].size == 0)}


@register_op("shuffle")
def _shuffle(ctx, ins, attrs):
    x = ins["X"][0]
    perm = jax.random.permutation(ctx.rng(), x.shape[0])
    return {"Out": x[perm]}


@register_op("reverse")
def _reverse(ctx, ins, attrs):
    axes = attrs.get("axis", [0])
    if not isinstance(axes, (list, tuple)):
        axes = [axes]
    return {"Out": jnp.flip(ins["X"][0], axis=tuple(axes))}


# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer) — reshape/concat/split etc.
# InferShape analogs of the reference's data-movement ops.
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import (ShapeError, VarInfo,  # noqa: E402
                                    dim_ok, filled_from_attrs, first,
                                    numpy_broadcast, passthrough,
                                    prod_dims, same_as, shapes_compatible,
                                    squeeze_ids, unify_dim)
from ..core.registry import register_shape_fn  # noqa: E402

register_shape_fn("feed", "fetch")(passthrough("X"))
register_shape_fn("assign", "fill_zeros_like", "fill_any_like", "shuffle",
                  "reverse")(same_as("X"))
register_shape_fn("scatter")(same_as("X"))
register_shape_fn("fill_constant", "gaussian_random", "uniform_random",
                  "truncated_gaussian_random")(filled_from_attrs())
@register_shape_fn("where", "select")
def _where_shape(op, ins, attrs):
    # jnp.where broadcasts all three operands
    cond, x, y = first(ins, "Condition"), first(ins, "X"), first(ins, "Y")
    if x.shape is None or y.shape is None or cond.shape is None:
        return {"Out": VarInfo(None, x.dtype)}
    shape = numpy_broadcast(numpy_broadcast(cond.shape, x.shape,
                                            "where Condition/X"),
                            y.shape, "where X/Y")
    return {"Out": VarInfo(shape, x.dtype)}


@register_shape_fn("shape")
def _shape_shape(op, ins, attrs):
    x = first(ins, "X")
    nd = -1 if x.shape is None else len(x.shape)
    return {"Out": VarInfo((nd,), "int64")}


@register_shape_fn("fill_constant_batch_size_like")
def _fill_cbsl_shape(op, ins, attrs):
    ref = first(ins, "Input")
    shape = list(attrs.get("shape", []))
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    if not shape:
        return {"Out": VarInfo(None, attrs.get("dtype", "float32"))}
    if ref.shape is not None:
        if in_idx >= len(ref.shape) or out_idx >= len(shape):
            raise ShapeError(
                f"fill_constant_batch_size_like: dim idx ({in_idx}, "
                f"{out_idx}) out of range for {list(ref.shape)} -> {shape}")
        shape[out_idx] = ref.shape[in_idx]
    else:
        shape[out_idx] = -1
    return {"Out": VarInfo(shape, attrs.get("dtype", "float32"))}


@register_shape_fn("assign_value")
def _assign_value_shape(op, ins, attrs):
    import numpy as _np
    dt = attrs.get("dtype", "float32")
    if attrs.get("shape"):
        return {"Out": VarInfo(tuple(attrs["shape"]), dt)}
    vals = attrs.get("values")
    if vals is None:
        return {"Out": VarInfo(None, dt)}
    return {"Out": VarInfo(_np.shape(vals), dt)}


@register_shape_fn("reshape")
def _reshape_shape(op, ins, attrs):
    x = first(ins, "X")
    shape = list(attrs.get("shape", []))
    if x.shape is None or not shape:
        return {"Out": VarInfo(None, x.dtype)}
    for i, s in enumerate(shape):
        if s == 0:
            if i >= len(x.shape):
                raise ShapeError(
                    f"reshape: dim {i} copies input dim but input rank is "
                    f"{len(x.shape)}")
            shape[i] = x.shape[i]
    neg = [i for i, s in enumerate(shape) if s == -1]
    if len(neg) > 1:
        raise ShapeError(f"reshape: more than one -1 in {shape}")
    total = prod_dims(x.shape)
    known = prod_dims([s for i, s in enumerate(shape) if i not in neg])
    if total >= 0 and known >= 0:
        if neg:
            if known == 0 or total % known:
                raise ShapeError(
                    f"reshape: cannot infer -1: {list(x.shape)} "
                    f"({total} elems) -> {shape}")
            shape[neg[0]] = total // known
        elif known != total:
            raise ShapeError(
                f"reshape: element count mismatch: {list(x.shape)} "
                f"({total}) -> {shape} ({known})")
    elif neg:
        shape[neg[0]] = -1
    return {"Out": VarInfo(shape, x.dtype)}


@register_shape_fn("squeeze")
def _squeeze_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    axes = attrs.get("axes", None)
    nd = len(x.shape)
    if axes:
        axes = {a % nd for a in axes}
        for a in axes:
            if x.shape[a] not in (-1, 1):
                raise ShapeError(
                    f"squeeze: axis {a} has size {x.shape[a]} != 1 in "
                    f"{list(x.shape)}")
        shape = tuple(d for i, d in enumerate(x.shape) if i not in axes)
    else:
        shape = tuple(d for d in x.shape if d != 1)
    return {"Out": x.with_shape(shape)}


@register_shape_fn("unsqueeze")
def _unsqueeze_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    shape = list(x.shape)
    for a in sorted(attrs.get("axes", [])):
        shape.insert(a if a >= 0 else a + len(shape) + 1, 1)
    return {"Out": x.with_shape(shape)}


@register_shape_fn("transpose")
def _transpose_shape(op, ins, attrs):
    x = first(ins, "X")
    perm = attrs.get("axis")
    if x.shape is None or perm is None:
        return {"Out": x}
    if sorted(a % len(x.shape) for a in perm) != list(range(len(x.shape))):
        raise ShapeError(
            f"transpose: axis {list(perm)} is not a permutation of rank "
            f"{len(x.shape)}")
    return {"Out": x.with_shape(tuple(x.shape[a] for a in perm))}


@register_shape_fn("concat")
def _concat_shape(op, ins, attrs):
    xs = [v for v in ins.get("X", []) if v is not None]
    known = [v for v in xs if v.shape is not None]
    if not known:
        return {"Out": VarInfo(None, xs[0].dtype if xs else None)}
    nd = len(known[0].shape)
    axis = attrs.get("axis", 0) % nd
    shape = list(known[0].shape)
    for v in known[1:]:
        if len(v.shape) != nd:
            raise ShapeError(
                f"concat: rank mismatch {list(known[0].shape)} vs "
                f"{list(v.shape)}")
        for i in range(nd):
            if i != axis and not dim_ok(shape[i], v.shape[i]):
                raise ShapeError(
                    f"concat: non-axis dim {i} differs: "
                    f"{list(known[0].shape)} vs {list(v.shape)}")
            shape[i] = unify_dim(shape[i], v.shape[i]) if i != axis \
                else shape[i]
    if len(known) == len(xs):
        cat = 0
        for v in known:
            if v.shape[axis] < 0:
                cat = -1
                break
            cat += v.shape[axis]
    else:
        cat = -1
    shape[axis] = cat
    return {"Out": VarInfo(shape, known[0].dtype)}


@register_shape_fn("split")
def _split_shape(op, ins, attrs):
    x = first(ins, "X")
    names = op.outputs.get("Out", [])
    if x.shape is None:
        return {"Out": [VarInfo(None, x.dtype)] * len(names)}
    axis = attrs.get("axis", 0) % len(x.shape)
    sections = attrs.get("sections")
    if sections:
        if len(sections) != len(names):
            raise ShapeError(
                f"split: {len(sections)} sections for {len(names)} outputs")
        if x.shape[axis] >= 0 and sum(sections) != x.shape[axis]:
            raise ShapeError(
                f"split: sections {list(sections)} do not sum to dim "
                f"{x.shape[axis]}")
        return {"Out": [x.with_shape(x.shape[:axis] + (s,)
                                     + x.shape[axis + 1:])
                        for s in sections]}
    num = attrs.get("num", len(names))
    if x.shape[axis] >= 0 and num and x.shape[axis] % num:
        raise ShapeError(
            f"split: dim {x.shape[axis]} not divisible into {num} parts")
    part = -1 if x.shape[axis] < 0 else x.shape[axis] // num
    return {"Out": [x.with_shape(x.shape[:axis] + (part,)
                                 + x.shape[axis + 1:])] * len(names)}


@register_shape_fn("pad")
def _pad_shape(op, ins, attrs):
    x = first(ins, "X")
    p = attrs.get("paddings")
    if x.shape is None or p is None:
        return {"Out": x}
    if len(p) != 2 * len(x.shape):
        raise ShapeError(
            f"pad: {len(p)} padding entries for rank {len(x.shape)}")
    shape = tuple(d if d < 0 else d + p[2 * i] + p[2 * i + 1]
                  for i, d in enumerate(x.shape))
    return {"Out": x.with_shape(shape)}


@register_shape_fn("crop")
def _crop_shape(op, ins, attrs):
    x = first(ins, "X")
    shape = attrs.get("shape")
    if shape is None:
        return {"Out": VarInfo(None, x.dtype)}
    offsets = attrs.get("offsets") or (0,) * len(shape)
    if x.shape is not None:
        # a negative size keeps to the end — the lowering slices x[o:],
        # so the dim is input minus offset (symbolic when input is)
        out = tuple(
            (x.shape[i] - offsets[i] if x.shape[i] >= 0 else -1)
            if s < 0 else s
            for i, s in enumerate(shape))
    else:
        out = tuple(s if s >= 0 else -1 for s in shape)
    return {"Out": VarInfo(out, x.dtype)}


@register_shape_fn("expand")
def _expand_shape(op, ins, attrs):
    return _tile_like(first(ins, "X"), attrs.get("expand_times"))


@register_shape_fn("tile")
def _tile_shape(op, ins, attrs):
    return _tile_like(first(ins, "X"), attrs.get("repeat_times"))


def _tile_like(x, times):
    if x.shape is None or times is None:
        return {"Out": VarInfo(None, x.dtype)}
    times = list(times)
    if len(times) < len(x.shape):
        times = [1] * (len(x.shape) - len(times)) + times
    shape = [1] * (len(times) - len(x.shape)) + list(x.shape)
    out = tuple(d if d < 0 else d * t for d, t in zip(shape, times))
    return {"Out": VarInfo(out, x.dtype)}


@register_shape_fn("slice")
def _slice_shape(op, ins, attrs):
    x = first(ins, "Input") if ins.get("Input") else first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    shape = list(x.shape)
    for ax, st, en in zip(attrs.get("axes", []), attrs.get("starts", []),
                          attrs.get("ends", [])):
        ax = ax % len(shape)
        d = shape[ax]
        if d < 0:
            continue
        lo = max(st + d, 0) if st < 0 else min(st, d)
        hi = max(en + d, 0) if en < 0 else min(en, d)
        shape[ax] = max(hi - lo, 0)
    return {"Out": x.with_shape(shape)}


@register_shape_fn("gather")
def _gather_shape(op, ins, attrs):
    x, idx = first(ins, "X"), first(ins, "Index")
    if x.shape is None or idx.shape is None:
        return {"Out": VarInfo(None, x.dtype)}
    axis = attrs.get("axis", 0) % len(x.shape)
    # NO [N,1]->[N] squeeze: the lowering is a plain jnp.take, so a 2-D
    # index really does produce (..., N, 1, ...) — the rule must describe
    # the runtime, not the reference's squeezing variant
    return {"Out": VarInfo(x.shape[:axis] + idx.shape
                           + x.shape[axis + 1:], x.dtype)}


@register_shape_fn("multiplex")
def _multiplex_shape(op, ins, attrs):
    x = first(ins, "X")
    return {"Out": x}


@register_shape_fn("top_k")
def _top_k_shape(op, ins, attrs):
    x = first(ins, "X")
    k = attrs.get("k", 1)
    if x.shape is None:
        return {"Out": x, "Indices": VarInfo(None, "int64")}
    if x.shape[-1] >= 0 and k > x.shape[-1]:
        raise ShapeError(f"top_k: k={k} > last dim {x.shape[-1]}")
    shape = x.shape[:-1] + (k,)
    return {"Out": x.with_shape(shape),
            "Indices": VarInfo(shape, "int64")}


@register_shape_fn("sampling_id")
def _sampling_id_shape(op, ins, attrs):
    x = first(ins, "X")
    b = x.shape[0] if x.shape is not None else -1
    return {"Out": VarInfo((b,), "int64")}


@register_shape_fn("argmax", "arg_max", "max_ids")
def _argmax_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": VarInfo(None, "int64")}
    axis = attrs.get("axis", -1) % len(x.shape)
    return {"Out": VarInfo(x.shape[:axis] + x.shape[axis + 1:], "int64")}


@register_shape_fn("argsort")
def _argsort_shape(op, ins, attrs):
    x = first(ins, "X")
    return {"Out": x, "Indices": VarInfo(x.shape, "int64")}


@register_shape_fn("one_hot")
def _one_hot_shape(op, ins, attrs):
    ids = first(ins, "X")
    s = squeeze_ids(ids)
    if s is None:
        return {"Out": VarInfo(None, "float32")}
    return {"Out": VarInfo(s + (attrs["depth"],), "float32")}


@register_shape_fn("range")
def _range_shape(op, ins, attrs):
    start, end = attrs.get("start"), attrs.get("end")
    step = attrs.get("step", 1)
    dt = attrs.get("dtype", "int64")
    try:
        n = max(0, int(-(-(end - start) // step)))
    except (TypeError, ZeroDivisionError):
        n = -1
    return {"Out": VarInfo((n,), dt)}


@register_shape_fn("flatten")
def _flatten_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    ax = attrs.get("axis", 1)
    return {"Out": x.with_shape((prod_dims(x.shape[:ax]),
                                 prod_dims(x.shape[ax:])))}


@register_shape_fn("stack")
def _stack_shape(op, ins, attrs):
    xs = [v for v in ins.get("X", []) if v is not None]
    base = next((v for v in xs if v.shape is not None), None)
    if base is None:
        return {"Out": VarInfo(None, xs[0].dtype if xs else None)}
    for v in xs:
        if not shapes_compatible(v.shape, base.shape):
            raise ShapeError(
                f"stack: operand shapes differ: {list(base.shape)} vs "
                f"{list(v.shape)}")
    axis = attrs.get("axis", 0)
    nd = len(base.shape) + 1
    axis = axis % nd
    shape = base.shape[:axis] + (len(xs),) + base.shape[axis:]
    return {"Out": VarInfo(shape, base.dtype)}


@register_shape_fn("unstack")
def _unstack_shape(op, ins, attrs):
    x = first(ins, "X")
    names = op.outputs.get("Y", [])
    if x.shape is None:
        return {"Y": [VarInfo(None, x.dtype)] * len(names)}
    axis = attrs.get("axis", 0) % len(x.shape)
    if x.shape[axis] >= 0 and len(names) not in (0, x.shape[axis]):
        raise ShapeError(
            f"unstack: {len(names)} outputs for dim {x.shape[axis]}")
    part = x.shape[:axis] + x.shape[axis + 1:]
    return {"Y": [x.with_shape(part)] * len(names)}


@register_shape_fn("is_empty")
def _is_empty_shape(op, ins, attrs):
    return {"Out": VarInfo((), "bool")}


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop).  reshape keeps the
# batch sharding only when the batch dim survives the reshape; transpose
# permutes entries; concat/split replicate their concat axis (a sharded
# concat dim would interleave shards).
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import (first_in, merge_entry,  # noqa: E402
                                   shard_batch_only, shard_noop,
                                   shard_replicated, shard_same_as)
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("feed", "fetch", "assign", "fill_zeros_like",
                  "fill_any_like", "shuffle", "scatter", "reverse",
                  "lod_reset")(shard_same_as("X"))
register_shard_fn("fill_constant", "gaussian_random", "uniform_random",
                  "truncated_gaussian_random", "range", "assign_value",
                  "shape")(shard_replicated("Out"))
register_shard_fn("is_empty")(shard_noop())


@register_shard_fn("reshape")
def _reshape_shard(op, ins, attrs):
    x = first_in(ins, "X")
    if x.spec is None:
        return {}
    new_shape = list(attrs.get("shape", []))
    if not new_shape:
        return {}
    keep_batch = new_shape[0] in (-1, 0) or \
        (x.shape is not None and new_shape[0] == x.shape[0])
    return {"Out": ((x.entry(0),) if keep_batch else (None,))
            + (None,) * (len(new_shape) - 1)}


@register_shard_fn("transpose")
def _transpose_shard(op, ins, attrs):
    x = first_in(ins, "X")
    perm = attrs.get("axis")
    if x.spec is None or perm is None:
        return {}
    n = len(x.spec)
    return {"Out": tuple(x.entry(a % n) for a in perm)}


@register_shard_fn("concat")
def _concat_shard(op, ins, attrs):
    xs = ins.get("X", [])
    if not any(x.spec is not None for x in xs):
        return {}
    nd = next((x.ndim for x in xs if x.ndim is not None), None)
    if nd is None:
        return {}
    axis = attrs.get("axis", 0) % nd
    entries = []
    for i in range(nd):
        if i == axis:
            entries.append(None)
            continue
        e = None
        for x in xs:
            e = merge_entry(e, x.entry(i), f"concat operands dim {i}")
        entries.append(e)
    return {"Out": tuple(entries)}


@register_shard_fn("squeeze", "unsqueeze", "flatten")
def _rank_change_shard(op, ins, attrs):
    # conservatively keep only the batch-dim sharding (dim 0 survives all
    # three ops' lowerings for the axes>=1 cases the layers emit)
    x = first_in(ins, "X")
    if x.spec is None:
        return {}
    return {"Out": (x.entry(0),)}


# index/selection family: batch dim follows X/Ids, everything else
# replicates (indices are tiny; gather output layout is data-driven)
register_shard_fn("gather", "one_hot", "top_k", "argmax", "arg_max",
                  "argsort", "sampling_id", "max_ids")(
    shard_batch_only("X", fallbacks=("Ids",), also=("Indices",)))
