"""Tensor creation / data-movement op lowerings.

Reference category (SURVEY §2.2 Data/layout + I/O): reshape, transpose,
concat, split, pad, crop, expand, gather/scatter, multiplex, top_k,
fill_constant(_batch_size_like), fill_zeros_like, gaussian_random,
uniform_random, assign, one_hot, shape.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.types import convert_dtype


@register_op("feed", "fetch")
def _feed_fetch(ctx, ins, attrs):
    """Kept for program parity (feed_op.cc/fetch_op.cc); the executor feeds
    and fetches by name directly, so these are identity/no-ops."""
    if "X" in ins and ins["X"]:
        return {"Out": ins["X"][0]}
    return {}


@register_op("assign")
def _assign(ctx, ins, attrs):
    return {"Out": ins["X"][0]}


@register_op("shape")
def _shape(ctx, ins, attrs):
    return {"Out": jnp.asarray(ins["X"][0].shape, dtype=jnp.int64)}


@register_op("fill_constant")
def _fill_constant(ctx, ins, attrs):
    dt = convert_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs.get("shape", []))
    return {"Out": jnp.full(shape, attrs.get("value", 0.0), dtype=dt)}


@register_op("fill_constant_batch_size_like")
def _fill_cbsl(ctx, ins, attrs):
    """Shape copied from Input except the batch dim (fill_constant_batch_
    size_like_op.cc) — used to seed decoder states."""
    ref = ins["Input"][0]
    dt = convert_dtype(attrs.get("dtype", "float32"))
    shape = list(attrs.get("shape", []))
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    return {"Out": jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=dt)}


@register_op("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    return {"Out": jnp.zeros_like(ins["X"][0])}


@register_op("fill_any_like")
def _fill_any_like(ctx, ins, attrs):
    return {"Out": jnp.full_like(ins["X"][0], attrs.get("value", 0.0))}


@register_op("gaussian_random")
def _gaussian_random(ctx, ins, attrs):
    dt = convert_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs.get("shape", []))
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    return {"Out": mean + std * jax.random.normal(ctx.rng(), shape, dtype=dt)}


@register_op("uniform_random")
def _uniform_random(ctx, ins, attrs):
    dt = convert_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs.get("shape", []))
    return {"Out": jax.random.uniform(ctx.rng(), shape, dtype=dt,
                                      minval=attrs.get("min", -1.0),
                                      maxval=attrs.get("max", 1.0))}


@register_op("truncated_gaussian_random")
def _truncated_gaussian_random(ctx, ins, attrs):
    dt = convert_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs.get("shape", []))
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    return {"Out": mean + std * jax.random.truncated_normal(
        ctx.rng(), -2.0, 2.0, shape, dtype=dt)}


@register_op("assign_value")
def _assign_value(ctx, ins, attrs):
    vals = attrs["values"]
    dt = convert_dtype(attrs.get("dtype", "float32"))
    arr = jnp.asarray(vals, dtype=dt)
    if "shape" in attrs and attrs["shape"]:
        arr = arr.reshape(tuple(attrs["shape"]))
    return {"Out": arr}


@register_op("reshape")
def _reshape(ctx, ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    # fluid: 0 means copy input dim, -1 infers
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return {"Out": x.reshape(tuple(shape))}


@register_op("squeeze")
def _squeeze(ctx, ins, attrs):
    axes = attrs.get("axes", None)
    return {"Out": jnp.squeeze(ins["X"][0],
                               axis=tuple(axes) if axes else None)}


@register_op("unsqueeze")
def _unsqueeze(ctx, ins, attrs):
    return {"Out": jnp.expand_dims(ins["X"][0], tuple(attrs["axes"]))}


@register_op("transpose")
def _transpose(ctx, ins, attrs):
    return {"Out": jnp.transpose(ins["X"][0], tuple(attrs["axis"]))}


@register_op("concat")
def _concat(ctx, ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))}


@register_op("split")
def _split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    sections = attrs.get("sections")
    if sections:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, attrs["num"], axis=axis)
    return {"Out": list(parts)}


@register_op("pad")
def _pad(ctx, ins, attrs):
    """pad_op: paddings = [before0, after0, before1, after1, ...]"""
    x = ins["X"][0]
    p = attrs["paddings"]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))}


@register_op("crop")
def _crop(ctx, ins, attrs):
    x = ins["X"][0]
    offsets = attrs["offsets"]
    shape = attrs["shape"]
    # a negative size is a symbolic dim (e.g. batch -1): keep to the end
    slices = tuple(slice(o, o + s if s >= 0 else None)
                   for o, s in zip(offsets, shape))
    return {"Out": x[slices]}


@register_op("expand")
def _expand(ctx, ins, attrs):
    """expand_op: tile each dim by expand_times."""
    return {"Out": jnp.tile(ins["X"][0], tuple(attrs["expand_times"]))}


@register_op("tile")
def _tile(ctx, ins, attrs):
    return {"Out": jnp.tile(ins["X"][0], tuple(attrs["repeat_times"]))}


@register_op("slice")
def _slice(ctx, ins, attrs):
    # fluid's slice_op names its input slot "Input"; accept both spellings
    x = ins.get("Input", ins.get("X"))[0]
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    sl = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        sl[ax] = slice(st, en)
    return {"Out": x[tuple(sl)]}


@register_op("gather")
def _gather(ctx, ins, attrs):
    """gather_op: rows of X by Index (gather.h)."""
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": jnp.take(x, idx.astype(jnp.int32),
                            axis=attrs.get("axis", 0))}


@register_op("scatter")
def _scatter(ctx, ins, attrs):
    """scatter_op: write Updates rows into X at Ids (scatter.h).
    overwrite=False accumulates (the SelectedRows-merge behavior)."""
    x, ids, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    ids = ids.astype(jnp.int32).reshape(-1)
    if attrs.get("overwrite", True):
        return {"Out": x.at[ids].set(upd)}
    return {"Out": x.at[ids].add(upd)}


@register_op("multiplex")
def _multiplex(ctx, ins, attrs):
    """multiplex_op: per-row select among candidate tensors by Ids."""
    ids = ins["Ids"][0].astype(jnp.int32).reshape(-1)
    stack = jnp.stack(ins["X"], axis=0)  # [K, N, ...]
    return {"Out": stack[ids, jnp.arange(stack.shape[1])]}


@register_op("top_k")
def _top_k(ctx, ins, attrs):
    vals, idx = jax.lax.top_k(ins["X"][0], attrs["k"])
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op("sampling_id")
def _sampling_id(ctx, ins, attrs):
    """sampling_id_op (SamplingIdLayer.cpp): sample one id per row from
    the row's probability distribution; per-step PRNG key from ctx."""
    x = ins["X"][0]                  # [B, V] probabilities
    logp = jnp.log(jnp.clip(x.astype(jnp.float32), 1e-20, None))
    ids = jax.random.categorical(ctx.rng(), logp, axis=-1)
    return {"Out": ids.astype(jnp.int64)}


@register_op("argmax", "arg_max", "max_ids")
def _argmax(ctx, ins, attrs):
    return {"Out": jnp.argmax(ins["X"][0], axis=attrs.get("axis", -1))
            .astype(jnp.int64)}


@register_op("argsort")
def _argsort(ctx, ins, attrs):
    axis = attrs.get("axis", -1)
    x = ins["X"][0]
    idx = jnp.argsort(x, axis=axis, descending=attrs.get("descending", False))
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(jnp.int64)}


@register_op("one_hot")
def _one_hot(ctx, ins, attrs):
    x = ins["X"][0].astype(jnp.int32)
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x.squeeze(-1)
    return {"Out": jax.nn.one_hot(x, attrs["depth"], dtype=jnp.float32)}


@register_op("range")
def _range(ctx, ins, attrs):
    return {"Out": jnp.arange(attrs["start"], attrs["end"],
                              attrs.get("step", 1),
                              dtype=convert_dtype(attrs.get("dtype", "int64")))}


@register_op("flatten")
def _flatten(ctx, ins, attrs):
    x = ins["X"][0]
    ax = attrs.get("axis", 1)
    lead = 1
    for s in x.shape[:ax]:
        lead *= s
    return {"Out": x.reshape((lead, -1))}


@register_op("stack")
def _stack(ctx, ins, attrs):
    return {"Out": jnp.stack(ins["X"], axis=attrs.get("axis", 0))}


@register_op("unstack")
def _unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    return {"Y": [jnp.squeeze(p, axis)
                  for p in jnp.split(x, x.shape[axis], axis=axis)]}


@register_op("where", "select")
def _where(ctx, ins, attrs):
    return {"Out": jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])}


@register_op("is_empty")
def _is_empty(ctx, ins, attrs):
    """is_empty_op.cc — static under XLA (shapes are compile-time)."""
    return {"Out": jnp.asarray(ins["X"][0].size == 0)}


@register_op("shuffle")
def _shuffle(ctx, ins, attrs):
    x = ins["X"][0]
    perm = jax.random.permutation(ctx.rng(), x.shape[0])
    return {"Out": x[perm]}


@register_op("reverse")
def _reverse(ctx, ins, attrs):
    axes = attrs.get("axis", [0])
    if not isinstance(axes, (list, tuple)):
        axes = [axes]
    return {"Out": jnp.flip(ins["X"][0], axis=tuple(axes))}
