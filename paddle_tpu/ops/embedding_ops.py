"""Embedding / sparse op lowerings.

Reference: lookup_table_op.cc (dense or SelectedRows gradient), nce_op,
HierarchicalSigmoidLayer (v1).  On TPU the SelectedRows sparse-gradient
machinery (selected_rows.h:19) is subsumed by XLA scatter-add gradients of
gather — and sharded tables ride the mesh via paddle_tpu.parallel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("lookup_table")
def _lookup_table(ctx, ins, attrs):
    """W [V, D]; Ids [...,1] or [...] int -> Out [..., D].

    padding_idx rows return zeros (lookup_table_op.cc padding_idx attr).
    The gather's vjp is a scatter-add — exactly the SelectedRows grad path,
    derived automatically.
    """
    w, ids = ins["W"][0], ins["Ids"][0]
    ids = ids.astype(jnp.int32)
    squeeze = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze:
        ids = ids.squeeze(-1)
    pad = attrs.get("padding_idx", None)
    safe = ids
    if pad is not None and pad >= 0:
        safe = jnp.where(ids == pad, 0, ids)
    out = jnp.take(w, safe, axis=0)
    if pad is not None and pad >= 0:
        out = jnp.where((ids == pad)[..., None], 0.0, out)
    return {"Out": out}


@register_op("lookup_table_sparse")
def _lookup_table_sparse(ctx, ins, attrs):
    """Host-resident sparse-table gather (paddle_tpu.sparse): the table
    lives on the HOST, the ``SparseSession`` rim feeds the dense
    ``[n_unique, dim]`` rows a batch touches plus the inverse index
    mapping each id position to its unique slot — the device op is just
    the dense gather.  ``Rows``'s gradient (the scatter-add VJP of this
    take) is fetched as ``<rows>@GRAD`` and pushed back host-side, which
    is the reference's SparseRemoteParameterUpdater pull/push cycle
    (RemoteParameterUpdater.h:265, math/SparseRowMatrix.h:206).

    ``Ids`` rides along unconsumed (the session derives Inverse from it
    host-side); keeping it an input preserves the graph's data
    dependency for pruning/validation."""
    rows, inv = ins["Rows"][0], ins["Inverse"][0]
    return {"Out": jnp.take(rows, inv.astype(jnp.int32), axis=0)}


@register_op("nce")
def _nce(ctx, ins, attrs):
    """nce_op: noise-contrastive estimation with uniform negative sampling.

    Inputs: Input [B, D], Label [B, 1] (single true class), Weight [V, D],
    optional Bias [V].  attrs: num_neg_samples, num_total_classes.
    Output Cost [B, 1]; SampleLogits/SampleLabels exposed like the reference.
    """
    x = ins["Input"][0]
    label = ins["Label"][0].astype(jnp.int32).reshape(-1)
    w = ins["Weight"][0]
    bias = ins["Bias"][0] if "Bias" in ins and ins["Bias"] else None
    k = attrs.get("num_neg_samples", 10)
    V = attrs.get("num_total_classes", w.shape[0])
    B = x.shape[0]
    neg = jax.random.randint(ctx.rng(), (B, k), 0, V)
    samples = jnp.concatenate([label[:, None], neg], axis=1)   # [B, 1+k]
    sw = jnp.take(w, samples, axis=0)                          # [B, 1+k, D]
    logits = jnp.einsum("bd,bkd->bk", x, sw)
    if bias is not None:
        logits = logits + jnp.take(bias.reshape(-1), samples)
    # P(noise) uniform = 1/V; logit correction log(k * pn)
    logits = logits - jnp.log(k / V)
    labels = jnp.concatenate(
        [jnp.ones((B, 1)), jnp.zeros((B, k))], axis=1)
    ce = jnp.maximum(logits, 0) - logits * labels + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    cost = jnp.sum(ce, axis=1, keepdims=True)
    return {"Cost": cost, "SampleLogits": logits,
            "SampleLabels": samples.astype(jnp.int64)}


@register_op("hierarchical_sigmoid", "hsigmoid")
def _hsigmoid(ctx, ins, attrs):
    """HierarchicalSigmoidLayer (v1): complete-binary-tree hierarchical
    softmax over num_classes leaves."""
    x = ins["X"][0]                       # [B, D]
    label = ins["Label"][0].astype(jnp.int32).reshape(-1)
    w = ins["W"][0]                       # [num_classes-1, D] internal nodes
    bias = ins["Bias"][0] if "Bias" in ins and ins["Bias"] else None
    num_classes = attrs["num_classes"]
    depth = max(1, int(jnp.ceil(jnp.log2(num_classes)).item()) if not
                isinstance(num_classes, int) else (num_classes - 1).bit_length())
    # path through a complete binary tree: node ids from root, code bits
    codes = label + num_classes - 1       # leaf index in heap layout... walk up
    path_nodes = []
    path_bits = []
    node = codes
    for _ in range(depth):
        bit = node % 2                    # left/right
        node = (node - 1) // 2
        path_nodes.append(node)
        path_bits.append(bit)
    nodes = jnp.stack(path_nodes, axis=1)      # [B, depth]
    bits = jnp.stack(path_bits, axis=1).astype(x.dtype)
    valid = (nodes >= 0) & (nodes < num_classes - 1)
    nsafe = jnp.clip(nodes, 0, num_classes - 2)
    wn = jnp.take(w, nsafe, axis=0)            # [B, depth, D]
    logits = jnp.einsum("bd,bkd->bk", x, wn)
    if bias is not None:
        logits = logits + jnp.take(bias.reshape(-1), nsafe)
    ce = jnp.maximum(logits, 0) - logits * bits + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    cost = jnp.sum(ce * valid.astype(x.dtype), axis=1, keepdims=True)
    return {"Out": cost, "PreOut": logits}


# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer).
# ---------------------------------------------------------------------------
import numpy as _np  # noqa: E402

from ..analysis.shape_infer import (ShapeError, VarInfo, first,  # noqa: E402
                                    squeeze_ids)
from ..core.registry import register_shape_fn  # noqa: E402


@register_shape_fn("lookup_table")
def _lookup_table_shape(op, ins, attrs):
    w, ids = first(ins, "W"), first(ins, "Ids")
    if ids.dtype is not None and ids.dtype.kind == "f":
        raise ShapeError(
            f"lookup_table: Ids must be integral, got {ids.dtype.name}")
    s = squeeze_ids(ids)
    if s is None or w.shape is None:
        return {"Out": VarInfo(None, w.dtype)}
    return {"Out": VarInfo(s + (w.shape[-1],), w.dtype)}


@register_shape_fn("lookup_table_sparse")
def _lookup_table_sparse_shape(op, ins, attrs):
    rows, inv = first(ins, "Rows"), first(ins, "Inverse")
    if inv.dtype is not None and inv.dtype.kind == "f":
        raise ShapeError(
            f"lookup_table_sparse: Inverse must be integral, got "
            f"{inv.dtype.name}")
    dim = int(attrs.get("dim", rows.shape[-1] if rows.shape else -1))
    if inv.shape is None:
        return {"Out": VarInfo(None, rows.dtype)}
    return {"Out": VarInfo(tuple(inv.shape) + (dim,), rows.dtype)}


@register_shape_fn("nce")
def _nce_shape(op, ins, attrs):
    x, w = first(ins, "Input"), first(ins, "Weight")
    if x.shape is not None and w.shape is not None and \
            x.shape[-1] >= 0 and w.shape[-1] >= 0 and \
            x.shape[-1] != w.shape[-1]:
        raise ShapeError(
            f"nce: Input dim {x.shape[-1]} != Weight dim {w.shape[-1]}")
    b = x.shape[0] if x.shape is not None else -1
    k = attrs.get("num_neg_samples", 10)
    return {"Cost": VarInfo((b, 1), _np.float32 if x.dtype is None
                            else x.dtype),
            "SampleLogits": VarInfo((b, 1 + k), x.dtype),
            "SampleLabels": VarInfo((b, 1 + k), "int64")}


@register_shape_fn("hierarchical_sigmoid", "hsigmoid")
def _hsigmoid_shape(op, ins, attrs):
    x = first(ins, "X")
    b = x.shape[0] if x.shape is not None else -1
    num_classes = attrs.get("num_classes")
    depth = (int(num_classes) - 1).bit_length() \
        if isinstance(num_classes, int) else -1
    return {"Out": VarInfo((b, 1), x.dtype),
            "PreOut": VarInfo((b, depth), x.dtype)}


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop).  A vocab-sharded table
# (Megatron embedding / the reference's SelectedRows-on-pserver analog)
# lowers to a masked partial gather + all-reduce under GSPMD; the output
# rides the Ids' batch sharding either way, with the emb dim following the
# table's column split.
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import (first_in,  # noqa: E402
                                   shard_batch_only, squeeze_spec_ids)
from ..core.registry import register_shard_fn  # noqa: E402


@register_shard_fn("lookup_table")
def _lookup_table_shard(op, ins, attrs):
    w, ids = first_in(ins, "W"), first_in(ins, "Ids")
    if w.spec is None and ids.spec is None:
        return {}
    lead = squeeze_spec_ids(ids)
    return {"Out": lead + (w.entry(-1),)}


@register_shard_fn("lookup_table_sparse")
def _lookup_table_sparse_shard(op, ins, attrs):
    # The table is HOST-side; the planner sees only the dense gathered
    # rows as a device tensor.  Out follows the inverse index's (batch)
    # sharding with the emb dim riding the rows feed's column split
    # (normally replicated — the rows feed is host-built per batch).
    rows, inv = first_in(ins, "Rows"), first_in(ins, "Inverse")
    if rows.spec is None and inv.spec is None:
        return {}
    lead = tuple(inv.spec) if inv.spec is not None else (None,)
    return {"Out": lead + (rows.entry(-1),)}


register_shard_fn("nce", "hierarchical_sigmoid", "hsigmoid")(
    shard_batch_only("Input", out="Cost", fallbacks=("X",),
                     also=("Out", "PreOut", "SampleLogits",
                           "SampleLabels")))
