"""I/O op lowerings (save_op.cc:37-80, load_op.cc).

Persistence itself is host-side (paddle_tpu.io reads/writes the Scope with
numpy), because device->host transfer cannot live inside a jitted program.
The ops are registered so programs containing them remain loadable; when
executed they are no-ops and paddle_tpu.io performs the actual serialization.
"""
from __future__ import annotations

from ..core.registry import register_op


@register_op("save")
def _save(ctx, ins, attrs):
    return {}


@register_op("load")
def _load(ctx, ins, attrs):
    return {}


from ..analysis.shape_infer import no_outputs  # noqa: E402
from ..core.registry import register_shape_fn  # noqa: E402

register_shape_fn("save", "load")(no_outputs())

# Sharding propagation: persistence ops are host-side no-ops.
from ..analysis.shard_prop import shard_noop  # noqa: E402
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("save", "load")(shard_noop())
