"""Mixture-of-Experts op lowering — the Program-level path to expert
parallelism over the 'ep' mesh axis.

GShard-style dense formulation: routing is einsums over a [T, E, C]
dispatch tensor (parallel/moe.py), and expert-parallelism is expressed as
SHARDING CONSTRAINTS, not hand-written collectives — when the lowering
context carries a mesh whose 'ep' axis is >1 (ShardedExecutor), the
[E, C, D] expert batches are constrained to P('ep', ...) matching the
P('ep', ...)-sharded expert weights, and GSPMD inserts the all-to-all
each way (exactly how GShard itself drove the XLA partitioner).  On a
single device the same graph runs constraint-free with identical math —
which is what the equivalence test asserts.

Reference capability frame: the reference never shipped MoE; nearest
ancestors are per-layer device placement (ParallelNeuralNetwork.cpp) and
the sparse-update machinery (SelectedRows).  This is capability-forward
surface the ep mesh axis exists for.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.registry import register_op

_ACTS = {
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "swish": jax.nn.swish,
}


@register_op("moe")
def _moe(ctx, ins, attrs):
    from ..parallel.moe import load_balancing_loss, moe_dispatch

    x = ins["X"][0]
    gate_w = ins["GateW"][0]
    w1 = ins["W1"][0]          # [E, D, H], sharded P('ep', ...) on a mesh
    w2 = ins["W2"][0]          # [E, H, D]
    top_k = int(attrs.get("top_k", 2))
    cap_f = float(attrs.get("capacity_factor", 1.25))
    act = _ACTS[attrs.get("activation", "relu")]

    shape = x.shape
    D = shape[-1]
    xt = x.reshape(-1, D)
    T, E = xt.shape[0], gate_w.shape[-1]

    logits = xt @ gate_w
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
        x.dtype)
    capacity = max(1, int(cap_f * top_k * T / E))
    dispatch, combine = moe_dispatch(gates, capacity, top_k)
    aux = load_balancing_loss(gates, dispatch)

    ep = ctx.mesh_axis_size("ep")

    def on_experts(a):
        if ep > 1:
            return lax.with_sharding_constraint(
                a, NamedSharding(ctx.mesh, P("ep", None, None)))
        return a

    expert_in = on_experts(jnp.einsum("tec,td->ecd", dispatch, xt))
    h = act(jnp.einsum("ecd,edh->ech", expert_in, w1))
    out_e = on_experts(jnp.einsum("ech,ehd->ecd", h, w2))
    out = jnp.einsum("tec,ecd->td", combine, out_e)
    return {"Out": out.reshape(shape),
            "AuxLoss": aux.reshape(()).astype(jnp.float32)}


# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer).
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import ShapeError, VarInfo, first  # noqa: E402
from ..core.registry import register_shape_fn  # noqa: E402


@register_shape_fn("moe")
def _moe_shape(op, ins, attrs):
    x, gate_w = first(ins, "X"), first(ins, "GateW")
    w1 = first(ins, "W1")
    if x.shape is not None and gate_w.shape is not None and \
            x.shape[-1] >= 0 and gate_w.shape[0] >= 0 and \
            x.shape[-1] != gate_w.shape[0]:
        raise ShapeError(
            f"moe: X feature dim {x.shape[-1]} != GateW rows "
            f"{gate_w.shape[0]}")
    if w1.shape is not None and gate_w.shape is not None and \
            w1.shape[0] >= 0 and gate_w.shape[-1] >= 0 and \
            w1.shape[0] != gate_w.shape[-1]:
        raise ShapeError(
            f"moe: W1 expert count {w1.shape[0]} != GateW experts "
            f"{gate_w.shape[-1]}")
    return {"Out": x, "AuxLoss": VarInfo((), "float32")}


# ---------------------------------------------------------------------------
# Sharding-propagation rule (analysis.shard_prop): the fused MoE op is
# token-preserving — Out rides X's sharding, the aux loss replicates.
# (Expert-parallel specs on W1/W2 partition the expert dim; the dispatch
# all-to-all is GSPMD's to insert and the cost model's to charge.)
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import first_in  # noqa: E402
from ..core.registry import register_shard_fn  # noqa: E402


@register_shard_fn("moe")
def _moe_shard(op, ins, attrs):
    x = first_in(ins, "X")
    if x.spec is None:
        return {}
    return {"Out": x.spec, "AuxLoss": ()}
