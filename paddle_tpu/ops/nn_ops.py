"""Neural-network op lowerings: conv, pool, normalization, softmax, dropout.

Reference category (SURVEY §2.2 NN): conv_op/conv_cudnn_op, conv_transpose,
pool_op/pool_cudnn, pool_with_index, batch_norm_op, softmax,
softmax_with_cross_entropy, cross_entropy, dropout, lrn, maxout, prelu (in
activation_ops).  cuDNN paths collapse into XLA convolutions, which tile onto
the MXU; data layout is NCHW for API parity.  Measured (ResNet-50 train step,
bs128 bf16, v5e): logical NCHW vs NHWC is within ~1% — XLA's layout
assignment re-tiles internally, so no NHWC rewrite is forced on users.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


def _conv2d_space_to_depth(x, w, pads):
    """Stride-2 small-channel conv (a ResNet/VGG-style stem) folded into a
    stride-1 conv over 2x2-space-to-depth input: 4x the MXU lane utilization
    when C_in is tiny (3 channels fill 3/128 lanes).  Exact — padded filter
    taps are zero.  Public MLPerf-era technique, not in the reference."""
    n, c, h, wd = x.shape
    o, _, kh, kw = w.shape
    kh2, kw2 = ((kh + 1) // 2) * 2, ((kw + 1) // 2) * 2
    wp = jnp.pad(w, ((0, 0), (0, 0), (0, kh2 - kh), (0, kw2 - kw)))
    w2 = wp.reshape(o, c, kh2 // 2, 2, kw2 // 2, 2) \
           .transpose(0, 1, 3, 5, 2, 4).reshape(o, c * 4, kh2 // 2, kw2 // 2)
    xp = jnp.pad(x, ((0, 0), (0, 0), (pads[0], pads[0]),
                     (pads[1], pads[1])))
    hp, wp_ = h + 2 * pads[0], wd + 2 * pads[1]
    xs = xp.reshape(n, c, hp // 2, 2, wp_ // 2, 2) \
           .transpose(0, 1, 3, 5, 2, 4).reshape(n, c * 4, hp // 2, wp_ // 2)
    return lax.conv_general_dilated(
        xs, w2, (1, 1), [(0, 0), (0, 0)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def _conv1x1_pallas_wanted(ctx, attrs) -> bool:
    """Tri-state opt-in resolution for the hand-written 1x1 Pallas path:
    per-op attr (layers.conv2d(use_pallas=...)) > per-executor setting
    (Executor(conv1x1_pallas=...)) > process flag (conv1x1_pallas)."""
    v = attrs.get("use_pallas")
    if v is None:
        v = getattr(ctx, "conv1x1_pallas", None)
    if v is None:
        from ..flags import get_flag
        v = get_flag("conv1x1_pallas")
    return bool(v)


@register_op("conv2d", "depthwise_conv2d")
def _conv2d(ctx, ins, attrs):
    """conv_op.cc / conv_cudnn_op: Input [N,C,H,W], Filter [M,C/g,kh,kw]."""
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1) or 1)
    if _conv1x1_pallas_wanted(ctx, attrs):
        from . import pallas_conv
        interpret = bool(attrs.get("pallas_interpret", False))
        # single-device only: GSPMD treats pallas_call as opaque, so under
        # a >1-device mesh the routing would silently replicate the conv
        single = ctx.mesh is None or getattr(ctx.mesh, "size", 1) == 1
        if (single and pallas_conv._HAVE_PALLAS
                and (interpret or jax.default_backend() == "tpu")
                and pallas_conv.conv1x1_eligible(
                    x.shape, w.shape, strides, pads, dil, groups)):
            return {"Output": pallas_conv.conv2d_1x1(
                x, w, strides, interpret=interpret)}
    if (strides == (2, 2) and dil == (1, 1) and groups == 1
            and x.shape[1] <= 4 and x.ndim == 4
            and (x.shape[2] + 2 * pads[0]) % 2 == 0
            and (x.shape[3] + 2 * pads[1]) % 2 == 0):
        return {"Output": _conv2d_space_to_depth(x, w, pads)}
    out = lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups,
    )
    return {"Output": out}


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    """conv_transpose_op: Filter layout [C_in, C_out/g, kh, kw] ('IOHW')."""
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    kh, kw = w.shape[2], w.shape[3]
    # transposed conv == lhs-dilated conv with flipped, transposed kernel
    out = lax.conv_general_dilated(
        x, jnp.flip(w, (2, 3)).swapaxes(0, 1),
        window_strides=(1, 1),
        padding=[(dil[0] * (kh - 1) - pads[0], dil[0] * (kh - 1) - pads[0]),
                 (dil[1] * (kw - 1) - pads[1], dil[1] * (kw - 1) - pads[1])],
        lhs_dilation=strides,
        rhs_dilation=dil,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return {"Output": out}


@register_op("conv3d")
def _conv3d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = tuple(attrs.get("strides", [1, 1, 1]))
    pads = tuple(attrs.get("paddings", [0, 0, 0]))
    dil = tuple(attrs.get("dilations", [1, 1, 1]))
    out = lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(p, p) for p in pads], rhs_dilation=dil,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=int(attrs.get("groups", 1) or 1),
    )
    return {"Output": out}


def _pool2d_core(x, ptype, ksize, strides, pads, global_pooling, exclusive,
                 adaptive=False, ceil_mode=False):
    if global_pooling or adaptive and tuple(ksize) == (1, 1):
        axis = (2, 3)
        if ptype == "max":
            return jnp.max(x, axis=axis, keepdims=True)
        return jnp.mean(x, axis=axis, keepdims=True)
    ksize = _pair(ksize)
    strides = _pair(strides)
    pads = _pair(pads)
    window = (1, 1) + ksize
    ws = (1, 1) + strides
    extra = (0, 0)
    if ceil_mode:
        # v1 default (PoolLayer ceil): pad right/bottom so partial windows
        # produce an output element
        def _extra(size, k, p, s):
            rem = (size + 2 * p - k) % s
            return (s - rem) % s if rem else 0
        extra = (_extra(x.shape[2], ksize[0], pads[0], strides[0]),
                 _extra(x.shape[3], ksize[1], pads[1], strides[1]))
    padding = ((0, 0), (0, 0), (pads[0], pads[0] + extra[0]),
               (pads[1], pads[1] + extra[1]))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max,
                                 window, ws, padding)
    s = lax.reduce_window(x, 0.0, lax.add,
                          window, ws, padding)
    if exclusive and (pads[0] or pads[1] or extra[0] or extra[1]):
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add,
                                window, ws, padding)
        return s / cnt
    return s / (ksize[0] * ksize[1])


@register_op("pool2d")
def _pool2d(ctx, ins, attrs):
    x = ins["X"][0]
    out = _pool2d_core(
        x, attrs.get("pooling_type", "max"), attrs.get("ksize", [2, 2]),
        attrs.get("strides", [1, 1]), attrs.get("paddings", [0, 0]),
        attrs.get("global_pooling", False), attrs.get("exclusive", True),
        ceil_mode=attrs.get("ceil_mode", False))
    return {"Out": out}


@register_op("pool3d")
def _pool3d(ctx, ins, attrs):
    """pool3d_op (pool_op.cc 3-D branch): NCDHW max/avg pooling."""
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False):
        ks = list(x.shape[2:])
        strides, pads = ks, [0, 0, 0]
    else:
        ks = list(attrs.get("ksize", [2, 2, 2]))
        strides = list(attrs.get("strides", ks))
        pads = list(attrs.get("paddings", [0, 0, 0]))
    window = (1, 1) + tuple(ks)
    stride = (1, 1) + tuple(strides)
    pad = ((0, 0), (0, 0)) + tuple((p, p) for p in pads)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, stride, pad)
    else:
        s = lax.reduce_window(x, 0.0, lax.add, window, stride, pad)
        ones = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                 stride, pad)
        out = s / ones
    return {"Out": out}


@register_op("max_pool2d_with_index", "pool2d_with_index")
def _max_pool2d_with_index(ctx, ins, attrs):
    """pool_with_index_op: returns flat H*W indices of maxima (for unpool).
    Patch extraction keeps this one fused XLA computation."""
    x = ins["X"][0]
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", ksize))
    pads = _pair(attrs.get("paddings", [0, 0]))
    n, c, h, w = x.shape
    patches = lax.conv_general_dilated_patches(
        x, ksize, strides,
        [(pads[0], pads[0]), (pads[1], pads[1])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    oh, ow = patches.shape[2], patches.shape[3]
    patches = patches.reshape(n, c, ksize[0] * ksize[1], oh, ow)
    arg = jnp.argmax(patches, axis=2)
    out = jnp.max(patches, axis=2)
    # convert patch-local index to flat input H*W index
    ph, pw = arg // ksize[1], arg % ksize[1]
    base_h = (jnp.arange(oh) * strides[0] - pads[0])[None, None, :, None]
    base_w = (jnp.arange(ow) * strides[1] - pads[1])[None, None, None, :]
    idx = (base_h + ph) * w + (base_w + pw)
    return {"Out": out, "Mask": idx.astype(jnp.int64)}


@register_op("unpool")
def _unpool(ctx, ins, attrs):
    """unpool_op: scatter values back to positions given by Indices."""
    x, idx = ins["X"][0], ins["Indices"][0]
    n, c, oh, ow = x.shape
    uh, uw = attrs["unpool_size"] if "unpool_size" in attrs else (
        attrs["ksize"][0] * oh, attrs["ksize"][1] * ow)
    flat = jnp.zeros((n, c, uh * uw), x.dtype)
    out = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1).astype(jnp.int32),
    ].set(x.reshape(n, c, -1))
    return {"Out": out.reshape(n, c, uh, uw)}


@register_op("batch_norm")
def _batch_norm(ctx, ins, attrs):
    """batch_norm_op.cc: NCHW (or NC) input; train updates running stats.

    Outputs mirror the reference (Y, MeanOut, VarianceOut, SavedMean,
    SavedVariance) so optimizer/IO code can treat stats as persistables.
    """
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    # v1 use_global_stats tri-state (BatchNormBaseLayer.cpp): True forces
    # running stats even in training, False forces batch stats even at
    # PASS_TEST (the GAN configs rely on this); None keeps is_test routing.
    # Running stats still update only on training passes.
    use_global = attrs.get("use_global_stats")
    if use_global is None:
        use_global = is_test
    axes = tuple(i for i in range(x.ndim) if i != 1)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    if use_global:
        use_mean = mean.astype(jnp.float32)
        use_var = var.astype(jnp.float32)
        mean_out, var_out = mean, var
    else:
        # single-pass stats: mean and mean-of-squares with fp32 accumulation
        # (one read of x for both reductions; under AMP x is bf16 and the
        # fp32 accumulate keeps the stats honest).  Caveat: E[x^2]-E[x]^2
        # cancels catastrophically when |mean| >> std; the fp32 accumulate
        # and the clamp below bound the damage, and post-BN activations in
        # practice are near zero-mean, but a pathological input distribution
        # can lose stat precision vs the two-pass form.
        use_mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
        m2 = jnp.mean(lax.square(x.astype(jnp.float32)), axis=axes)
        use_var = jnp.maximum(m2 - lax.square(use_mean), 0.0)
        use_mean_sg = lax.stop_gradient(use_mean)
        use_var_sg = lax.stop_gradient(use_var)
        if is_test:
            # batch stats forced by use_global_stats=False, but a test pass
            # never advances the moving averages
            mean_out, var_out = mean, var
        else:
            mean_out = (momentum * mean
                        + (1.0 - momentum) * use_mean_sg.astype(mean.dtype))
            var_out = (momentum * var
                       + (1.0 - momentum) * use_var_sg.astype(var.dtype))
    inv = lax.rsqrt(use_var + eps)
    # fold into a per-channel scale/shift so the big tensor gets ONE fused
    # multiply-add in its own dtype (no fp32 round trip through HBM)
    eff_scale = scale.astype(jnp.float32) * inv
    eff_shift = bias.astype(jnp.float32) - use_mean * eff_scale
    y = (x * eff_scale.reshape(bshape).astype(x.dtype)
         + eff_shift.reshape(bshape).astype(x.dtype))
    return {"Y": y, "MeanOut": mean_out, "VarianceOut": var_out,
            "SavedMean": use_mean, "SavedVariance": inv}


@register_op("layer_norm")
def _layer_norm(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    if "Scale" in ins and ins["Scale"]:
        shape = (1,) * begin + x.shape[begin:]
        y = y * ins["Scale"][0].reshape(shape)
    if "Bias" in ins and ins["Bias"]:
        shape = (1,) * begin + x.shape[begin:]
        y = y + ins["Bias"][0].reshape(shape)
    return {"Y": y, "Mean": mean.reshape(x.shape[:begin]),
            "Variance": var.reshape(x.shape[:begin])}


@register_op("softmax")
def _softmax(ctx, ins, attrs):
    return {"Out": jax.nn.softmax(ins["X"][0], axis=attrs.get("axis", -1))}


@register_op("log_softmax")
def _log_softmax(ctx, ins, attrs):
    return {"Out": jax.nn.log_softmax(ins["X"][0], axis=attrs.get("axis", -1))}


@register_op("cross_entropy")
def _cross_entropy(ctx, ins, attrs):
    """cross_entropy_op: X is probabilities [N, D]; hard or soft labels.
    Out is [N, 1] like the reference."""
    x, label = ins["X"][0], ins["Label"][0]
    eps = 1e-8
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, eps)), axis=-1,
                        keepdims=True)
    else:
        lab = label.astype(jnp.int32)
        if lab.ndim == x.ndim:
            lab = lab.squeeze(-1)
        picked = jnp.take_along_axis(x, lab[..., None], axis=-1)
        loss = -jnp.log(jnp.maximum(picked, eps))
    return {"Y": loss}


@register_op("softmax_with_cross_entropy")
def _softmax_with_ce(ctx, ins, attrs):
    """Fused, numerically-stable logits->loss (softmax_with_cross_entropy_op)."""
    logits, label = ins["Logits"][0], ins["Label"][0]
    logp = jax.nn.log_softmax(logits, axis=-1)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        lab = label.astype(jnp.int32)
        if lab.ndim == logits.ndim:
            lab = lab.squeeze(-1)
        loss = -jnp.take_along_axis(logp, lab[..., None], axis=-1)
    return {"Softmax": jnp.exp(logp), "Loss": loss}


@register_op("dropout")
def _dropout(ctx, ins, attrs):
    """dropout_op: reference semantics — train: x*mask; test: x*(1-p).
    'upscale_in_train' implementation also supported."""
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    upscale = attrs.get("dropout_implementation", "downgrade_in_infer") \
        == "upscale_in_train"
    if attrs.get("is_test", False) or ctx.is_test:
        out = x if upscale else x * (1.0 - p)
        return {"Out": out, "Mask": jnp.ones_like(x)}
    keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    out = x * mask
    if upscale:
        out = out / (1.0 - p)
    return {"Out": out, "Mask": mask}


@register_op("lrn")
def _lrn(ctx, ins, attrs):
    """lrn_op: cross-channel local response normalization (AlexNet)."""
    x = ins["X"][0]
    n = attrs.get("n", 5)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    k = attrs.get("k", 2.0)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": x / jnp.power(mid, beta), "MidOut": mid}


@register_op("maxout")
def _maxout(ctx, ins, attrs):
    """maxout_op: max over groups of channels."""
    x = ins["X"][0]
    g = attrs["groups"]
    n, c, h, w = x.shape
    return {"Out": jnp.max(x.reshape(n, c // g, g, h, w), axis=2)}


@register_op("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs):
    """v1 BilinearInterpLayer / interpolate: resize H,W bilinearly."""
    x = ins["X"][0]
    oh = attrs["out_h"]
    ow = attrs["out_w"]
    n, c = x.shape[0], x.shape[1]
    out = jax.image.resize(x, (n, c, oh, ow), method="bilinear")
    return {"Out": out}


@register_op("pad_constant_like")
def _pad_constant_like(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, pads, constant_values=attrs.get("pad_value", 0.0))}


@register_op("spp")
def _spp(ctx, ins, attrs):
    """spp_op: spatial pyramid pooling — concat of pyramid_height levels."""
    x = ins["X"][0]
    levels = attrs.get("pyramid_height", 3)
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for lv in range(levels):
        bins = 2 ** lv
        kh, kw = -(-h // bins), -(-w // bins)
        sh, sw = kh, kw
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        o = _pool2d_core(x, ptype, (kh, kw), (sh, sw), (ph, pw), False, False)
        outs.append(o.reshape(n, -1))
    return {"Out": jnp.concatenate(outs, axis=1)}


@register_op("im2sequence", "block_expand")
def _im2sequence(ctx, ins, attrs):
    """block_expand (v1 BlockExpandLayer): image patches -> sequence."""
    x = ins["X"][0]
    kh, kw = _pair(attrs.get("kernels", attrs.get("block", [1, 1])))
    sh, sw = _pair(attrs.get("strides", [1, 1]))
    ph, pw = _pair(attrs.get("paddings", [0, 0]))
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n, ckk, oh, ow = patches.shape
    out = patches.reshape(n, ckk, oh * ow).transpose(0, 2, 1)
    return {"Out": out}



# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer) — the InferShape analogs
# of conv_op.cc / pool_op.cc / batch_norm_op.cc etc.
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import (ShapeError, VarInfo,  # noqa: E402
                                    conv_out_dim, dim_ok, first, mirror,
                                    same_as)
from ..core.registry import register_shape_fn  # noqa: E402

register_shape_fn("softmax", "log_softmax")(same_as("X"))
register_shape_fn("pad_constant_like")(same_as("X"))


@register_shape_fn("conv2d", "depthwise_conv2d")
def _conv2d_shape(op, ins, attrs):
    x, w = first(ins, "Input"), first(ins, "Filter")
    if x.shape is None or w.shape is None:
        return {"Output": VarInfo(None, x.dtype)}
    if len(x.shape) != 4 or len(w.shape) != 4:
        raise ShapeError(
            f"conv2d: Input/Filter must be rank-4, got {list(x.shape)} / "
            f"{list(w.shape)}")
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1) or 1)
    n, c, h, wd = x.shape
    o, cg, kh, kw = w.shape
    if c >= 0 and cg >= 0 and c != cg * groups:
        raise ShapeError(
            f"conv2d: input channels {c} != Filter C/g {cg} * groups "
            f"{groups}")
    if o >= 0 and groups > 1 and o % groups:
        raise ShapeError(
            f"conv2d: output channels {o} not divisible by groups {groups}")
    oh = conv_out_dim(h, kh, pads[0], strides[0], dil[0])
    ow = conv_out_dim(wd, kw, pads[1], strides[1], dil[1])
    return {"Output": VarInfo((n, o, oh, ow), x.dtype)}


@register_shape_fn("conv2d_transpose")
def _conv2d_transpose_shape(op, ins, attrs):
    x, w = first(ins, "Input"), first(ins, "Filter")
    if x.shape is None or w.shape is None:
        return {"Output": VarInfo(None, x.dtype)}
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    n, c, h, wd = x.shape
    ci, co, kh, kw = w.shape
    if c >= 0 and ci >= 0 and c != ci:
        raise ShapeError(
            f"conv2d_transpose: input channels {c} != Filter C_in {ci}")

    def _out(size, k, p, s, d):
        if size < 0:
            return -1
        return (size - 1) * s - 2 * p + d * (k - 1) + 1

    return {"Output": VarInfo(
        (n, co, _out(h, kh, pads[0], strides[0], dil[0]),
         _out(wd, kw, pads[1], strides[1], dil[1])), x.dtype)}


@register_shape_fn("conv3d")
def _conv3d_shape(op, ins, attrs):
    x, w = first(ins, "Input"), first(ins, "Filter")
    if x.shape is None or w.shape is None:
        return {"Output": VarInfo(None, x.dtype)}
    strides = tuple(attrs.get("strides", [1, 1, 1]))
    pads = tuple(attrs.get("paddings", [0, 0, 0]))
    dil = tuple(attrs.get("dilations", [1, 1, 1]))
    n, c = x.shape[0], x.shape[1]
    o = w.shape[0]
    dims = tuple(conv_out_dim(x.shape[2 + i], w.shape[2 + i], pads[i],
                              strides[i], dil[i]) for i in range(3))
    return {"Output": VarInfo((n, o) + dims, x.dtype)}


def _pool2d_out_shape(x, attrs):
    if attrs.get("global_pooling", False):
        return x.shape[:2] + (1, 1)
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    ceil = attrs.get("ceil_mode", False)
    return x.shape[:2] + (
        conv_out_dim(x.shape[2], ksize[0], pads[0], strides[0],
                     ceil_mode=ceil),
        conv_out_dim(x.shape[3], ksize[1], pads[1], strides[1],
                     ceil_mode=ceil))


@register_shape_fn("pool2d")
def _pool2d_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    if len(x.shape) != 4:
        raise ShapeError(f"pool2d: X must be rank-4, got {list(x.shape)}")
    return {"Out": x.with_shape(_pool2d_out_shape(x, attrs))}


@register_shape_fn("max_pool2d_with_index", "pool2d_with_index")
def _pool2d_with_index_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x, "Mask": VarInfo(None, "int64")}
    a = dict(attrs)
    a.setdefault("strides", a.get("ksize", [2, 2]))
    # the patch-extraction lowering always floors, unlike _pool2d_core
    a["ceil_mode"] = False
    shape = _pool2d_out_shape(x, a)
    return {"Out": x.with_shape(shape), "Mask": VarInfo(shape, "int64")}


@register_shape_fn("pool3d")
def _pool3d_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    if attrs.get("global_pooling", False):
        return {"Out": x.with_shape(x.shape[:2] + (1, 1, 1))}
    ks = list(attrs.get("ksize", [2, 2, 2]))
    strides = list(attrs.get("strides", ks))
    pads = list(attrs.get("paddings", [0, 0, 0]))
    dims = tuple(conv_out_dim(x.shape[2 + i], ks[i], pads[i], strides[i])
                 for i in range(3))
    return {"Out": x.with_shape(x.shape[:2] + dims)}


@register_shape_fn("unpool")
def _unpool_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    n, c, oh, ow = x.shape
    if "unpool_size" in attrs:
        uh, uw = attrs["unpool_size"]
    else:
        uh, uw = attrs["ksize"][0] * oh, attrs["ksize"][1] * ow
    return {"Out": x.with_shape((n, c, uh, uw))}


@register_shape_fn("batch_norm")
def _batch_norm_shape(op, ins, attrs):
    x = first(ins, "X")
    scale = first(ins, "Scale")
    if x.shape is not None and scale.shape is not None and \
            len(x.shape) >= 2 and not dim_ok(x.shape[1], scale.shape[-1]):
        raise ShapeError(
            f"batch_norm: channel dim {x.shape[1]} != Scale size "
            f"{scale.shape[-1]}")
    res = {"Y": x}
    res.update(mirror({"MeanOut": "Mean", "VarianceOut": "Variance",
                       "SavedMean": "Mean", "SavedVariance": "Variance"})(
        op, ins, attrs))
    return res


@register_shape_fn("layer_norm")
def _layer_norm_shape(op, ins, attrs):
    x = first(ins, "X")
    res = {"Y": x}
    if x.shape is not None:
        begin = attrs.get("begin_norm_axis", 1)
        stat = VarInfo(x.shape[:begin], x.dtype)
        res["Mean"] = stat
        res["Variance"] = stat
    return res


@register_shape_fn("cross_entropy")
def _cross_entropy_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Y": x}
    return {"Y": x.with_shape(x.shape[:-1] + (1,))}


@register_shape_fn("softmax_with_cross_entropy")
def _softmax_ce_shape(op, ins, attrs):
    logits, label = first(ins, "Logits"), first(ins, "Label")
    if logits.shape is None:
        return {"Softmax": logits, "Loss": VarInfo(None, logits.dtype)}
    if label.shape is not None and not attrs.get("soft_label", False):
        if not dim_ok(label.shape[0], logits.shape[0]):
            raise ShapeError(
                f"softmax_with_cross_entropy: batch mismatch Logits "
                f"{list(logits.shape)} vs Label {list(label.shape)}")
    return {"Softmax": logits,
            "Loss": logits.with_shape(logits.shape[:-1] + (1,))}


@register_shape_fn("dropout")
def _dropout_shape(op, ins, attrs):
    x = first(ins, "X")
    return {"Out": x, "Mask": x}


@register_shape_fn("lrn")
def _lrn_shape(op, ins, attrs):
    x = first(ins, "X")
    return {"Out": x, "MidOut": x}


@register_shape_fn("maxout")
def _maxout_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    g = attrs["groups"]
    n, c, h, w = x.shape
    if c >= 0 and c % g:
        raise ShapeError(f"maxout: channels {c} not divisible by groups {g}")
    return {"Out": x.with_shape((n, -1 if c < 0 else c // g, h, w))}


@register_shape_fn("bilinear_interp")
def _bilinear_interp_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    return {"Out": x.with_shape(x.shape[:2] + (attrs["out_h"],
                                               attrs["out_w"]))}


@register_shape_fn("spp")
def _spp_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    n, c = x.shape[0], x.shape[1]
    bins = sum(4 ** lv for lv in range(attrs.get("pyramid_height", 3)))
    return {"Out": x.with_shape((n, -1 if c < 0 else c * bins))}


@register_shape_fn("im2sequence", "block_expand")
def _im2sequence_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    kh, kw = _pair(attrs.get("kernels", attrs.get("block", [1, 1])))
    sh, sw = _pair(attrs.get("strides", [1, 1]))
    ph, pw = _pair(attrs.get("paddings", [0, 0]))
    n, c, h, wd = x.shape
    oh = conv_out_dim(h, kh, ph, sh)
    ow = conv_out_dim(wd, kw, pw, sw)
    t = -1 if oh < 0 or ow < 0 else oh * ow
    d = -1 if c < 0 else c * kh * kw
    return {"Out": x.with_shape((n, t, d))}


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop): convs follow
# batch/output-channel sharding, normalizations and pointwise heads are
# shape-preserving, losses keep the batch dim only.
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import (shard_batch_only,  # noqa: E402
                                   shard_conv2d, shard_same_as)
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("conv2d", "depthwise_conv2d")(shard_conv2d())
register_shard_fn("softmax", "log_softmax", "lrn")(shard_same_as("X"))
register_shard_fn("dropout")(shard_same_as("X", also=("Mask",)))
register_shard_fn("cross_entropy")(shard_batch_only("X", out="Y"))


@register_shard_fn("pool2d", "pool3d", "max_pool2d_with_index",
                   "pool2d_with_index")
def _pool_shard(op, ins, attrs):
    from ..analysis.shard_prop import ShardConflict, first_in
    x = first_in(ins, "X")
    if x.spec is None:
        return {}
    if any(x.entry(i) for i in range(2, len(x.spec))):
        raise ShardConflict(
            "pooling input spatially sharded: halo exchange required")
    spec = (x.entry(0), x.entry(1)) + (None,) * (len(x.spec) - 2)
    res = {"Out": spec}
    if op.outputs.get("Mask"):
        res["Mask"] = spec
    return res


@register_shard_fn("batch_norm")
def _batch_norm_shard(op, ins, attrs):
    from ..analysis.shard_prop import first_in
    x = first_in(ins, "X")
    res = {}
    if x.spec is not None:
        res["Y"] = x.spec
    for out_slot, in_slot in (("MeanOut", "Mean"),
                              ("VarianceOut", "Variance"),
                              ("SavedMean", "Mean"),
                              ("SavedVariance", "Variance")):
        v = first_in(ins, in_slot)
        if op.outputs.get(out_slot) and v.spec is not None:
            res[out_slot] = v.spec
    return res


@register_shard_fn("layer_norm")
def _layer_norm_shard(op, ins, attrs):
    from ..analysis.shard_prop import first_in
    x = first_in(ins, "X")
    if x.spec is None:
        return {}
    begin = attrs.get("begin_norm_axis", 1)
    res = {"Y": x.spec}
    if op.outputs.get("Mean"):
        res["Mean"] = x.spec[:begin]
    if op.outputs.get("Variance"):
        res["Variance"] = x.spec[:begin]
    return res


@register_shard_fn("softmax_with_cross_entropy")
def _softmax_ce_shard(op, ins, attrs):
    from ..analysis.shard_prop import first_in
    logits = first_in(ins, "Logits")
    if logits.spec is None:
        return {}
    return {"Softmax": logits.spec, "Loss": (logits.entry(0), None)}
