"""Math / elementwise / reduction / comparison op lowerings.

Covers the reference's math category (SURVEY §2.2: elementwise_op.h, mul_op,
matmul_op.cc, sum_op, scale_op, cast_op, clip_op, clip_by_norm_op, sign_op,
logical_op, compare_op, reduce_op.cc) as jnp/lax lowerings.  Gradients come
from jax.vjp — no *_grad ops exist.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core.types import convert_dtype


# ---------------------------------------------------------------------------
# elementwise binary with fluid broadcast semantics
# (reference: elementwise_op.h trailing-axis broadcast: Y's shape must match a
# contiguous run of X's dims starting at `axis`)
# ---------------------------------------------------------------------------
def _bcast(x, y, axis: int):
    if x.shape == y.shape or axis in (-1, None):
        return x, y
    if y.ndim > x.ndim:
        raise ValueError(f"elementwise: y rank {y.ndim} > x rank {x.ndim}")
    trailing = x.ndim - axis - y.ndim
    if trailing < 0:
        raise ValueError(f"elementwise: bad axis {axis} for shapes "
                         f"{x.shape} {y.shape}")
    y = y.reshape(y.shape + (1,) * trailing)
    return x, y


def _elementwise(fn):
    def impl(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        x, y = _bcast(x, y, attrs.get("axis", -1))
        return {"Out": fn(x, y)}
    return impl


register_op("elementwise_add")(_elementwise(jnp.add))
register_op("elementwise_sub")(_elementwise(jnp.subtract))
register_op("elementwise_mul")(_elementwise(jnp.multiply))
register_op("elementwise_div")(_elementwise(jnp.divide))
register_op("elementwise_pow")(_elementwise(jnp.power))
register_op("elementwise_max")(_elementwise(jnp.maximum))
register_op("elementwise_min")(_elementwise(jnp.minimum))
register_op("elementwise_mod")(_elementwise(jnp.mod))


@register_op("mul")
def _mul(ctx, ins, attrs):
    """fluid mul_op (mul_op.cc): flatten x/y to 2-D then matmul — the FC
    primitive.  Kept batched + bf16-friendly so it lands on the MXU."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((_prod(xs[:xn]), _prod(xs[xn:])))
    y2 = y.reshape((_prod(ys[:yn]), _prod(ys[yn:])))
    out = jnp.matmul(x2, y2)
    return {"Out": out.reshape(xs[:xn] + ys[yn:])}


def _prod(t):
    # no int() cast: dims may be symbolic (jax.export shape polymorphism)
    p = 1
    for v in t:
        p *= v
    return p


@register_op("matmul")
def _matmul(ctx, ins, attrs):
    """matmul_op.cc semantics: optional transposes, batched stacks."""
    x, y = ins["X"][0], ins["Y"][0]
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


@register_op("sum")
def _sum(ctx, ins, attrs):
    """sum_op: add N tensors (used to merge multi-consumer grads)."""
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("scale")
def _scale(ctx, ins, attrs):
    x = ins["X"][0]
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": x * s + b}
    return {"Out": (x + b) * s}


@register_op("minus")
def _minus(ctx, ins, attrs):
    return {"Out": ins["X"][0] - ins["Y"][0]}


@register_op("cast")
def _cast(ctx, ins, attrs):
    dt = convert_dtype(attrs.get("out_dtype", attrs.get("dtype", "float32")))
    return {"Out": ins["X"][0].astype(dt)}


@register_op("clip")
def _clip(ctx, ins, attrs):
    return {"Out": jnp.clip(ins["X"][0], attrs["min"], attrs["max"])}


@register_op("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": x * scale.astype(x.dtype)}


@register_op("sign")
def _sign(ctx, ins, attrs):
    return {"Out": jnp.sign(ins["X"][0])}


@register_op("pow")
def _pow(ctx, ins, attrs):
    return {"Out": jnp.power(ins["X"][0], attrs.get("factor", 1.0))}


# -- logical / comparison ----------------------------------------------------
def _logical(fn, unary=False):
    def impl(ctx, ins, attrs):
        if unary:
            return {"Out": fn(ins["X"][0].astype(bool))}
        return {"Out": fn(ins["X"][0].astype(bool), ins["Y"][0].astype(bool))}
    return impl


register_op("logical_and")(_logical(jnp.logical_and))
register_op("logical_or")(_logical(jnp.logical_or))
register_op("logical_xor")(_logical(jnp.logical_xor))
register_op("logical_not")(_logical(jnp.logical_not, unary=True))


def _compare(fn):
    def impl(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        x, y = _bcast(x, y, attrs.get("axis", -1))
        return {"Out": fn(x, y)}
    return impl


register_op("equal")(_compare(jnp.equal))
register_op("not_equal")(_compare(jnp.not_equal))
register_op("less_than")(_compare(jnp.less))
register_op("less_equal")(_compare(jnp.less_equal))
register_op("greater_than")(_compare(jnp.greater))
register_op("greater_equal")(_compare(jnp.greater_equal))


# -- reductions (reduce_op.cc: dim/keep_dim/reduce_all attrs) ---------------
def _reduce(fn):
    def impl(ctx, ins, attrs):
        x = ins["X"][0]
        if attrs.get("reduce_all", False):
            axis = None
        else:
            dim = attrs.get("dim", [0])
            axis = tuple(dim) if isinstance(dim, (list, tuple)) else (int(dim),)
            axis = tuple(d % x.ndim for d in axis)
        keep = attrs.get("keep_dim", False)
        return {"Out": fn(x, axis=axis, keepdims=keep)}
    return impl


register_op("reduce_sum")(_reduce(jnp.sum))
register_op("reduce_mean")(_reduce(jnp.mean))
register_op("reduce_max")(_reduce(jnp.max))
register_op("reduce_min")(_reduce(jnp.min))
register_op("reduce_prod")(_reduce(jnp.prod))


@register_op("mean")
def _mean(ctx, ins, attrs):
    """mean_op: full reduction to scalar (loss averaging)."""
    return {"Out": jnp.mean(ins["X"][0])}


@register_op("increment")
def _increment(ctx, ins, attrs):
    return {"Out": ins["X"][0] + jnp.asarray(attrs.get("step", 1.0),
                                             ins["X"][0].dtype)}


@register_op("abs_diff", "squared_difference")
def _sq_diff(ctx, ins, attrs):
    d = ins["X"][0] - ins["Y"][0]
    return {"Out": d * d}


@register_op("cumsum")
def _cumsum(ctx, ins, attrs):
    return {"Out": jnp.cumsum(ins["X"][0], axis=attrs.get("axis", -1))}


@register_op("isfinite")
def _isfinite(ctx, ins, attrs):
    return {"Out": jnp.all(jnp.isfinite(ins["X"][0]))}


@register_op("l2_normalize", "norm")
def _l2_normalize(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-12)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    return {"Out": x / jnp.maximum(norm, eps)}


# ---------------------------------------------------------------------------
# v1 attention-support / CTR ops (gserver layers without fluid successors)
# ---------------------------------------------------------------------------
@register_op("conv_shift")
def _conv_shift(ctx, ins, attrs):
    """ConvShiftLayer.cpp: circular correlation (NTM attention shift).
    X [B, M], Y [B, N] (N odd) -> Out[b, i] = sum_j X[b, (i + j - N//2) % M]
    * Y[b, j]."""
    x, y = ins["X"][0], ins["Y"][0]
    B, M = x.shape
    N = y.shape[1]
    half = N // 2
    cols = []
    for j in range(N):
        cols.append(jnp.roll(x, half - j, axis=1) * y[:, j:j + 1])
    return {"Out": sum(cols)}


@register_op("interpolation")
def _interpolation(ctx, ins, attrs):
    """InterpolationLayer.cpp: out = w*X + (1-w)*Y with per-row w [B,1]."""
    w, x, y = ins["W"][0], ins["X"][0], ins["Y"][0]
    w = w.reshape((-1,) + (1,) * (x.ndim - 1))
    return {"Out": w * x + (1.0 - w) * y}


@register_op("outer_prod")
def _outer_prod(ctx, ins, attrs):
    """OuterProdLayer.cpp: per-row outer product, flattened [B, M*N]."""
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.einsum("bm,bn->bmn", x, y).reshape(x.shape[0], -1)}


@register_op("factorization_machine")
def _factorization_machine(ctx, ins, attrs):
    """FactorizationMachineLayer.cpp second-order term:
    0.5 * sum_k((X V)_k^2 - (X^2 V^2)_k) -> [B, 1]."""
    x, v = ins["X"][0], ins["V"][0]
    xv = x @ v
    x2v2 = (x * x) @ (v * v)
    return {"Out": 0.5 * jnp.sum(xv * xv - x2v2, axis=1, keepdims=True)}


@register_op("scale_sub_region")
def _scale_sub_region(ctx, ins, attrs):
    """ScaleSubRegionLayer.cpp: scale value inside per-sample [C,H,W]
    index boxes (Indices [B,6] = c1,c2,h1,h2,w1,w2, 1-based inclusive)."""
    x, idx = ins["X"][0], ins["Indices"][0].astype(jnp.int32)
    value = attrs.get("value", 1.0)
    B, C, H, W = x.shape
    c = jnp.arange(C)[None, :, None, None]
    h = jnp.arange(H)[None, None, :, None]
    w = jnp.arange(W)[None, None, None, :]
    i = idx.reshape(B, 6, 1, 1, 1)
    mask = ((c >= i[:, 0] - 1) & (c <= i[:, 1] - 1) &
            (h >= i[:, 2] - 1) & (h <= i[:, 3] - 1) &
            (w >= i[:, 4] - 1) & (w <= i[:, 5] - 1))
    return {"Out": jnp.where(mask, x * value, x)}


# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer) — the InferShape analogs
# of elementwise_op.h / mul_op.cc / matmul_op.cc / reduce_op.cc.
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import (ShapeError, VarInfo, dim_ok,  # noqa: E402
                                    elementwise, first, prod_dims,
                                    reduce_rule, same_as,
                                    shapes_compatible, unify_dim)
from ..core.registry import register_shape_fn  # noqa: E402

register_shape_fn(
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_pow", "elementwise_max",
    "elementwise_min", "elementwise_mod",
)(elementwise())
register_shape_fn(
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal",
)(elementwise(dtype="bool"))
register_shape_fn("logical_and", "logical_or", "logical_xor")(
    elementwise(dtype="bool"))
register_shape_fn("logical_not")(same_as("X", dtype="bool"))
register_shape_fn(
    "scale", "minus", "clip", "clip_by_norm", "sign", "pow", "increment",
    "cumsum", "l2_normalize", "norm", "interpolation", "scale_sub_region",
)(same_as("X"))
register_shape_fn("abs_diff", "squared_difference")(elementwise())
register_shape_fn("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
                  "reduce_prod")(reduce_rule())


@register_shape_fn("mul")
def _mul_shape(op, ins, attrs):
    """mul_op.cc InferShape: flatten to 2-D at the num_col_dims splits and
    check the contraction."""
    x, y = first(ins, "X"), first(ins, "Y")
    if x.shape is None or y.shape is None:
        return {"Out": VarInfo(None, x.dtype)}
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    if not 0 < xn < len(x.shape) + 1 or not 0 < yn < len(y.shape) + 1:
        raise ShapeError(
            f"mul: num_col_dims ({xn}, {yn}) out of range for ranks "
            f"{len(x.shape)}, {len(y.shape)}")
    k1, k2 = prod_dims(x.shape[xn:]), prod_dims(y.shape[:yn])
    if not dim_ok(k1, k2):
        raise ShapeError(
            f"mul: contraction mismatch {list(x.shape)}[{xn}:] ({k1}) vs "
            f"{list(y.shape)}[:{yn}] ({k2})")
    return {"Out": VarInfo(x.shape[:xn] + y.shape[yn:], x.dtype)}


@register_shape_fn("matmul")
def _matmul_shape(op, ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    if x.shape is None or y.shape is None:
        return {"Out": VarInfo(None, x.dtype)}
    xs, ys = list(x.shape), list(y.shape)
    if len(xs) < 1 or len(ys) < 1:
        raise ShapeError("matmul: operands must have rank >= 1")
    if attrs.get("transpose_X", False) and len(xs) > 1:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if attrs.get("transpose_Y", False) and len(ys) > 1:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    if len(xs) == 1:
        xs = [1] + xs
    if len(ys) == 1:
        ys = ys + [1]
    if not dim_ok(xs[-1], ys[-2]):
        raise ShapeError(
            f"matmul: contraction mismatch {list(x.shape)} @ "
            f"{list(y.shape)} ({xs[-1]} vs {ys[-2]})")
    batch = []
    for i in range(2, max(len(xs), len(ys)))[::-1]:
        bx = xs[-i - 1] if i < len(xs) else None
        by = ys[-i - 1] if i < len(ys) else None
        if bx is not None and by is not None:
            if not (dim_ok(bx, by) or bx == 1 or by == 1):
                raise ShapeError(
                    f"matmul: batch dims mismatch {list(x.shape)} vs "
                    f"{list(y.shape)}")
            # broadcast with -1-safe semantics: a 1 yields the other
            # side verbatim (even if unknown); -1 never collapses to 1
            if bx == 1:
                batch.append(by)
            elif by == 1:
                batch.append(bx)
            else:
                batch.append(unify_dim(bx, by))
        else:
            batch.append(bx if bx is not None else by)
    shape = tuple(batch) + (xs[-2], ys[-1])
    if x.ndim == 1:
        shape = shape[:-2] + (shape[-1],)
    elif y.ndim == 1:
        shape = shape[:-1]
    return {"Out": VarInfo(shape, x.dtype)}


@register_shape_fn("sum")
def _sum_shape(op, ins, attrs):
    """sum_op: every input must carry the same shape."""
    xs = ins.get("X", [])
    out = xs[0] if xs else None
    for x in xs[1:]:
        if not shapes_compatible(out.shape, x.shape):
            raise ShapeError(
                f"sum: operand shapes differ: {list(out.shape)} vs "
                f"{list(x.shape)}")
    return {"Out": out}


@register_shape_fn("mean")
def _mean_shape(op, ins, attrs):
    x = first(ins, "X")
    return {"Out": x.with_shape(())}


@register_shape_fn("cast")
def _cast_shape(op, ins, attrs):
    x = first(ins, "X")
    return {"Out": x.with_dtype(
        attrs.get("out_dtype", attrs.get("dtype", "float32")))}


@register_shape_fn("isfinite")
def _isfinite_shape(op, ins, attrs):
    return {"Out": VarInfo((), "bool")}


@register_shape_fn("conv_shift")
def _conv_shift_shape(op, ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    if x.shape is not None and y.shape is not None and \
            len(y.shape) == 2 and y.shape[1] >= 0 and y.shape[1] % 2 == 0:
        raise ShapeError(f"conv_shift: Y width must be odd, got "
                         f"{y.shape[1]}")
    return {"Out": x}


@register_shape_fn("outer_prod")
def _outer_prod_shape(op, ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    if x.shape is None or y.shape is None:
        return {"Out": VarInfo(None, x.dtype)}
    if len(x.shape) != 2 or len(y.shape) != 2:
        raise ShapeError("outer_prod: X and Y must be rank-2")
    m, n = x.shape[1], y.shape[1]
    return {"Out": VarInfo((x.shape[0], -1 if m < 0 or n < 0 else m * n),
                           x.dtype)}


@register_shape_fn("factorization_machine")
def _fm_shape(op, ins, attrs):
    x, v = first(ins, "X"), first(ins, "V")
    if x.shape is not None and v.shape is not None and \
            not dim_ok(x.shape[-1], v.shape[0]):
        raise ShapeError(
            f"factorization_machine: X feature dim {x.shape[-1]} vs V rows "
            f"{v.shape[0]}")
    b = x.shape[0] if x.shape is not None else -1
    return {"Out": VarInfo((b, 1), x.dtype)}


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop).  mul carries the
# Megatron contract: row dims follow X, col dims follow Y, and a sharded
# contraction must match on both sides (the row-parallel all-reduce).
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import (merge_specs,  # noqa: E402
                                   shard_elementwise, shard_matmul,
                                   shard_mul, shard_reduce,
                                   shard_replicated, shard_same_as)
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn(
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_pow", "elementwise_max",
    "elementwise_min", "elementwise_mod", "equal", "not_equal",
    "less_than", "less_equal", "greater_than", "greater_equal",
    "logical_and", "logical_or", "logical_xor", "abs_diff",
    "squared_difference",
)(shard_elementwise())
register_shard_fn(
    "logical_not", "scale", "minus", "clip", "clip_by_norm", "sign",
    "pow", "increment", "cumsum", "l2_normalize", "norm",
    "interpolation", "scale_sub_region", "cast",
)(shard_same_as("X"))
register_shard_fn("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
                  "reduce_prod")(shard_reduce())
register_shard_fn("mul")(shard_mul())
register_shard_fn("matmul")(shard_matmul())
register_shard_fn("mean", "isfinite")(shard_replicated("Out"))


@register_shard_fn("sum")
def _sum_shard(op, ins, attrs):
    spec = None
    for x in ins.get("X", []):
        spec = merge_specs(spec, x.spec, "sum operands")
    return {} if spec is None else {"Out": spec}
