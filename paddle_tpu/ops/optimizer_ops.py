"""Optimizer op lowerings.

The reference implements optimizers *as graph ops* taking param/grad/moments
as inputs and producing updated outputs (SURVEY §2.2 "Optimizers (as ops!)":
sgd_op, momentum_op, adam_op.cc/.h, adamax_op, adagrad_op, adadelta_op,
decayed_adagrad_op, rmsprop_op, ftrl_op, proximal_gd_op,
proximal_adagrad_op).  We keep that design: updates are pure functions inside
the compiled step, so the whole train step (fwd+bwd+update) is ONE XLA
computation with donated parameter buffers — no per-parameter kernel launches.

All moments are persistable scope vars created by paddle_tpu.optimizer
(the fluid optimizer.py accumulator pattern)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op, register_tunable

# Pre-registered Pallas expansion candidate (ROADMAP item 5): the
# optimizer step is pure memory traffic — every param/moment leaf is
# read and written once with trivial arithmetic — so XLA's per-op
# kernels pay one HBM round-trip per leaf per tensor.  The candidate is
# ONE fused Pallas kernel sweeping all leaves (flattened+concatenated
# views, one grid).  Declared pending-hardware so the first chip session
# measures it for free (`python -m paddle_tpu tune
# pallas/fused_optimizer_update`); the opprof 'XLA loses here' report
# references this rule id when optimizer-update op classes dominate a
# measured profile.
register_tunable(
    "pallas/fused_optimizer_update", side="device",
    space={"fused": (False, True), "block_elems": (4096, 8192, 16384)},
    default={"fused": False, "block_elems": 8192},
    description="fuse the per-leaf optimizer update ops (sgd/momentum/"
                "adam/... families) into one Pallas kernel over all "
                "param leaves; block_elems is the per-grid-step slab",
    pending_hardware=True,
    decision_rule="flip fused=True only when an on-chip paired A/B over "
                  "a real training step (benchmark/opprof.py workloads) "
                  "shows >= 1.10x median step time with >= 75% of pairs "
                  "favoring, AND the opprof per-op table attributes "
                  ">= 10% of measured step time to optimizer-update op "
                  "classes (otherwise the fusion cannot pay)")


def _lr(ins):
    return ins["LearningRate"][0].reshape(())


@register_op("sgd")
def _sgd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    return {"ParamOut": p - _lr(ins) * g}


@register_op("momentum")
def _momentum(ctx, ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = attrs.get("mu", 0.9)
    lr = _lr(ins)
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": p_out, "VelocityOut": v_out}


@register_op("adam")
def _adam(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, v = ins["Moment1"][0], ins["Moment2"][0]
    b1p = ins["Beta1Pow"][0].reshape(())
    b2p = ins["Beta2Pow"][0].reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    out = {"Beta1PowOut": (b1p * b1).reshape(1),
           "Beta2PowOut": (b2p * b2).reshape(1)}
    if attrs.get("lazy_mode") and "Rows" in ins:
        # adam_op.cc lazy_mode: touch only the rows the batch looked up.
        # The dense grad row already sums duplicate ids, so per-row values
        # are identical across duplicates and .at[ids].set is idempotent;
        # untouched rows keep stale moments (reference sparse semantics).
        ids = jnp.concatenate([i.reshape(-1) for i in ins["Rows"]])
        g_r = g[ids]
        m_r = b1 * m[ids] + (1 - b1) * g_r
        v_r = b2 * v[ids] + (1 - b2) * jnp.square(g_r)
        p_r = p[ids] - lr_t * m_r / (jnp.sqrt(v_r) + eps)
        mode = "promise_in_bounds"
        out.update({
            "ParamOut": p.at[ids].set(p_r.astype(p.dtype), mode=mode),
            "Moment1Out": m.at[ids].set(m_r.astype(m.dtype), mode=mode),
            "Moment2Out": v.at[ids].set(v_r.astype(v.dtype), mode=mode)})
        return out
    m_out = b1 * m + (1 - b1) * g
    v_out = b2 * v + (1 - b2) * jnp.square(g)
    p_out = p - lr_t * m_out / (jnp.sqrt(v_out) + eps)
    out.update({"ParamOut": p_out, "Moment1Out": m_out,
                "Moment2Out": v_out})
    return out


@register_op("adamax")
def _adamax(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, inf = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0].reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf, jnp.abs(g))
    p_out = p - (lr / (1 - b1p)) * m_out / (inf_out + eps)
    return {"ParamOut": p_out, "MomentOut": m_out, "InfNormOut": inf_out,
            "Beta1PowOut": (b1p * b1).reshape(1)}


@register_op("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    eps = attrs.get("epsilon", 1e-6)
    m_out = mom + jnp.square(g)
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("adadelta")
def _adadelta(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    avg_sq_g, avg_sq_u = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    upd = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(upd)
    return {"ParamOut": p + upd, "AvgSquaredGradOut": g2,
            "AvgSquaredUpdateOut": u2}


@register_op("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_out = decay * mom + (1 - decay) * jnp.square(g)
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    rho = attrs.get("decay", 0.95)
    mu = attrs.get("momentum", 0.0)
    eps = attrs.get("epsilon", 1e-6)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    mom_out = mu * mom + _lr(ins) * g / jnp.sqrt(ms_out + eps)
    return {"ParamOut": p - mom_out, "MomentOut": mom_out,
            "MeanSquareOut": ms_out}


@register_op("ftrl")
def _ftrl(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    sq_acc, lin_acc = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    lr = _lr(ins)
    new_sq = sq_acc + jnp.square(g)
    sigma = (jnp.power(new_sq, -power) - jnp.power(sq_acc, -power)) / lr
    new_lin = lin_acc + g - sigma * p
    denom = jnp.power(new_sq, -power) / lr + 2 * l2
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    p_out = pre / denom
    return {"ParamOut": p_out, "SquaredAccumOut": new_sq,
            "LinearAccumOut": new_lin}


@register_op("proximal_gd")
def _proximal_gd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr = _lr(ins)
    prox = p - lr * g
    if l1 > 0:
        prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
    return {"ParamOut": prox / (1.0 + lr * l2)}


@register_op("proximal_adagrad")
def _proximal_adagrad(ctx, ins, attrs):
    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr = _lr(ins)
    m_out = mom + jnp.square(g)
    eff_lr = lr / jnp.sqrt(m_out)
    prox = p - eff_lr * g
    if l1 > 0:
        prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - eff_lr * l1, 0.0)
    return {"ParamOut": prox / (1.0 + eff_lr * l2), "MomentOut": m_out}


# ---------------------------------------------------------------------------
# Static shape/dtype rules: every optimizer op mirrors its state inputs to
# the matching *Out slots (the reference's Param/Grad same-dims CHECKs in
# sgd_op.cc etc. become an explicit Param-vs-Grad shape check).
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import (ShapeError, first, mirror,  # noqa: E402
                                    shapes_compatible)
from ..core.registry import register_shape_fn  # noqa: E402


def _opt_rule(mapping):
    base = mirror(mapping)

    def rule(op, ins, attrs):
        p, g = first(ins, "Param"), first(ins, "Grad")
        if not shapes_compatible(p.shape, g.shape):
            raise ShapeError(
                f"Param {list(p.shape)} vs Grad {list(g.shape)} dims differ")
        return base(op, ins, attrs)

    return rule


register_shape_fn("sgd")(_opt_rule({"ParamOut": "Param"}))
register_shape_fn("momentum")(_opt_rule(
    {"ParamOut": "Param", "VelocityOut": "Velocity"}))
register_shape_fn("adam")(_opt_rule(
    {"ParamOut": "Param", "Moment1Out": "Moment1", "Moment2Out": "Moment2",
     "Beta1PowOut": "Beta1Pow", "Beta2PowOut": "Beta2Pow"}))
register_shape_fn("adamax")(_opt_rule(
    {"ParamOut": "Param", "MomentOut": "Moment", "InfNormOut": "InfNorm",
     "Beta1PowOut": "Beta1Pow"}))
register_shape_fn("adagrad", "decayed_adagrad", "proximal_adagrad")(
    _opt_rule({"ParamOut": "Param", "MomentOut": "Moment"}))
register_shape_fn("adadelta")(_opt_rule(
    {"ParamOut": "Param", "AvgSquaredGradOut": "AvgSquaredGrad",
     "AvgSquaredUpdateOut": "AvgSquaredUpdate"}))
register_shape_fn("rmsprop")(_opt_rule(
    {"ParamOut": "Param", "MomentOut": "Moment",
     "MeanSquareOut": "MeanSquare"}))
register_shape_fn("ftrl")(_opt_rule(
    {"ParamOut": "Param", "SquaredAccumOut": "SquaredAccumulator",
     "LinearAccumOut": "LinearAccumulator"}))
register_shape_fn("proximal_gd")(_opt_rule({"ParamOut": "Param"}))


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop): every optimizer op
# keeps its state on the parameter's sharding (the dp-reduced gradient
# arrives in the param's layout; accumulators ride along), with the
# Param-vs-Grad merge surfacing layout mismatches as PT041.
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import shard_mirror  # noqa: E402
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("sgd", "proximal_gd")(shard_mirror(
    {"ParamOut": "Param"}, check_grad=True))
register_shard_fn("momentum")(shard_mirror(
    {"ParamOut": "Param", "VelocityOut": "Velocity"}, check_grad=True))
register_shard_fn("adam")(shard_mirror(
    {"ParamOut": "Param", "Moment1Out": "Moment1",
     "Moment2Out": "Moment2", "Beta1PowOut": "Beta1Pow",
     "Beta2PowOut": "Beta2Pow"}, check_grad=True))
register_shard_fn("adamax")(shard_mirror(
    {"ParamOut": "Param", "MomentOut": "Moment", "InfNormOut": "InfNorm",
     "Beta1PowOut": "Beta1Pow"}, check_grad=True))
register_shard_fn("adagrad", "decayed_adagrad", "proximal_adagrad")(
    shard_mirror({"ParamOut": "Param", "MomentOut": "Moment"},
                 check_grad=True))
register_shard_fn("adadelta")(shard_mirror(
    {"ParamOut": "Param", "AvgSquaredGradOut": "AvgSquaredGrad",
     "AvgSquaredUpdateOut": "AvgSquaredUpdate"}, check_grad=True))
register_shard_fn("rmsprop")(shard_mirror(
    {"ParamOut": "Param", "MomentOut": "Moment",
     "MeanSquareOut": "MeanSquare"}, check_grad=True))
register_shard_fn("ftrl")(shard_mirror(
    {"ParamOut": "Param", "SquaredAccumOut": "SquaredAccumulator",
     "LinearAccumOut": "LinearAccumulator"}, check_grad=True))
