"""Sequence op lowerings over the padded+lengths representation.

The reference stores variable-length sequences padding-free via LoD offsets
(lod_tensor.h:34-83; v1 Argument.sequenceStartPositions, Argument.h:84-90) and
reorders into time-major shrinking batches (SequenceToBatch.cpp,
lod_rank_table_op.cc).  XLA needs static shapes, so the TPU-native design is:

    value:  [B, T_max, ...] padded dense tensor
    length: [B] int32 companion (var ``name@LEN`` threaded by the executor)

Every sequence op masks by length.  This trades padding FLOPs for MXU-sized
static matmuls — the standard TPU bargain — and buckets in the data feeder
keep T_max tight (see paddle_tpu.reader).

Fused RNNs (``lstm``/``gru``, reference lstm_op.cc + math/lstm_compute,
gru_op) are lax.scan loops whose per-step math is batched matmul — XLA fuses
the gate nonlinearities; the recurrent matmul rides the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op, register_tunable

# Pre-registered Pallas expansion candidate (ROADMAP item 5): the lod
# sequence family (sequence_expand/pool/concat/slice/pad/unpad, ...) is
# gather/scatter over padded [B, T, ...] layouts — XLA lowers the masked
# forms to select+reduce chains that re-read the padded tensor per op.
# The candidate is hand-written Pallas gather/scatter kernels indexed by
# the @LEN companions directly.  Declared pending-hardware so the first
# chip session measures it for free (`python -m paddle_tpu tune
# pallas/lod_gather_scatter`); the opprof 'XLA loses here' report
# references this rule id when lod sequence op classes dominate a
# measured profile.
register_tunable(
    "pallas/lod_gather_scatter", side="device",
    space={"kernel": ("xla", "pallas"), "block_rows": (128, 256, 512)},
    default={"kernel": "xla", "block_rows": 256},
    description="route the lod gather/scatter sequence ops (sequence_"
                "expand/pool/concat/slice/pad/unpad families) through "
                "hand-written Pallas kernels indexed by @LEN instead of "
                "XLA's masked select+reduce lowering",
    pending_hardware=True,
    decision_rule="flip kernel=pallas only when an on-chip paired A/B "
                  "over a sequence-heavy step (benchmark/opprof.py lstm "
                  "workload) shows >= 1.15x median step time with "
                  ">= 75% of pairs favoring — the bar is higher than "
                  "the generic 1.10x because the masked-XLA form "
                  "co-fuses with neighbors and the kernel forfeits "
                  "that; AND the opprof per-op table attributes >= 10% "
                  "of measured step time to lod sequence op classes")


def _mask(lens, T, dtype=jnp.float32):
    """[B,T] validity mask from lengths."""
    return (jnp.arange(T)[None, :] < lens[:, None]).astype(dtype)


def _in_lens(ctx, slot="X", idx=0):
    name = ctx.op.inputs[slot][idx]
    lens = ctx.get_len(name)
    return lens


def _seq_lens_or_full(ctx, x, slot="X"):
    lens = _in_lens(ctx, slot)
    if lens is None:
        lens = jnp.full((x.shape[0],), x.shape[1], dtype=jnp.int32)
    return lens


@register_op("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    """sequence_pool_op: AVERAGE/SUM/SQRT/MAX/LAST/FIRST over time.

    Nested input ([B, S, T, ...] with an @LEN2 companion): LAST returns the
    last valid token of the last valid subsequence; FIRST the first token of
    the first subsequence — the level-0 aggregation of the reference's
    nested LoD."""
    x = ins["X"][0]                      # [B, T, ...]
    lens = _seq_lens_or_full(ctx, x)
    lens2 = ctx.get_len2(ctx.op.inputs["X"][0])
    if lens2 is not None:
        ptype_n = attrs.get("pooltype",
                            attrs.get("pool_type", "AVERAGE")).upper()
        B = x.shape[0]
        b_idx = jnp.arange(B)
        if ptype_n == "LAST":
            last_s = jnp.maximum(lens - 1, 0)                # [B]
            il = jnp.take_along_axis(lens2, last_s[:, None],
                                     axis=1)[:, 0]           # [B]
            return {"Out": x[b_idx, last_s, jnp.maximum(il - 1, 0)]}
        if ptype_n == "FIRST":
            return {"Out": x[:, 0, 0]}
        raise NotImplementedError(
            f"sequence_pool {ptype_n} over nested sequences: only "
            f"LAST/FIRST are defined (matching last_seq/first_seq use)")
    ptype = attrs.get("pooltype", attrs.get("pool_type", "AVERAGE")).upper()
    if ptype == "AVG":                 # v1 AvgPooling spelling
        ptype = "AVERAGE"
    T = x.shape[1]
    m = _mask(lens, T, x.dtype).reshape((x.shape[0], T) + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / jnp.maximum(
            lens.astype(x.dtype), 1).reshape((-1,) + (1,) * (x.ndim - 2))
    elif ptype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(jnp.maximum(
            lens.astype(x.dtype), 1)).reshape((-1,) + (1,) * (x.ndim - 2))
    elif ptype == "MAX":
        neg = jnp.asarray(-3.4e38, x.dtype)
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype(jnp.int32)
            .repeat(1, axis=1), axis=1).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return {"Out": out}


@register_op("sequence_softmax")
def _sequence_softmax(ctx, ins, attrs):
    """softmax over the time dim, masked to each sequence's length."""
    x = ins["X"][0]                      # [B, T] or [B, T, 1]
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    v = x.squeeze(-1) if squeeze else x
    lens = _seq_lens_or_full(ctx, v)
    m = _mask(lens, v.shape[1], jnp.bool_)
    z = jnp.where(m, v, -3.4e38)
    out = jax.nn.softmax(z, axis=1)
    out = out * m.astype(out.dtype)
    if squeeze:
        out = out[..., None]
    ctx.set_len(ctx.op.outputs["Out"][0], lens)
    return {"Out": out}


@register_op("sequence_expand", "sequence_expand_as")
def _sequence_expand(ctx, ins, attrs):
    """sequence_expand_op: broadcast one row per sequence along Y's time."""
    x, y = ins["X"][0], ins["Y"][0]
    lens = _seq_lens_or_full(ctx, y, slot="Y")
    T = y.shape[1]
    if x.ndim == y.ndim:                  # already time-major: tile-nothing
        out = x
    else:                                 # [B, D] -> [B, T, D]
        out = jnp.broadcast_to(x[:, None], (x.shape[0], T) + x.shape[1:])
    m = _mask(lens, T, out.dtype).reshape(
        (out.shape[0], T) + (1,) * (out.ndim - 2))
    out = out * m
    ctx.set_len(ctx.op.outputs["Out"][0], lens)
    return {"Out": out}


@register_op("sequence_concat")
def _sequence_concat(ctx, ins, attrs):
    """sequence_concat_op axis=0: concatenate per-sequence along time.

    out[i] = concat(x0[i, :l0_i], x1[i, :l1_i], ...) then re-padded.
    Built with a gather from the stacked inputs — one fused XLA gather.
    """
    xs = ins["X"]
    B = xs[0].shape[0]
    lens_list = []
    for i, nm in enumerate(ctx.op.inputs["X"]):
        l = ctx.get_len(nm)
        if l is None:
            l = jnp.full((B,), xs[i].shape[1], jnp.int32)
        lens_list.append(l)
    total = sum(lens_list)
    T_out = sum(x.shape[1] for x in xs)
    # For output position t of row b, find which source and source offset.
    starts = jnp.cumsum(jnp.stack([jnp.zeros_like(lens_list[0])] +
                                  lens_list[:-1]), axis=0)  # [K, B]
    tpos = jnp.arange(T_out)[None, :]                        # [1, T_out]
    src = jnp.zeros((B, T_out), jnp.int32)
    off = tpos.repeat(B, 0)
    for k in range(len(xs)):
        sel = tpos >= starts[k][:, None]
        src = jnp.where(sel, k, src)
        off = jnp.where(sel, tpos - starts[k][:, None], off)
    padded = jnp.stack([jnp.pad(x, [(0, 0), (0, T_out - x.shape[1])] +
                                [(0, 0)] * (x.ndim - 2)) for x in xs])  # [K,B,T_out,...]
    b_idx = jnp.arange(B)[:, None]
    out = padded[src, b_idx, jnp.clip(off, 0, T_out - 1)]
    m = _mask(total, T_out, out.dtype).reshape(
        (B, T_out) + (1,) * (out.ndim - 2))
    out = out * m
    ctx.set_len(ctx.op.outputs["Out"][0], total)
    return {"Out": out}


@register_op("sequence_context")
def _sequence_context(ctx, ins, attrs):
    """v1 ContextProjection without the matmul: [B,T,D] -> [B,T,ctx_len*D]
    concat of shifted timesteps (function/ContextProjectionOp.cpp).  With
    a PadW input ([begin_pad+end_pad, D], trainable), out-of-range
    positions read the learned boundary rows instead of zeros — the
    reference's trainable_padding path."""
    x = ins["X"][0]
    pad_w = ins.get("PadW", [None])[0]
    lens = _seq_lens_or_full(ctx, x)
    ctx_len = attrs.get("contextLength", 3)
    start = attrs.get("contextStart", -(ctx_len // 2))
    begin_pad = max(0, -start)
    B, T, D = x.shape
    m = _mask(lens, T, x.dtype)[..., None]
    xm = x * m
    t = jnp.arange(T)
    cols = []
    for j in range(ctx_len):
        shift = start + j
        src = t + shift                                   # [T]
        base = jnp.take(xm, jnp.clip(src, 0, T - 1), axis=1)  # [B,T,D]
        under = (src < 0)[None, :, None]
        over = (src[None, :] >= lens[:, None])[..., None]
        if pad_w is not None:
            total = pad_w.shape[0]
            u_idx = jnp.clip(begin_pad + src, 0, total - 1)
            u_rows = pad_w[u_idx][None, :, :].astype(x.dtype)
            o_idx = jnp.clip(begin_pad + (src[None, :] - lens[:, None]),
                             0, total - 1)
            o_rows = pad_w[o_idx].astype(x.dtype)
            col = jnp.where(under, u_rows, base)
            col = jnp.where(over, o_rows, col)
        else:
            col = jnp.where(under | over, jnp.zeros_like(base), base)
        cols.append(col)
    out = jnp.concatenate(cols, axis=-1) * m
    ctx.set_len(ctx.op.outputs["Out"][0], lens)
    return {"Out": out}


@register_op("sub_nested_seq")
def _sub_nested_seq(ctx, ins, attrs):
    """SubNestedSequenceLayer.cpp: select subsequences of a level-2
    sequence [B,S,T,...] by per-batch indices [B,K].  Invalid indices
    (<0, the kmax_seq_score pad, or >=S) contribute zero rows and are
    excluded from the output lengths, so downstream sequence ops mask
    them as padding."""
    x = ins["X"][0]
    sel = ins["Selection"][0].astype(jnp.int32)
    if sel.ndim == 1:
        sel = sel[:, None]
    S = x.shape[1]
    valid = (sel >= 0) & (sel < S)                      # [B, K]
    safe = jnp.clip(sel, 0, S - 1)
    idx = safe.reshape(safe.shape + (1,) * (x.ndim - 2))
    out = jnp.take_along_axis(x, idx, axis=1)
    vmask = valid.reshape(valid.shape + (1,) * (x.ndim - 2))
    out = out * vmask.astype(x.dtype)
    lens2 = ctx.get_len2(ctx.op.inputs["X"][0])
    if lens2 is not None:
        ctx.set_len2(ctx.op.outputs["Out"][0],
                     jnp.take_along_axis(lens2, safe, axis=1) *
                     valid.astype(lens2.dtype))
    ctx.set_len(ctx.op.outputs["Out"][0],
                jnp.sum(valid, axis=1).astype(jnp.int32))
    return {"Out": out}


@register_op("conv2d_dynamic_filter")
def _conv2d_dynamic_filter(ctx, ins, attrs):
    """v1 conv_operator: convolution whose FILTER is another layer's
    output (ConvOperator.cpp).  The filter layer yields one filter set
    PER SAMPLE ([B, O*I*kh*kw]); lowered as one grouped conv by folding
    the batch into channels (feature_group_count=B) — stays a single MXU
    conv instead of a python loop over samples."""
    x, w = ins["Input"][0], ins["Filter"][0]
    O, I, kh, kw = attrs["filter_shape"]
    strides = tuple(attrs.get("strides", [1, 1]))
    pads = tuple(attrs.get("paddings", [0, 0]))
    B = x.shape[0]
    w = w.astype(x.dtype)
    if w.ndim == 2 and w.shape[0] == B and w.size == B * O * I * kh * kw:
        # per-sample filters: x [B,C,H,W] -> [1,B*C,H,W], w -> [B*O,I,kh,kw]
        xg = x.reshape((1, B * x.shape[1]) + x.shape[2:])
        wg = w.reshape(B * O, I, kh, kw)
        out = jax.lax.conv_general_dilated(
            xg, wg, window_strides=strides,
            padding=[(p, p) for p in pads],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=B)
        out = out.reshape((B, O) + out.shape[2:])
    else:
        out = jax.lax.conv_general_dilated(
            x, w.reshape(O, I, kh, kw), window_strides=strides,
            padding=[(p, p) for p in pads],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return {"Output": out}


@register_op("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    """sequence_conv_op: context-window projection along time
    (v1 ContextProjection, function/ContextProjection*).  Filter shape
    [ctx_len * D, M]."""
    x, w = ins["X"][0], ins["Filter"][0]
    lens = _seq_lens_or_full(ctx, x)
    stride = attrs.get("contextStride", 1)
    assert stride == 1, "sequence_conv supports stride 1 (as the reference)"
    ctx_len = attrs.get("contextLength", 3)
    start = attrs.get("contextStart", -(ctx_len // 2))
    B, T, D = x.shape
    m = _mask(lens, T, x.dtype)[..., None]
    xm = x * m
    cols = []
    for j in range(ctx_len):
        shift = start + j
        rolled = jnp.roll(xm, -shift, axis=1)
        # zero positions that rolled around
        t = jnp.arange(T)
        valid = (t + shift >= 0) & (t + shift < T)
        cols.append(rolled * valid[None, :, None].astype(x.dtype))
    ctxmat = jnp.concatenate(cols, axis=-1)          # [B, T, ctx_len*D]
    out = jnp.einsum("btd,dm->btm", ctxmat, w)
    out = out * m
    ctx.set_len(ctx.op.outputs["Out"][0], lens)
    return {"Out": out}


@register_op("sequence_slice")
def _sequence_slice(ctx, ins, attrs):
    """sequence_slice_op: per-sequence [offset, offset+length) gather."""
    x = ins["X"][0]
    offset = ins["Offset"][0].reshape(-1).astype(jnp.int32)
    length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    B, T = x.shape[0], x.shape[1]
    tpos = jnp.arange(T)[None, :]
    idx = jnp.clip(offset[:, None] + tpos, 0, T - 1)
    out = jnp.take_along_axis(
        x, idx.reshape((B, T) + (1,) * (x.ndim - 2)), axis=1)
    m = _mask(length, T, x.dtype).reshape((B, T) + (1,) * (x.ndim - 2))
    out = out * m
    ctx.set_len(ctx.op.outputs["Out"][0], length)
    return {"Out": out}


def _masked_reverse(x, lens):
    """Reverse the first ``lens[b]`` steps of each row, padding stays put
    (reference sequence_reverse_op semantics)."""
    B, T = x.shape[0], x.shape[1]
    tpos = jnp.arange(T)[None, :]
    idx = jnp.where(tpos < lens[:, None], lens[:, None] - 1 - tpos, tpos)
    return jnp.take_along_axis(
        x, idx.reshape((B, T) + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1)


@register_op("sequence_reverse")
def _sequence_reverse(ctx, ins, attrs):
    x = ins["X"][0]
    lens = _seq_lens_or_full(ctx, x)
    out = _masked_reverse(x, lens)
    ctx.set_len(ctx.op.outputs["Y" if "Y" in ctx.op.outputs else "Out"][0], lens)
    return {("Y" if "Y" in ctx.op.outputs else "Out"): out}


@register_op("sequence_reshape")
def _sequence_reshape(ctx, ins, attrs):
    """sequence_reshape_op: change feature dim, scaling lengths."""
    x = ins["X"][0]
    new_dim = attrs["new_dim"]
    B, T, D = x.shape
    if (T * D) % new_dim:
        raise ValueError(
            f"sequence_reshape: T*D={T * D} not divisible by new_dim "
            f"{new_dim}")
    lens = _seq_lens_or_full(ctx, x)
    out = x.reshape(B, T * D // new_dim, new_dim)
    new_lens = (lens * D) // new_dim
    ctx.set_len(ctx.op.outputs["Out"][0], new_lens)
    return {"Out": out}


@register_op("sequence_pad")
def _sequence_pad(ctx, ins, attrs):
    """Identity in the padded representation (kept for API parity)."""
    x = ins["X"][0]
    lens = _seq_lens_or_full(ctx, x)
    return {"Out": x, "Length": lens}


@register_op("sequence_unpad")
def _sequence_unpad(ctx, ins, attrs):
    x = ins["X"][0]
    lens = ins["Length"][0] if "Length" in ins and ins["Length"] else \
        _seq_lens_or_full(ctx, x)
    ctx.set_len(ctx.op.outputs["Out"][0], lens.reshape(-1))
    return {"Out": x}


@register_op("lod_reset")
def _lod_reset(ctx, ins, attrs):
    x = ins["X"][0]
    if "Y" in ins and ins["Y"]:
        lens = ins["Y"][0].reshape(-1).astype(jnp.int32)
    else:
        target = attrs.get("target_lod", [])
        offs = jnp.asarray(target, jnp.int32)
        lens = offs[1:] - offs[:-1]
    ctx.set_len(ctx.op.outputs["Out"][0], lens)
    return {"Out": x}


@register_op("row_conv")
def _row_conv(ctx, ins, attrs):
    """row_conv_op: lookahead convolution (DeepSpeech2-style)."""
    x, w = ins["X"][0], ins["Filter"][0]   # x [B,T,D], w [future_ctx, D]
    lens = _seq_lens_or_full(ctx, x)
    T = x.shape[1]
    m = _mask(lens, T, x.dtype)[..., None]
    xm = x * m
    ctx_len = w.shape[0]
    out = jnp.zeros_like(x)
    for j in range(ctx_len):
        rolled = jnp.roll(xm, -j, axis=1)
        t = jnp.arange(T)
        valid = (t + j < T)[None, :, None].astype(x.dtype)
        out = out + rolled * valid * w[j][None, None, :]
    out = out * m
    ctx.set_len(ctx.op.outputs["Out"][0], lens)
    return {"Out": out}


@register_op("max_sequence_len")
def _max_sequence_len(ctx, ins, attrs):
    lens = _in_lens(ctx, "RankTable") if "RankTable" in ctx.op.inputs else \
        _in_lens(ctx, "X")
    if lens is None:
        x = next(iter(ins.values()))[0]
        return {"Out": jnp.asarray(x.shape[1], jnp.int64)}
    return {"Out": jnp.max(lens).astype(jnp.int64)}


# ---------------------------------------------------------------------------
# Fused recurrent ops (reference lstm_op.cc + math/lstm_compute;
# gru_op.cc + math/gru_compute; *_unit ops)
# Gate order: i, f, c(candidate), o for LSTM; u(update), r(reset), c for GRU.
# ---------------------------------------------------------------------------
_ACT = {
    "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh, "relu": jax.nn.relu,
    "identity": lambda x: x,
}


@register_op("lstm")
def _lstm(ctx, ins, attrs):
    """dynamic LSTM over [B,T,4H] pre-projected input; recurrent Weight
    [H,4H]; Bias [1,4H] (+[1,3H] peephole tail when use_peepholes)."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    bias = ins["Bias"][0].reshape(-1) if "Bias" in ins and ins["Bias"] else None
    lens = _seq_lens_or_full(ctx, x, slot="Input")
    B, T, H4 = x.shape
    H = H4 // 4
    use_peep = attrs.get("use_peepholes", False)
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACT[attrs.get("cell_activation", "tanh")]
    cand_act = _ACT[attrs.get("candidate_activation", "tanh")]
    b_gate = None
    wi = wf = wo = None
    if bias is not None:
        b_gate = bias[:4 * H]
        if use_peep:
            peep = bias[4 * H:7 * H]
            wi, wf, wo = peep[:H], peep[H:2 * H], peep[2 * H:]
    h0 = ins["H0"][0] if "H0" in ins and ins["H0"] else jnp.zeros((B, H), x.dtype)
    c0 = ins["C0"][0] if "C0" in ins and ins["C0"] else jnp.zeros((B, H), x.dtype)
    is_reverse = attrs.get("is_reverse", False)
    if is_reverse:
        x = _masked_reverse(x, lens)
    xt_seq = jnp.swapaxes(x, 0, 1)              # [T, B, 4H]
    step_mask = _mask(lens, T, x.dtype).T       # [T, B]

    def step(carry, inp):
        h, c = carry
        xt, mt = inp
        gates = xt + h @ w
        if b_gate is not None:
            gates = gates + b_gate
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if use_peep:
            gi = gi + c * wi
            gf = gf + c * wf
        i = gate_act(gi)
        f = gate_act(gf)
        cand = cand_act(gc)
        c_new = f * c + i * cand
        if use_peep:
            go = go + c_new * wo
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        mt = mt[:, None]
        h_new = mt * h_new + (1 - mt) * h
        c_new = mt * c_new + (1 - mt) * c
        return (h_new, c_new), (h_new * mt, c_new * mt)

    (_, _), (hs, cs) = lax.scan(step, (h0, c0), (xt_seq, step_mask))
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        hidden = _masked_reverse(hidden, lens)
        cell = _masked_reverse(cell, lens)
    for slot, val in (("Hidden", hidden), ("Cell", cell)):
        if slot in ctx.op.outputs and ctx.op.outputs[slot]:
            ctx.set_len(ctx.op.outputs[slot][0], lens)
    return {"Hidden": hidden, "Cell": cell}


@register_op("gru")
def _gru(ctx, ins, attrs):
    """dynamic GRU over [B,T,3H]; Weight [H,3H] laid out [u|r|c]."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    bias = ins["Bias"][0].reshape(-1) if "Bias" in ins and ins["Bias"] else None
    lens = _seq_lens_or_full(ctx, x, slot="Input")
    B, T, H3 = x.shape
    H = H3 // 3
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]
    w_ur = w[:, :2 * H]
    w_c = w[:, 2 * H:]
    h0 = ins["H0"][0] if "H0" in ins and ins["H0"] else jnp.zeros((B, H), x.dtype)
    is_reverse = attrs.get("is_reverse", False)
    if is_reverse:
        x = _masked_reverse(x, lens)
    xt_seq = jnp.swapaxes(x, 0, 1)
    step_mask = _mask(lens, T, x.dtype).T

    def step(h, inp):
        xt, mt = inp
        x_ur = xt[:, :2 * H]
        x_c = xt[:, 2 * H:]
        ur = x_ur + h @ w_ur
        if bias is not None:
            ur = ur + bias[:2 * H]
        u, r = jnp.split(gate_act(ur), 2, axis=-1)
        c = x_c + (r * h) @ w_c
        if bias is not None:
            c = c + bias[2 * H:]
        c = cand_act(c)
        h_new = u * h + (1.0 - u) * c
        mt = mt[:, None]
        h_new = mt * h_new + (1 - mt) * h
        return h_new, h_new * mt

    _, hs = lax.scan(step, h0, (xt_seq, step_mask))
    hidden = jnp.swapaxes(hs, 0, 1)
    if is_reverse:
        hidden = _masked_reverse(hidden, lens)
    if "Hidden" in ctx.op.outputs and ctx.op.outputs["Hidden"]:
        ctx.set_len(ctx.op.outputs["Hidden"][0], lens)
    return {"Hidden": hidden}


@register_op("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    """single LSTM step from pre-computed gates [B,4H] (lstm_unit_op)."""
    gates, c_prev = ins["X"][0], ins["C_prev"][0]
    forget_bias = attrs.get("forget_bias", 0.0)
    gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    o = jax.nn.sigmoid(go)
    c = f * c_prev + i * jnp.tanh(gc)
    h = o * jnp.tanh(c)
    return {"C": c, "H": h}


@register_op("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """single GRU step (gru_unit_op): Input [B,3H], HiddenPrev [B,H],
    Weight [H,3H]."""
    x, h, w = ins["Input"][0], ins["HiddenPrev"][0], ins["Weight"][0]
    H = h.shape[-1]
    bias = ins["Bias"][0].reshape(-1) if "Bias" in ins and ins["Bias"] else None
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]
    ur = x[:, :2 * H] + h @ w[:, :2 * H]
    if bias is not None:
        ur = ur + bias[:2 * H]
    g = gate_act(ur)
    u, r = g[:, :H], g[:, H:]
    c = x[:, 2 * H:] + (r * h) @ w[:, 2 * H:]
    if bias is not None:
        c = c + bias[2 * H:]
    c = cand_act(c)
    h_new = u * h + (1.0 - u) * c
    return {"Hidden": h_new, "Gate": g, "ResetHiddenPrev": r * h}


@register_op("kmax_seq_score")
def _kmax_seq_score(ctx, ins, attrs):
    """KmaxSeqScoreLayer.cpp: indices of the top-k scores per sequence
    (padding positions masked out); -1 pads when a sequence is shorter
    than k."""
    x = ins["X"][0]                      # [B, T] or [B, T, 1]
    if x.ndim == 3:
        x = x[..., 0]
    k = int(attrs.get("beam_size", attrs.get("k", 1)))
    lens = _seq_lens_or_full(ctx, x)
    T = x.shape[1]
    neg = jnp.asarray(-3.4e38, x.dtype)
    masked = jnp.where(jnp.arange(T)[None, :] < lens[:, None], x, neg)
    k_eff = min(k, T)
    _, idx = jax.lax.top_k(masked, k_eff)
    valid = jnp.arange(k_eff)[None, :] < jnp.minimum(lens, k_eff)[:, None]
    out = jnp.where(valid, idx, -1)
    if k_eff < k:
        out = jnp.pad(out, ((0, 0), (0, k - k_eff)), constant_values=-1)
    return {"Out": out.astype(jnp.int64)}


# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer) over the padded+lengths
# representation — the InferShape analogs of sequence_*_op.cc and
# lstm_op.cc/gru_op.cc.
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import (ShapeError, VarInfo,  # noqa: E402
                                    conv_out_dim, dim_ok, first, same_as)
from ..core.registry import register_shape_fn  # noqa: E402

register_shape_fn("sequence_softmax", "sequence_slice", "sequence_unpad",
                  "lod_reset", "row_conv")(same_as("X"))


@register_shape_fn("sequence_pool")
def _sequence_pool_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None or len(x.shape) < 2:
        return {"Out": VarInfo(None, x.dtype)}
    # [B, T, ...] -> [B, ...]; the nested (lod-2) LAST/FIRST form drops two
    # dims, but lod levels are runtime metadata — stay at the common case
    # and let the declaration fill the gap when it disagrees in rank only.
    return {"Out": x.with_shape(x.shape[:1] + x.shape[2:])}


@register_shape_fn("sequence_expand", "sequence_expand_as")
def _sequence_expand_shape(op, ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    if x.shape is None:
        return {"Out": x}
    if y.shape is not None and len(y.shape) >= 2 and \
            len(x.shape) < len(y.shape):
        return {"Out": x.with_shape(x.shape[:1] + (y.shape[1],)
                                    + x.shape[1:])}
    return {"Out": x}


@register_shape_fn("sequence_concat")
def _sequence_concat_shape(op, ins, attrs):
    xs = [v for v in ins.get("X", []) if v is not None]
    known = [v for v in xs if v.shape is not None]
    if not known or len(known) != len(xs):
        return {"Out": VarInfo(None, xs[0].dtype if xs else None)}
    base = known[0]
    t = 0
    for v in known:
        if len(v.shape) != len(base.shape) or \
                not all(dim_ok(a, b) for a, b in
                        zip(v.shape[2:], base.shape[2:])):
            raise ShapeError(
                f"sequence_concat: feature dims differ: "
                f"{list(base.shape)} vs {list(v.shape)}")
        t = -1 if t < 0 or v.shape[1] < 0 else t + v.shape[1]
    return {"Out": base.with_shape(base.shape[:1] + (t,) + base.shape[2:])}


@register_shape_fn("sequence_context")
def _sequence_context_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    if len(x.shape) != 3:
        raise ShapeError(
            f"sequence_context: X must be [B, T, D], got {list(x.shape)}")
    b, t, d = x.shape
    ctx_len = attrs.get("contextLength", 3)
    return {"Out": x.with_shape((b, t, -1 if d < 0 else ctx_len * d))}


@register_shape_fn("sub_nested_seq")
def _sub_nested_seq_shape(op, ins, attrs):
    x, sel = first(ins, "X"), first(ins, "Selection")
    if x.shape is None or sel.shape is None:
        return {"Out": VarInfo(None, x.dtype)}
    k = sel.shape[1] if len(sel.shape) >= 2 else 1
    return {"Out": x.with_shape(x.shape[:1] + (k,) + x.shape[2:])}


@register_shape_fn("conv2d_dynamic_filter")
def _conv2d_dynamic_filter_shape(op, ins, attrs):
    x = first(ins, "Input")
    if x.shape is None:
        return {"Output": x}
    o, i, kh, kw = attrs["filter_shape"]
    strides = tuple(attrs.get("strides", [1, 1]))
    pads = tuple(attrs.get("paddings", [0, 0]))
    return {"Output": VarInfo(
        (x.shape[0], o, conv_out_dim(x.shape[2], kh, pads[0], strides[0]),
         conv_out_dim(x.shape[3], kw, pads[1], strides[1])), x.dtype)}


@register_shape_fn("sequence_conv")
def _sequence_conv_shape(op, ins, attrs):
    x, w = first(ins, "X"), first(ins, "Filter")
    if x.shape is None:
        return {"Out": x}
    if w.shape is not None and x.shape[-1] >= 0 and w.shape[0] >= 0:
        ctx_len = attrs.get("contextLength", 3)
        if w.shape[0] != ctx_len * x.shape[-1]:
            raise ShapeError(
                f"sequence_conv: Filter rows {w.shape[0]} != "
                f"contextLength {ctx_len} * D {x.shape[-1]}")
    m = w.shape[-1] if w.shape is not None else -1
    return {"Out": x.with_shape(x.shape[:-1] + (m,))}


@register_shape_fn("sequence_reverse")
def _sequence_reverse_shape(op, ins, attrs):
    x = first(ins, "X")
    out_slot = "Y" if op.outputs.get("Y") else "Out"
    return {out_slot: x}


@register_shape_fn("sequence_reshape")
def _sequence_reshape_shape(op, ins, attrs):
    x = first(ins, "X")
    if x.shape is None:
        return {"Out": x}
    new_dim = attrs["new_dim"]
    b, t, d = x.shape
    if t >= 0 and d >= 0:
        if (t * d) % new_dim:
            raise ShapeError(
                f"sequence_reshape: T*D={t * d} not divisible by new_dim "
                f"{new_dim}")
        return {"Out": x.with_shape((b, t * d // new_dim, new_dim))}
    return {"Out": x.with_shape((b, -1, new_dim))}


@register_shape_fn("sequence_pad")
def _sequence_pad_shape(op, ins, attrs):
    x = first(ins, "X")
    b = x.shape[0] if x.shape is not None else -1
    return {"Out": x, "Length": VarInfo((b,), "int32")}


@register_shape_fn("max_sequence_len")
def _max_sequence_len_shape(op, ins, attrs):
    return {"Out": VarInfo((), "int64")}


@register_shape_fn("lstm")
def _lstm_shape(op, ins, attrs):
    x, w = first(ins, "Input"), first(ins, "Weight")
    if x.shape is None:
        return {"Hidden": x, "Cell": x}
    b, t, h4 = x.shape
    if h4 >= 0 and h4 % 4:
        raise ShapeError(f"lstm: input width {h4} is not 4*H")
    h = -1 if h4 < 0 else h4 // 4
    if w.shape is not None and h >= 0 and \
            (len(w.shape) != 2
             or not all(dim_ok(a, b)
                        for a, b in zip(w.shape, (h, h4)))):
        raise ShapeError(
            f"lstm: Weight {list(w.shape)} != [H, 4H] = [{h}, {h4}]")
    info = VarInfo((b, t, h), x.dtype)
    return {"Hidden": info, "Cell": info}


@register_shape_fn("gru")
def _gru_shape(op, ins, attrs):
    x, w = first(ins, "Input"), first(ins, "Weight")
    if x.shape is None:
        return {"Hidden": x}
    b, t, h3 = x.shape
    if h3 >= 0 and h3 % 3:
        raise ShapeError(f"gru: input width {h3} is not 3*H")
    h = -1 if h3 < 0 else h3 // 3
    if w.shape is not None and h >= 0 and \
            (len(w.shape) != 2
             or not all(dim_ok(a, b)
                        for a, b in zip(w.shape, (h, h3)))):
        raise ShapeError(
            f"gru: Weight {list(w.shape)} != [H, 3H] = [{h}, {h3}]")
    return {"Hidden": VarInfo((b, t, h), x.dtype)}


@register_shape_fn("lstm_unit")
def _lstm_unit_shape(op, ins, attrs):
    gates, c_prev = first(ins, "X"), first(ins, "C_prev")
    if gates.shape is not None and c_prev.shape is not None and \
            gates.shape[-1] >= 0 and c_prev.shape[-1] >= 0 and \
            gates.shape[-1] != 4 * c_prev.shape[-1]:
        raise ShapeError(
            f"lstm_unit: gates width {gates.shape[-1]} != 4 * H "
            f"{c_prev.shape[-1]}")
    return {"C": c_prev, "H": c_prev}


@register_shape_fn("gru_unit")
def _gru_unit_shape(op, ins, attrs):
    x, h = first(ins, "Input"), first(ins, "HiddenPrev")
    res = {"Hidden": h}
    if h.shape is not None and h.shape[-1] >= 0:
        res["Gate"] = h.with_shape(h.shape[:-1] + (2 * h.shape[-1],))
        res["ResetHiddenPrev"] = h
    return res


@register_shape_fn("kmax_seq_score")
def _kmax_seq_score_shape(op, ins, attrs):
    x = first(ins, "X")
    b = x.shape[0] if x.shape is not None else -1
    k = int(attrs.get("beam_size", attrs.get("k", 1)))
    return {"Out": VarInfo((b, k), "int64")}


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop).  Recurrences keep the
# batch sharding; the lstm/gru gate dim follows the Weight's column split
# (the Megatron gate-parallel pattern — the col-split input projection and
# the recurrent weight shard the same axis).
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import (first_in, merge_entry,  # noqa: E402
                                   shard_noop, shard_same_as)
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("sequence_softmax")(shard_same_as("X"))
register_shard_fn("sequence_reverse")(shard_same_as("X", out="Y"))
register_shard_fn("sequence_unpad", "sequence_pad")(shard_noop())


@register_shard_fn("sequence_pool")
def _sequence_pool_shard(op, ins, attrs):
    x = first_in(ins, "X")
    if x.spec is None:
        return {}
    # [B, T, D] -> [B, D]: the time dim drops
    return {"Out": (x.entry(0),) + tuple(x.spec[2:])}


@register_shard_fn("lstm")
def _lstm_shard(op, ins, attrs):
    x, w = first_in(ins, "Input"), first_in(ins, "Weight")
    if x.spec is None and w.spec is None:
        return {}
    h_entry = merge_entry(x.entry(2), w.entry(1), "lstm gate dim")
    info = ((x.entry(0), x.entry(1), h_entry))
    return {"Hidden": info, "Cell": info}


@register_shard_fn("gru")
def _gru_shard(op, ins, attrs):
    x, w = first_in(ins, "Input"), first_in(ins, "Weight")
    if x.spec is None and w.spec is None:
        return {}
    h_entry = merge_entry(x.entry(2), w.entry(1), "gru gate dim")
    return {"Hidden": (x.entry(0), x.entry(1), h_entry)}
