"""Metric op lowerings (reference: accuracy_op.cc, auc_op.cc,
precision_recall_op.cc, positive_negative_pair_op.cc; v1 evaluators in
gserver/evaluators/).  Stateful accumulation lives in persistable vars
managed by paddle_tpu.evaluator, mirroring fluid evaluator.py:21-90."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op


@register_op("accuracy")
def _accuracy(ctx, ins, attrs):
    """accuracy_op: Indices are top-k predicted ids [N,k], Label [N,1]."""
    idx, label = ins["Indices"][0], ins["Label"][0]
    label = label.astype(idx.dtype).reshape(-1, 1)
    correct = jnp.any(idx == label, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = jnp.asarray(idx.shape[0], jnp.float32)
    return {"Accuracy": (num_correct / total).reshape(1),
            "Correct": num_correct.astype(jnp.int32).reshape(1),
            "Total": jnp.asarray([idx.shape[0]], jnp.int32)}


@register_op("auc")
def _auc(ctx, ins, attrs):
    """auc_op: streaming AUC over threshold buckets.  Inputs Predict [N,2]
    (binary probs) or [N,1], Label [N,1]; optional stat inputs accumulate."""
    pred = ins["Predict"][0]
    label = ins["Label"][0].reshape(-1)
    num_thresh = attrs.get("num_thresholds", 200)
    if pred.ndim == 2 and pred.shape[1] == 2:
        pos_prob = pred[:, 1]
    else:
        pos_prob = pred.reshape(-1)
    bucket = jnp.clip((pos_prob * num_thresh).astype(jnp.int32), 0, num_thresh)
    pos = (label > 0).astype(jnp.float32)
    neg = 1.0 - pos
    tp_hist = jnp.zeros(num_thresh + 1).at[bucket].add(pos)
    fp_hist = jnp.zeros(num_thresh + 1).at[bucket].add(neg)
    if "StatPos" in ins and ins["StatPos"]:
        tp_hist = tp_hist + ins["StatPos"][0]
        fp_hist = fp_hist + ins["StatNeg"][0]
    # TP/FP above each threshold = suffix sums
    tp = jnp.cumsum(tp_hist[::-1])[::-1]
    fp = jnp.cumsum(fp_hist[::-1])[::-1]
    tot_pos, tot_neg = tp[0], fp[0]
    tpr = tp / jnp.maximum(tot_pos, 1.0)
    fpr = fp / jnp.maximum(tot_neg, 1.0)
    auc = -jnp.trapezoid(tpr, fpr)
    return {"AUC": auc.reshape(1), "StatPosOut": tp_hist, "StatNegOut": fp_hist}


@register_op("precision_recall")
def _precision_recall(ctx, ins, attrs):
    """precision_recall_op: per-class macro/micro P/R/F1 from MaxProbs idx."""
    idx = ins["Indices"][0].reshape(-1)
    label = ins["Labels"][0].reshape(-1).astype(idx.dtype)
    ncls = attrs["class_number"]
    onehot_pred = jnp.zeros(ncls).at[idx].add(1.0)
    onehot_lab = jnp.zeros(ncls).at[label].add(1.0)
    tp = jnp.zeros(ncls).at[idx].add((idx == label).astype(jnp.float32))
    states = jnp.stack([tp, onehot_pred - tp, onehot_lab - tp], axis=1)
    if "StatesInfo" in ins and ins["StatesInfo"]:
        states = states + ins["StatesInfo"][0]
    tp_, fp_, fn_ = states[:, 0], states[:, 1], states[:, 2]
    prec = tp_ / jnp.maximum(tp_ + fp_, 1.0)
    rec = tp_ / jnp.maximum(tp_ + fn_, 1.0)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-6)
    macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
    tps, fps, fns = tp_.sum(), fp_.sum(), fn_.sum()
    mp = tps / jnp.maximum(tps + fps, 1.0)
    mr = tps / jnp.maximum(tps + fns, 1.0)
    mf = 2 * mp * mr / jnp.maximum(mp + mr, 1e-6)
    metrics = jnp.concatenate([macro, jnp.stack([mp, mr, mf])])
    return {"BatchMetrics": metrics, "AccumMetrics": metrics,
            "AccumStatesInfo": states}


@register_op("positive_negative_pair")
def _pnpair(ctx, ins, attrs):
    """positive_negative_pair_op: rank-order statistics within query groups."""
    score = ins["Score"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1)
    qid = ins["QueryID"][0].reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    lab_gt = label[:, None] > label[None, :]
    score_gt = score[:, None] > score[None, :]
    score_eq = score[:, None] == score[None, :]
    valid = same_q & lab_gt
    pos = jnp.sum((valid & score_gt).astype(jnp.float32))
    neu = jnp.sum((valid & score_eq).astype(jnp.float32))
    neg = jnp.sum(valid.astype(jnp.float32)) - pos - neu
    if "AccumulatePositivePair" in ins and ins["AccumulatePositivePair"]:
        pos = pos + ins["AccumulatePositivePair"][0].reshape(())
        neg = neg + ins["AccumulateNegativePair"][0].reshape(())
        neu = neu + ins["AccumulateNeutralPair"][0].reshape(())
    return {"PositivePair": pos.reshape(1), "NegativePair": neg.reshape(1),
            "NeutralPair": neu.reshape(1)}


# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer).
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import VarInfo  # noqa: E402
from ..core.registry import register_shape_fn  # noqa: E402


@register_shape_fn("accuracy")
def _accuracy_shape(op, ins, attrs):
    return {"Accuracy": VarInfo((1,), "float32"),
            "Correct": VarInfo((1,), "int32"),
            "Total": VarInfo((1,), "int32")}


@register_shape_fn("auc")
def _auc_shape(op, ins, attrs):
    n = attrs.get("num_thresholds", 200)
    hist = VarInfo((n + 1,), "float32")
    return {"AUC": VarInfo((1,), "float32"), "StatPosOut": hist,
            "StatNegOut": hist}


@register_shape_fn("precision_recall")
def _precision_recall_shape(op, ins, attrs):
    ncls = attrs["class_number"]
    m = VarInfo((6,), "float32")
    return {"BatchMetrics": m, "AccumMetrics": m,
            "AccumStatesInfo": VarInfo((ncls, 3), "float32")}


@register_shape_fn("positive_negative_pair")
def _pnpair_shape(op, ins, attrs):
    s = VarInfo((1,), "float32")
    return {"PositivePair": s, "NegativePair": s, "NeutralPair": s}


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop): metrics reduce to
# scalars/counters — replicated outputs regardless of input sharding.
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import shard_replicated  # noqa: E402
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("accuracy")(shard_replicated(
    "Accuracy", "Correct", "Total"))
register_shard_fn("auc")(shard_replicated("AUC"))
register_shard_fn("precision_recall")(shard_replicated(
    "BatchMetrics", "AccumMetrics", "AccumStatesInfo"))
register_shard_fn("positive_negative_pair")(shard_replicated(
    "PositivePair", "NegativePair", "NeutralPair"))
