"""Pallas TPU kernels for hot ops.

The reference hand-wrote CUDA for its hot paths (paddle/cuda hl_*.cu — fused
LSTM, attention-ish matrix kernels).  The TPU-native analog is Pallas: this
module provides a fused flash-attention kernel (online-softmax, O(T) memory,
K/V streamed through VMEM) used by ``nets.scaled_dot_product_attention`` and
available to models directly.

Both directions are fused kernels.  The forward computes exact attention and
saves only the per-row logsumexp; the backward (FlashAttention-2 style)
recomputes block-local probabilities from (q, k, lse) inside two Pallas
kernels — one accumulating dq over key blocks, one accumulating dk/dv over
query blocks — so the [T, T] probability matrix is never materialized in
either direction and O(T) memory holds for *training*, not just inference.
On non-TPU backends the jnp reference runs instead (CPU tests exercise the
kernels in interpret mode).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
    # renamed TPUCompilerParams -> CompilerParams across jax versions;
    # interpret-mode tests never touch it, so resolve at import to fail
    # loudly here rather than at first on-TPU trace
    _CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

NEG_INF = -1e30


def _sds(x, shape, dtype):
    """ShapeDtypeStruct inheriting ``x``'s varying-manual-axes type, so the
    kernels compose with the new shard_map's vma checker (ring attention
    calls them per device hop)."""
    aval = jax.typeof(x) if hasattr(jax, "typeof") else \
        jax.core.get_aval(x)
    vma = getattr(aval, "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------
def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                  *, block_k, num_k_blocks, causal, sm_scale, block_q):
    """Grid (bh, q_blocks, k_blocks), k innermost/sequential: K/V stream
    through VMEM one [block_k, D] tile at a time (O(T) memory), with the
    online-softmax running stats (m, l) and the output accumulator living in
    VMEM scratch across the k dimension.  Also emits the per-row logsumexp
    (the only residual the fused backward needs)."""
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        q32 = q_ref[0].astype(jnp.float32) * sm_scale      # [bq, D]
        kblk = k_ref[0].astype(jnp.float32)                # [bk, D]
        vblk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q32, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if causal:
            qpos = j * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = m_new

    if causal:
        # blocks strictly above the diagonal contribute nothing — skip them
        pl.when(kb * block_k <= (j + 1) * block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kb == num_k_blocks - 1)
    def _write():
        l_safe = jnp.maximum(l_ref[...], 1e-20)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(l_safe)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    """Returns (out, lse); lse is [BH, Tq, 1] float32."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    Dv = v.shape[2]
    nk = Tk // block_k
    grid = (BH, Tq // block_q, nk)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, num_k_blocks=nk,
                          causal=causal, sm_scale=sm_scale,
                          block_q=block_q),
        out_shape=[
            _sds(q, (BH, Tq, Dv), q.dtype),
            _sds(q, (BH, Tq, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, block_k, Dv), lambda i, j, kb: (i, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, Dv), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, Dv), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2: recompute p from (q, k, lse) per block)
# ---------------------------------------------------------------------------
def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, acc_ref, *, block_q, block_k, num_k_blocks,
                         causal, sm_scale):
    """Grid (bh, q_blocks, k_blocks), k innermost: dq for one query block
    accumulates over streamed K/V blocks in a VMEM scratch."""
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _compute():
        q32 = q_ref[0].astype(jnp.float32) * sm_scale      # [bq, D]
        kblk = k_ref[0].astype(jnp.float32)                # [bk, D]
        vblk = v_ref[0].astype(jnp.float32)                # [bk, Dv]
        do = do_ref[0].astype(jnp.float32)                 # [bq, Dv]
        lse = lse_ref[0]                                   # [bq, 1]
        delta = delta_ref[0]                               # [bq, 1]
        s = jax.lax.dot_general(
            q32, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if causal:
            qpos = j * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse)                               # normalized probs
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = p * (dp - delta)
        acc_ref[...] += jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, D]

    if causal:
        pl.when(kb * block_k <= (j + 1) * block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kb == num_k_blocks - 1)
    def _write():
        dq_ref[0] = (acc_ref[...] * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, block_q,
                          block_k, num_q_blocks, causal, sm_scale):
    """Grid (bh, k_blocks, q_blocks), q innermost: dk/dv for one key block
    accumulate over streamed Q/dO blocks in VMEM scratches."""
    kb = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        q32 = q_ref[0].astype(jnp.float32) * sm_scale      # [bq, D]
        kblk = k_ref[0].astype(jnp.float32)                # [bk, D]
        vblk = v_ref[0].astype(jnp.float32)                # [bk, Dv]
        do = do_ref[0].astype(jnp.float32)                 # [bq, Dv]
        lse = lse_ref[0]                                   # [bq, 1]
        delta = delta_ref[0]                               # [bq, 1]
        s = jax.lax.dot_general(
            q32, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if causal:
            qpos = j * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [bq, bk]
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, Dv]
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = p * (dp - delta)
        dk_acc[...] += jax.lax.dot_general(
            ds, q32, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, D]

    if causal:
        # query blocks entirely above the diagonal see this key block masked
        pl.when((j + 1) * block_q - 1 >= kb * block_k)(_compute)
    else:
        _compute()

    @pl.when(j == num_q_blocks - 1)
    def _write():
        # q32 already carried sm_scale, so dk_acc is fully scaled
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k,
               interpret, g_lse=None):
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    Dv = v.shape[2]
    nq = Tq // block_q
    nk = Tk // block_k
    # delta_i = sum_d dO_i · O_i  (rescaling term of dsoftmax); O(T·Dv) work,
    # fused by XLA — not worth a kernel.  A cotangent on lse folds in here:
    # dL/ds_ij = p_ij (dp_ij - delta_i + g_lse_i), so delta_eff = delta -
    # g_lse and the kernels run unchanged.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                # [BH, Tq, 1]
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32).reshape(delta.shape)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, num_k_blocks=nk, causal=causal,
                          sm_scale=sm_scale),
        out_shape=_sds(q, (BH, Tq, D), q.dtype),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, block_k, Dv), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, block_q, Dv), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda i, j, kb: (i, j, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, num_q_blocks=nq, causal=causal,
                          sm_scale=sm_scale),
        out_shape=[
            _sds(k, (BH, Tk, D), k.dtype),
            _sds(v, (BH, Tk, Dv), v.dtype),
        ],
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda i, kb, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_k, Dv), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_q, Dv), lambda i, kb, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, kb, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, kb, j: (i, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_k, Dv), lambda i, kb, j: (i, kb, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, Dv), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _reference_attention(q, k, v, causal, sm_scale):
    s = jnp.einsum("bqd,bkd->bqk", q * sm_scale, k)
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                        interpret)
    return out


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, g, causal, sm_scale, block_q,
                      block_k, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                      interpret)


def _flash_lse_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                       interpret):
    out, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(causal, sm_scale, block_q, block_k, interpret, res,
                       g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    return _flash_bwd(q, k, v, out, lse, g_out, causal, sm_scale, block_q,
                      block_k, interpret, g_lse=g_lse)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_with_lse(q, k, v, causal=False, sm_scale=None,
                             block_q=128, block_k=128, interpret=False):
    """Fused attention returning (out, lse [BH, Tq, 1]) — the streaming-
    softmax residual blockwise consumers (ring attention) merge across
    device hops.  q,k,v: [BH, T, D], block-divisible lengths.  Fully
    differentiable: an lse cotangent folds into the backward's delta."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    if q.shape[1] % bq or k.shape[1] % bk or (causal and
                                             q.shape[1] != k.shape[1]):
        raise ValueError(
            "flash_attention_with_lse needs block-divisible lengths "
            f"(got Tq={q.shape[1]}, Tk={k.shape[1]})")
    return _flash_lse(q, k, v, causal, sm_scale, bq, bk, interpret)


def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=128,
                    block_k=128, use_pallas=None, interpret=None):
    """Fused attention.  q,k,v: [B, T, H, D] (or [BH, T, D]).

    use_pallas=None auto-selects the Pallas kernel on TPU only; every other
    backend gets the exact jnp reference.  interpret=True (explicit, as the
    CPU tests do) runs the kernel through the Pallas interpreter instead.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    squeeze_heads = q.ndim == 4
    if squeeze_heads:
        B, Tq_out, H, _ = q.shape

        def rs(x):
            b, t, h, d = x.shape
            return jnp.moveaxis(x, 2, 1).reshape(b * h, t, d)

        q3, k3, v3 = rs(q), rs(k), rs(v)
    else:
        q3, k3, v3 = q, k, v
    if q3.shape[-1] != k3.shape[-1]:
        raise ValueError(
            f"flash_attention: q feature dim {q3.shape[-1]} != k feature "
            f"dim {k3.shape[-1]}")
    if use_pallas is None:
        use_pallas = _HAVE_PALLAS and jax.default_backend() == "tpu"
    interpret = bool(interpret)
    Tq, Tk = q3.shape[1], k3.shape[1]
    if use_pallas or interpret:
        bq = min(block_q, Tq)
        bk = min(block_k, Tk)
        if Tq % bq or Tk % bk or (causal and Tq != Tk):
            # ragged tail (kernel needs block-divisible lengths) or causal
            # cross-attention (kernel's diagonal offset assumes Tq==Tk):
            # run the exact jnp reference
            out = _reference_attention(q3, k3, v3, causal, sm_scale)
        else:
            out = _flash(q3, k3, v3, causal, sm_scale, bq, bk, interpret)
    else:
        out = _reference_attention(q3, k3, v3, causal, sm_scale)
    if squeeze_heads:
        out = jnp.moveaxis(
            out.reshape(B, H, Tq_out, v.shape[-1]), 1, 2)
    return out


# ---------------------------------------------------------------------------
# op registration (layer: layers.flash_attention)
# ---------------------------------------------------------------------------
from ..core.registry import register_op, register_tunable  # noqa: E402

# Autotuner knob declaration (paddle_tpu.tuning), next to the kernel it
# tunes.  Replay is fingerprint-coherent by construction: the winning
# blocks land in the flash_attention OP ATTRS (layers.flash_attention
# resolves omitted block_q/block_k through tuned() under the autotune
# flag), so they are part of the Program content digest every compile-
# cache key hashes.  Search needs the chip: benchmark/longctx.py --sweep
# is the measurement driver.
register_tunable(
    "pallas/flash_attention", side="device",
    space={"block_q": (512, 1024, 2048), "block_k": (1024, 2048, 4096)},
    default={"block_q": 1024, "block_k": 1024},
    description="flash-attention Pallas tile shape: rows of Q per grid "
                "step and the K-stream slab; 2048-row tiles additionally "
                "need the scoped-VMEM limit raised "
                "(xla/scoped_vmem_limit_kib).",
    pending_hardware=True,
    decision_rule="flip the default only when the on-chip longctx sweep "
                  "shows >= 1.10x median ms/step over 1024x1024 at BOTH "
                  "32k and 64k tokens (paired-window discipline, "
                  "spread < gain)")

# Paged KV-cache gather for the decode slot pool (serving/decode.py):
# replace the contiguous [S, Tmax, D] slabs with fixed-size pages plus a
# per-slot page table, gathered into the attention tile by a Pallas
# kernel — the vLLM layout, removing the max-len * slots HBM reservation.
# On this CPU container the contiguous slabs are strictly better (the
# gather is pure overhead without HBM pressure), so the search is
# pre-registered pending hardware rather than fabricated here.
register_tunable(
    "pallas/paged_kv_gather", side="device",
    space={"page_size": (16, 32, 64, 128), "gather_block": (128, 256, 512)},
    default={"page_size": 64, "gather_block": 256},
    description="paged KV-cache layout for incremental decode: tokens "
                "per cache page and the rows-per-grid-step of the Pallas "
                "page-table gather feeding attention_with_cache.",
    pending_hardware=True,
    decision_rule="adopt paging only when the on-chip decode benchmark "
                  "shows >= 1.15x decode tokens/s over the contiguous "
                  "slabs at >= 50% slot occupancy with mixed-length "
                  "traces, OR the contiguous reservation exceeds 25% of "
                  "HBM at the serving config — below either bar the "
                  "gather is pure overhead and the slabs stay")


_mesh_detect_warned = False


def _in_manual_mesh_context() -> bool:
    """True when tracing inside a shard_map manual region (e.g. a
    pipeline stage body): entering another shard_map with a concrete mesh
    there is an error, so the sp routing must fall back to the
    device-global kernel.

    Detection is version-shimmed in :mod:`paddle_tpu.compat`
    (AxisType/get_abstract_mesh on new JAX, the trace-state axis env on
    old).  Only the nothing-worked case degrades, and loudly, once: a
    silent blanket except here would disable the nested-shard_map guard
    without anyone noticing until a cryptic trace error deep in sp
    routing."""
    global _mesh_detect_warned
    from ..compat import manual_axes
    axes = manual_axes()
    if axes is not None:
        return bool(axes)
    if not _mesh_detect_warned:
        _mesh_detect_warned = True
        import warnings
        warnings.warn(
            "paddle_tpu: manual-mesh detection failed on this JAX "
            "(compat.manual_axes knows no working API) — JAX API "
            "drift?  The nested-shard_map guard is disabled; "
            "flash_attention inside pipeline stage bodies may "
            "mis-route to ring attention.", RuntimeWarning, stacklevel=2)
    return False


@register_op("flash_attention")
def _flash_attention_op(ctx, ins, attrs):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    causal = attrs.get("causal", False)
    # First-class sequence parallelism: under a ShardedExecutor whose mesh
    # has sp>1, eligible self-attention lowers to ring attention over the
    # sp axis (parallel/ring_attention.py) — K/V circulate on ICI, memory
    # O(T/sp) — instead of one device-global attention.  Eligibility is
    # checked statically; ineligible shapes (cross-attention, ragged T)
    # fall back to the GSPMD whole-array kernel.
    sp = ctx.mesh_axis_size("sp")
    if (sp > 1 and attrs.get("sequence_parallel", True)
            and not _in_manual_mesh_context()
            and q.ndim in (3, 4) and q.shape[1] == k.shape[1]
            and q.shape[1] % sp == 0):
        from ..parallel.ring_attention import ring_attention_sharded
        q4, k4, v4 = (x[:, :, None, :] if x.ndim == 3 else x
                      for x in (q, k, v))
        out = ring_attention_sharded(
            q4, k4, v4, ctx.mesh, causal=causal,
            block_q=attrs.get("block_q", 1024),
            block_k=attrs.get("block_k", 1024),
            interpret=attrs.get("interpret", False))
        return {"Out": out[:, :, 0, :] if q.ndim == 3 else out}
    return {"Out": flash_attention(
        q, k, v,
        causal=causal,
        block_q=attrs.get("block_q", 1024),   # swept best at 16k AND 32k
        block_k=attrs.get("block_k", 1024),
        interpret=attrs.get("interpret", False))}


# ---------------------------------------------------------------------------
# Static shape/dtype rule: flash_attention is shape-preserving on Q.
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import ShapeError, dim_ok, first  # noqa: E402
from ..core.registry import register_shape_fn  # noqa: E402


@register_shape_fn("flash_attention")
def _flash_attention_shape(op, ins, attrs):
    q, k, v = first(ins, "Q"), first(ins, "K"), first(ins, "V")
    for name, o in (("K", k), ("V", v)):
        if q.shape is not None and o.shape is not None:
            if len(o.shape) != len(q.shape) or \
                    not dim_ok(q.shape[-1], o.shape[-1]):
                raise ShapeError(
                    f"flash_attention: Q {list(q.shape)} vs {name} "
                    f"{list(o.shape)} (rank or head dim mismatch)")
    return {"Out": q}


# Sharding propagation: flash_attention is shape-preserving on Q (the
# kernel runs per-shard under shard_map; batch/head sharding rides along).
from ..analysis.shard_prop import shard_same_as  # noqa: E402
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("flash_attention")(shard_same_as("Q"))
