"""Activation op lowerings.

The reference registers ~20 activations in one file (activation_op.h, and the
v1 registry activations/ActivationFunction.cpp).  All are trivially jnp/lax —
XLA fuses them into the producing matmul/conv, replacing the handwritten CUDA
elementwise kernels (hl_cpu_*/hl_cuda_*).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _unary(fn):
    def impl(ctx, ins, attrs):
        return {"Out": fn(ins["X"][0], attrs)}
    return impl


def _simple(fn):
    return _unary(lambda x, attrs: fn(x))


register_op("sigmoid")(_simple(jax.nn.sigmoid))
register_op("logsigmoid")(_simple(jax.nn.log_sigmoid))
register_op("tanh")(_simple(jnp.tanh))
register_op("relu")(_simple(jax.nn.relu))
register_op("relu6")(_unary(lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0))))
register_op("abs")(_simple(jnp.abs))
register_op("sqrt")(_simple(jnp.sqrt))
register_op("rsqrt")(_simple(jax.lax.rsqrt))
register_op("square")(_simple(jnp.square))
register_op("exp")(_simple(jnp.exp))
register_op("log")(_simple(jnp.log))
register_op("floor")(_simple(jnp.floor))
register_op("ceil")(_simple(jnp.ceil))
register_op("round")(_simple(jnp.round))
register_op("reciprocal")(_simple(lambda x: 1.0 / x))
register_op("softsign")(_simple(jax.nn.soft_sign))
register_op("softplus", "softrelu")(_simple(jax.nn.softplus))
register_op("sin")(_simple(jnp.sin))
register_op("cos")(_simple(jnp.cos))
register_op("gelu")(_simple(jax.nn.gelu))
register_op("silu", "swish")(_simple(jax.nn.silu))


@register_op("brelu")
def _brelu(ctx, ins, attrs):
    """v1 brelu: clip(x, t_min, t_max) (ActivationFunction.cpp brelu)."""
    return {"Out": jnp.clip(ins["X"][0], attrs.get("t_min", 0.0),
                            attrs.get("t_max", 24.0))}


@register_op("leaky_relu")
def _leaky_relu(ctx, ins, attrs):
    return {"Out": jax.nn.leaky_relu(ins["X"][0],
                                     attrs.get("alpha", 0.02))}


@register_op("elu")
def _elu(ctx, ins, attrs):
    return {"Out": jax.nn.elu(ins["X"][0], attrs.get("alpha", 1.0))}


@register_op("stanh")
def _stanh(ctx, ins, attrs):
    """scaled tanh: b * tanh(a * x) (activation_op.h STanh)."""
    a = attrs.get("scale_a", 2.0 / 3.0)
    b = attrs.get("scale_b", 1.7159)
    return {"Out": b * jnp.tanh(a * ins["X"][0])}


@register_op("hard_shrink")
def _hard_shrink(ctx, ins, attrs):
    x = ins["X"][0]
    t = attrs.get("threshold", 0.5)
    return {"Out": jnp.where(jnp.abs(x) > t, x, jnp.zeros_like(x))}


@register_op("soft_shrink", "softshrink")
def _soft_shrink(ctx, ins, attrs):
    x = ins["X"][0]
    lam = attrs.get("lambda", 0.5)
    return {"Out": jnp.where(x > lam, x - lam,
                             jnp.where(x < -lam, x + lam, jnp.zeros_like(x)))}


@register_op("thresholded_relu")
def _thresholded_relu(ctx, ins, attrs):
    x = ins["X"][0]
    t = attrs.get("threshold", 1.0)
    return {"Out": jnp.where(x > t, x, jnp.zeros_like(x))}


@register_op("hard_sigmoid")
def _hard_sigmoid(ctx, ins, attrs):
    x = ins["X"][0]
    slope = attrs.get("slope", 0.2)
    offset = attrs.get("offset", 0.5)
    return {"Out": jnp.clip(x * slope + offset, 0.0, 1.0)}


@register_op("prelu")
def _prelu(ctx, ins, attrs):
    """prelu_op: learned negative slope — mode all (scalar), channel
    (alpha [C], x [N,C,...]) or element (alpha = x.shape[1:])."""
    x = ins["X"][0]
    alpha = ins["Alpha"][0]
    if alpha.size > 1:
        if alpha.ndim == x.ndim - 1:            # element mode
            alpha = alpha.reshape((1,) + alpha.shape)
        else:                                   # channel mode
            alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": jnp.where(x >= 0, x, alpha * x)}


# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer): every activation maps
# X -> Out elementwise, so one same_as rule covers the whole file — the
# InferShape analog of activation_op.h's UnaryOpUnchangedInferShape.
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import same_as  # noqa: E402
from ..core.registry import register_shape_fn  # noqa: E402

register_shape_fn(
    "sigmoid", "logsigmoid", "tanh", "relu", "relu6", "abs", "sqrt",
    "rsqrt", "square", "exp", "log", "floor", "ceil", "round",
    "reciprocal", "softsign", "softplus", "softrelu", "sin", "cos",
    "gelu", "silu", "swish", "brelu", "leaky_relu", "elu", "stanh",
    "hard_shrink", "soft_shrink", "softshrink", "thresholded_relu",
    "hard_sigmoid", "prelu",
)(same_as("X"))

# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop): activations are
# elementwise, so outputs carry their input's per-dim sharding unchanged.
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import shard_same_as  # noqa: E402
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn(
    "sigmoid", "logsigmoid", "tanh", "relu", "relu6", "abs", "sqrt",
    "rsqrt", "square", "exp", "log", "floor", "ceil", "round",
    "reciprocal", "softsign", "softplus", "softrelu", "sin", "cos",
    "gelu", "silu", "swish", "brelu", "leaky_relu", "elu", "stanh",
    "hard_shrink", "soft_shrink", "softshrink", "thresholded_relu",
    "hard_sigmoid", "prelu",
)(shard_same_as("X"))
