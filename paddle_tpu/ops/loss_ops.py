"""Loss op lowerings (SURVEY §2.2 Losses; reference files hinge_loss_op.cc,
huber_loss_op.cc, log_loss_op.cc, margin_rank_loss_op.cc, rank_loss_op.cc,
smooth_l1_loss_op.cc, squared_l2_distance_op.cc, squared_l2_norm_op.cc,
sigmoid_cross_entropy_with_logits_op.cc, modified_huber_loss_op.cc,
cos_sim_op.cc, bilinear_tensor_product_op.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("hinge_loss")
def _hinge_loss(ctx, ins, attrs):
    logits, labels = ins["Logits"][0], ins["Labels"][0]
    y = 2.0 * labels.astype(logits.dtype) - 1.0
    return {"Loss": jnp.maximum(0.0, 1.0 - y * logits)}


@register_op("huber_loss")
def _huber_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    d = attrs.get("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    loss = jnp.where(a <= d, 0.5 * r * r, d * (a - 0.5 * d))
    return {"Out": loss, "Residual": r}


@register_op("log_loss")
def _log_loss(ctx, ins, attrs):
    p, label = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1.0 - label) * jnp.log(1.0 - p + eps)
    return {"Loss": loss}


@register_op("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    """loss = max(0, -label*(x1-x2) + margin)"""
    x1, x2, label = ins["X1"][0], ins["X2"][0], ins["Label"][0]
    m = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + m)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("rank_loss")
def _rank_loss(ctx, ins, attrs):
    """RankNet pairwise loss (rank_loss_op.cc)."""
    label, left, right = ins["Label"][0], ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": jnp.logaddexp(0.0, d) - label * d}


@register_op("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    d = x - y
    if "InsideWeight" in ins and ins["InsideWeight"]:
        d = d * ins["InsideWeight"][0]
    a = jnp.abs(d)
    l = jnp.where(a < 1.0 / s2, 0.5 * s2 * d * d, a - 0.5 / s2)
    if "OutsideWeight" in ins and ins["OutsideWeight"]:
        l = l * ins["OutsideWeight"][0]
    out = jnp.sum(l.reshape(l.shape[0], -1), axis=1, keepdims=True)
    return {"Out": out, "Diff": d}


@register_op("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    d = x - y
    out = jnp.sum(jnp.square(d).reshape(d.shape[0], -1), axis=1, keepdims=True)
    return {"Out": out, "sub_result": d}


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    return {"Out": jnp.sum(jnp.square(ins["X"][0])).reshape(1)}


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    # max(x,0) - x*z + log(1+exp(-|x|)) — stable form
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": loss}


@register_op("modified_huber_loss")
def _modified_huber(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    z = (2.0 * y - 1.0) * x
    loss = jnp.where(z >= 1.0, jnp.zeros_like(z),
                     jnp.where(z >= -1.0, jnp.square(1.0 - z), -4.0 * z))
    return {"Out": loss, "IntermediateVal": z}


@register_op("cos_sim")
def _cos_sim(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    return {"Out": out, "XNorm": xn, "YNorm": yn}


@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, ins, attrs):
    """out[:, k] = x @ W[k] @ y^T diag  (+ bias) — attention scoring block."""
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    # w: [K, dx, dy]; x: [N, dx]; y: [N, dy]
    out = jnp.einsum("nd,kde,ne->nk", x, w, y)
    if "Bias" in ins and ins["Bias"]:
        out = out + ins["Bias"][0]
    return {"Out": out}


@register_op("mse_loss")
def _mse_loss(ctx, ins, attrs):
    d = ins["X"][0] - ins["Y"][0]
    return {"Out": jnp.square(d)}


@register_op("kldiv_loss")
def _kldiv_loss(ctx, ins, attrs):
    x, target = ins["X"][0], ins["Target"][0]
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - x)
    return {"Loss": loss}


# ---------------------------------------------------------------------------
# LambdaRank (v1 lambda_cost; reference CostLayer.cpp:349-519 LambdaCost)
# ---------------------------------------------------------------------------
def _lambda_rank_group(o, s, n, k, max_sort_size):
    """One padded query group.  o: model scores [M]; s: relevance labels
    [M]; n: valid count.  Returns (ndcg scalar, lambda-grad [M] w.r.t. o).

    TPU-native redesign of the reference's CPU-only per-list loops
    (CostLayer.cpp:363-519): groups are padded to a static M, the pairwise
    lambda matrix is a masked [M, M] computation, and the whole batch maps
    over groups with vmap — no host loop, no ragged sort.  Matches the
    reference exactly: items ordered by LABEL desc, dcgDif uses the
    1/ln(i+2) position discounts, lambda_ij = -|dcgDif|/(1+exp(o_i-o_j)),
    grads normalized by maxDCG; NDCG@k gain is 2^label - 1.
    """
    M = o.shape[0]
    pos = jnp.arange(M)
    valid = pos < n
    neg = jnp.float32(-3.4e38)
    s_sort_key = jnp.where(valid, s, neg)
    o_sort_key = jnp.where(valid, o, neg)
    disc = 1.0 / jnp.log(pos.astype(jnp.float32) + 2.0)
    topk = (pos < k) & valid

    idx_l = jnp.argsort(-s_sort_key, stable=True)   # label-desc order
    s_sorted = jnp.take(s, idx_l)
    o_sorted = jnp.take(o, idx_l)
    gain_sorted = jnp.exp2(s_sorted) - 1.0
    max_dcg = jnp.sum(jnp.where(topk, gain_sorted * disc, 0.0))
    max_dcg = jnp.maximum(max_dcg, 1e-12)           # CHECK_GT analog

    idx_o = jnp.argsort(-o_sort_key, stable=True)   # model-desc order
    dcg = jnp.sum(jnp.where(topk, (jnp.exp2(jnp.take(s, idx_o)) - 1.0)
                            * disc, 0.0))
    ndcg = dcg / max_dcg

    sort_size = n if max_sort_size < 0 else jnp.minimum(max_sort_size, n)
    i, j = pos[:, None], pos[None, :]
    pair = (i < j) & (j < n) & (i < sort_size)
    g2 = jnp.exp2(s_sorted)
    diff2 = g2[:, None] - g2[None, :]
    dcg_dif = jnp.where(j < sort_size,
                        diff2 * (disc[:, None] - disc[None, :]),
                        diff2 * disc[:, None])
    lam = -jnp.abs(dcg_dif) / (1.0 + jnp.exp(o_sorted[:, None]
                                             - o_sorted[None, :]))
    lam = jnp.where(pair, lam, 0.0) / max_dcg
    g_sorted = jnp.sum(lam, axis=1) - jnp.sum(lam, axis=0)
    grad = jnp.zeros_like(o).at[idx_l].set(g_sorted)
    return ndcg, grad


@register_op("lambda_rank")
def _lambda_rank(ctx, ins, attrs):
    """Listwise LambdaRank over padded query groups.  Score: model outputs
    [B, M] (or [B, M, 1]), Label: relevance [B, M(,1)], @LEN companion on
    Score gives valid counts.  Out: per-group NDCG@k [B, 1] whose custom
    VJP is the lambda gradient — the forward value is the metric (as in
    the reference, which reports NDCG as the layer output) while training
    descends the lambda direction."""
    from .sequence_ops import _seq_lens_or_full

    o = ins["Score"][0]
    s = ins["Label"][0]
    if o.ndim == 3:
        o = o[:, :, 0]
    if s.ndim == 3:
        s = s[:, :, 0]
    s = jax.lax.stop_gradient(s.astype(jnp.float32))
    lens = _seq_lens_or_full(ctx, o, slot="Score")
    lens = jax.lax.stop_gradient(lens)
    k = int(attrs.get("ndcg_num", 5))
    mss = int(attrs.get("max_sort_size", -1))

    @jax.custom_vjp
    def f(o):
        ndcg, _ = jax.vmap(
            lambda oo, ss, nn: _lambda_rank_group(oo, ss, nn, k, mss)
        )(o, s, lens)
        return ndcg

    def fwd(o):
        ndcg, grad = jax.vmap(
            lambda oo, ss, nn: _lambda_rank_group(oo, ss, nn, k, mss)
        )(o, s, lens)
        return ndcg, grad

    def bwd(grad, g):
        return (grad * g[:, None],)

    f.defvjp(fwd, bwd)
    return {"Out": f(o.astype(jnp.float32))[:, None]}


@register_op("cross_entropy_over_beam")
def _cross_entropy_over_beam(ctx, ins, attrs):
    """Beam-level training cost (CrossEntropyOverBeam.cpp:19-120): per
    expansion step, cross-entropy of the gold candidate among the beam's
    candidate scores; summed over steps.  TPU-native static-shape form:
    each step is (scores [B,K], candidate ids [B,K], gold id [B]).  When
    the gold is IN the beam the softmax runs over exactly the K candidate
    paths (reference in-beam case, bitwise comparable); when it fell off,
    the reference appends the gold as an extra path with its true path
    score — statically approximated here by a virtual (K+1)-th slot scored
    min(scores)-4 (a just-below-the-frontier path), which preserves the
    training signal (push gold up, beam down) with static shapes.
    """
    total = None
    for s, c, g in zip(ins["Scores"], ins["Cands"], ins["Gold"]):
        s = s.reshape(s.shape[0], -1).astype(jnp.float32)
        c = c.reshape(c.shape[0], -1)
        g = g.reshape(-1).astype(c.dtype)
        K = s.shape[1]
        match = c == g[:, None]
        in_beam = match.any(axis=1)
        pos = jnp.argmax(match, axis=1)
        extra = jnp.where(in_beam, -1e30, jnp.min(s, axis=1) - 4.0)
        aug = jnp.concatenate([s, extra[:, None]], axis=1)
        logp = jax.nn.log_softmax(aug, axis=1)
        idx = jnp.where(in_beam, pos, K)
        ce = -jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
        total = ce if total is None else total + ce
    return {"Out": total[:, None]}


# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer).
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import (ShapeError, VarInfo, dim_ok,  # noqa: E402
                                    first, same_as, shapes_compatible)
from ..core.registry import register_shape_fn  # noqa: E402

register_shape_fn("hinge_loss")(same_as("Logits", out="Loss"))
register_shape_fn("log_loss")(same_as("Predicted", out="Loss"))
register_shape_fn("sigmoid_cross_entropy_with_logits")(same_as("X"))
register_shape_fn("mse_loss")(same_as("X"))
register_shape_fn("kldiv_loss")(same_as("X", out="Loss"))
register_shape_fn("rank_loss")(same_as("Left"))
register_shape_fn("huber_loss")(same_as("X", also=("Residual",)))
register_shape_fn("modified_huber_loss")(
    same_as("X", also=("IntermediateVal",)))
register_shape_fn("margin_rank_loss")(same_as("X1", also=("Activated",)))


def _rowwise(x, extra=1):
    b = x.shape[0] if x.shape is not None else -1
    return VarInfo((b, extra), x.dtype)


@register_shape_fn("smooth_l1_loss")
def _smooth_l1_shape(op, ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    if not shapes_compatible(x.shape, y.shape):
        raise ShapeError(
            f"smooth_l1_loss: X {list(x.shape)} vs Y {list(y.shape)}")
    return {"Out": _rowwise(x), "Diff": x}


@register_shape_fn("squared_l2_distance")
def _squared_l2_distance_shape(op, ins, attrs):
    x = first(ins, "X")
    return {"Out": _rowwise(x), "sub_result": x}


@register_shape_fn("squared_l2_norm")
def _squared_l2_norm_shape(op, ins, attrs):
    return {"Out": VarInfo((1,), first(ins, "X").dtype)}


@register_shape_fn("cos_sim")
def _cos_sim_shape(op, ins, attrs):
    x = first(ins, "X")
    n = _rowwise(x)
    return {"Out": n, "XNorm": n, "YNorm": n}


@register_shape_fn("bilinear_tensor_product")
def _bilinear_tp_shape(op, ins, attrs):
    x, w = first(ins, "X"), first(ins, "Weight")
    if x.shape is None or w.shape is None:
        return {"Out": VarInfo(None, x.dtype)}
    if x.shape[-1] >= 0 and w.shape[1] >= 0 and \
            not dim_ok(x.shape[-1], w.shape[1]):
        raise ShapeError(
            f"bilinear_tensor_product: X dim {x.shape[-1]} vs Weight dx "
            f"{w.shape[1]}")
    return {"Out": VarInfo((x.shape[0], w.shape[0]), x.dtype)}


@register_shape_fn("lambda_rank")
def _lambda_rank_shape(op, ins, attrs):
    return {"Out": _rowwise(first(ins, "Score"))}


@register_shape_fn("cross_entropy_over_beam")
def _ce_over_beam_shape(op, ins, attrs):
    return {"Out": _rowwise(first(ins, "Scores"))}


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop): loss heads keep the
# batch sharding; elementwise losses are shape-preserving.
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import (shard_batch_only,  # noqa: E402
                                   shard_replicated, shard_same_as)
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("hinge_loss")(shard_same_as("Logits", out="Loss"))
register_shard_fn("log_loss")(shard_same_as("Predicted", out="Loss"))
register_shard_fn("sigmoid_cross_entropy_with_logits", "mse_loss")(
    shard_same_as("X"))
register_shard_fn("kldiv_loss")(shard_same_as("X", out="Loss"))
register_shard_fn("rank_loss")(shard_same_as("Left"))
register_shard_fn("huber_loss")(shard_same_as("X", also=("Residual",)))
register_shard_fn("margin_rank_loss")(
    shard_same_as("X1", also=("Activated",)))
register_shard_fn("smooth_l1_loss")(shard_batch_only("X"))
register_shard_fn("squared_l2_distance")(shard_batch_only("X"))
register_shard_fn("cos_sim")(shard_batch_only("X"))
register_shard_fn("squared_l2_norm")(shard_replicated("Out"))
