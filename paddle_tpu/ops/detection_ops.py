"""Detection op lowerings (reference: roi_pool_op, detection_output_op +
operators/math/detection_util.h; v1 layers MultiBoxLoss, DetectionOutput,
PriorBox, ROIPool)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("roi_pool")
def _roi_pool(ctx, ins, attrs):
    """roi_pool_op: max-pool each ROI to a fixed [ph, pw] grid.

    X [N,C,H,W]; ROIs [R,5] = (batch_idx, x1, y1, x2, y2) in input scale.
    Vectorized with vmap over ROIs — one fused gather/reduce program.
    """
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = attrs["pooled_height"]
    pw = attrs["pooled_width"]
    scale = attrs.get("spatial_scale", 1.0)
    N, C, H, W = x.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale)
        y1 = jnp.round(roi[2] * scale)
        x2 = jnp.round(roi[3] * scale)
        y2 = jnp.round(roi[4] * scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        img = x[b]                                     # [C,H,W]
        hs = jnp.arange(H, dtype=jnp.float32)
        ws = jnp.arange(W, dtype=jnp.float32)
        # bin index of each pixel, -1 if outside roi
        bin_h = jnp.floor((hs - y1) / (rh / ph))
        bin_w = jnp.floor((ws - x1) / (rw / pw))
        valid_h = (hs >= y1) & (hs <= y2)
        valid_w = (ws >= x1) & (ws <= x2)
        oh = jnp.clip(bin_h, 0, ph - 1).astype(jnp.int32)
        ow = jnp.clip(bin_w, 0, pw - 1).astype(jnp.int32)
        neg = jnp.asarray(-3.4e38, x.dtype)
        valid = valid_h[None, :, None] & valid_w[None, None, :]
        masked = jnp.where(valid, img, neg)
        out = jnp.full((C, ph, pw), neg, x.dtype)
        out = out.at[:, oh[:, None], ow[None, :]].max(masked)
        # Argmax (roi_pool_op.h argmax data): flat h*W+w index of each
        # bin's max — a pixel is its bin's argmax iff it attains the bin
        # max; ties resolve to the smallest flat index via scatter-min
        flat = (hs[:, None] * W + ws[None, :]).astype(jnp.int64)  # [H,W]
        is_max = valid & (img == out[:, oh[:, None], ow[None, :]])
        cand = jnp.where(is_max, flat[None], jnp.int64(H * W))
        amax = jnp.full((C, ph, pw), jnp.int64(H * W))
        amax = amax.at[:, oh[:, None], ow[None, :]].min(cand)
        empty = out <= neg / 2
        return (jnp.where(empty, 0.0, out),
                jnp.where(empty | (amax >= H * W), jnp.int64(-1), amax))

    out, amax = jax.vmap(one_roi)(rois.astype(jnp.float32))
    return {"Out": out, "Argmax": amax}


@register_op("prior_box")
def _prior_box(ctx, ins, attrs):
    """SSD prior boxes for a feature map (v1 PriorBox layer)."""
    feat, img = ins["Input"][0], ins["Image"][0]
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ars = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", True)
    clip = attrs.get("clip", True)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_h = ih / fh
    step_w = iw / fw
    full_ars = []
    for ar in ars:
        full_ars.append(ar)
        if flip and ar != 1.0:
            full_ars.append(1.0 / ar)
    boxes = []
    for ms in min_sizes:
        boxes.append((ms, ms))
        for mx in max_sizes:
            s = (ms * mx) ** 0.5
            boxes.append((s, s))
        for ar in full_ars:
            if ar == 1.0:
                continue
            boxes.append((ms * ar ** 0.5, ms / ar ** 0.5))
    cy = (jnp.arange(fh) + 0.5) * step_h
    cx = (jnp.arange(fw) + 0.5) * step_w
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")
    out = []
    for bw, bh in boxes:
        out.append(jnp.stack([
            (cxg - bw / 2) / iw, (cyg - bh / 2) / ih,
            (cxg + bw / 2) / iw, (cyg + bh / 2) / ih], axis=-1))
    prior = jnp.stack(out, axis=2).reshape(fh, fw, len(boxes), 4)
    if clip:
        prior = jnp.clip(prior, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), prior.shape)
    return {"Boxes": prior, "Variances": var}


@register_op("box_coder")
def _box_coder(ctx, ins, attrs):
    """decode_center_size box regression (detection_util.h)."""
    prior = ins["PriorBox"][0].reshape(-1, 4)
    pvar = ins["PriorBoxVar"][0].reshape(-1, 4) if "PriorBoxVar" in ins and \
        ins["PriorBoxVar"] else jnp.ones_like(prior)
    target = ins["TargetBox"][0]
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = (prior[:, 0] + prior[:, 2]) / 2
    pcy = (prior[:, 1] + prior[:, 3]) / 2
    t = target.reshape(-1, 4)
    cx = pvar[:, 0] * t[:, 0] * pw + pcx
    cy = pvar[:, 1] * t[:, 1] * ph + pcy
    w = jnp.exp(pvar[:, 2] * t[:, 2]) * pw
    h = jnp.exp(pvar[:, 3] * t[:, 3]) * ph
    out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1)
    return {"OutputBox": out.reshape(target.shape)}


@register_op("iou_similarity")
def _iou_similarity(ctx, ins, attrs):
    a = ins["X"][0].reshape(-1, 4)
    b = ins["Y"][0].reshape(-1, 4)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return {"Out": inter / jnp.maximum(area_a[:, None] + area_b[None, :]
                                       - inter, 1e-10)}


@register_op("ssd_loss")
def _ssd_loss(ctx, ins, attrs):
    """MultiBoxLoss (gserver/layers/MultiBoxLoss.cpp; fluid ssd_loss):
    prior-to-ground-truth matching, smooth-L1 localization loss on matched
    priors, softmax confidence loss with 3:1 hard negative mining.

    Static-shape TPU design: ground truth arrives PADDED [N, M, ...] with
    label < 0 marking padding rows (no LoD) — matching, mining, and both
    losses are vmapped batch programs with masks; the ragged reference
    pipeline (bipartite match + CPU sort) becomes one fused XLA program.

    Inputs: Location [N,P,4] predicted encodings; Confidence [N,P,C]
    logits; GTBox [N,M,4] corner-form; GTLabel [N,M] int (pad<0);
    PriorBox [P,4]; PriorBoxVar [P,4] (optional).
    Output Loss [N,1].
    """
    from jax import lax

    loc = ins["Location"][0]
    conf = ins["Confidence"][0]
    gt_box = ins["GTBox"][0]
    gt_label = ins["GTLabel"][0].astype(jnp.int32)
    if gt_label.ndim == 3:
        gt_label = gt_label.squeeze(-1)
    prior = ins["PriorBox"][0].reshape(-1, 4)
    pvar = (ins["PriorBoxVar"][0].reshape(-1, 4)
            if ins.get("PriorBoxVar") else
            jnp.broadcast_to(jnp.asarray([0.1, 0.1, 0.2, 0.2], loc.dtype),
                             prior.shape))
    overlap_t = attrs.get("overlap_threshold", 0.5)
    neg_ratio = attrs.get("neg_pos_ratio", 3.0)
    loc_w = attrs.get("loc_loss_weight", 1.0)
    conf_w = attrs.get("conf_loss_weight", 1.0)
    background = int(attrs.get("background_label", 0))
    N, P, C = conf.shape
    M = gt_box.shape[1]

    pw = prior[:, 2] - prior[:, 0]
    ph_ = prior[:, 3] - prior[:, 1]
    pcx = (prior[:, 0] + prior[:, 2]) / 2
    pcy = (prior[:, 1] + prior[:, 3]) / 2

    def encode(gt):                                   # [M,4] -> [M,P,4]
        gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-10)
        gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-10)
        gcx = (gt[:, 0] + gt[:, 2]) / 2
        gcy = (gt[:, 1] + gt[:, 3]) / 2
        tx = (gcx[:, None] - pcx[None]) / pw[None] / pvar[None, :, 0]
        ty = (gcy[:, None] - pcy[None]) / ph_[None] / pvar[None, :, 1]
        tw = jnp.log(gw[:, None] / pw[None]) / pvar[None, :, 2]
        th = jnp.log(gh[:, None] / ph_[None]) / pvar[None, :, 3]
        return jnp.stack([tx, ty, tw, th], axis=-1)

    def iou_mp(gt):                                   # [M,4] -> [M,P]
        lt = jnp.maximum(gt[:, None, :2], prior[None, :, :2])
        rb = jnp.minimum(gt[:, None, 2:], prior[None, :, 2:])
        wh = jnp.maximum(rb - lt, 0.0)
        inter = wh[..., 0] * wh[..., 1]
        ag = (gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1])
        ap = pw * ph_
        return inter / jnp.maximum(ag[:, None] + ap[None] - inter, 1e-10)

    def one(loc_i, conf_i, gtb, gtl):
        valid_gt = gtl >= 0                           # [M]
        iou = jnp.where(valid_gt[:, None], iou_mp(gtb), -1.0)   # [M,P]
        # per-prior best gt (per-prediction matching) ...
        best_gt = jnp.argmax(iou, axis=0)             # [P]
        best_iou = jnp.max(iou, axis=0)
        # ... plus bipartite pass: each gt claims its single best prior
        # (MultiBoxLoss.cpp matchBBox semantics)
        best_prior = jnp.argmax(iou, axis=1)          # [M]
        # scatter-max so padding gts (claim=-1/False) can't overwrite a
        # real gt that claimed the same prior index
        forced = jnp.zeros((P,), bool).at[best_prior].max(valid_gt)
        forced_gt = jnp.full((P,), -1, jnp.int32).at[best_prior].max(
            jnp.where(valid_gt, jnp.arange(M, dtype=jnp.int32), -1))
        pos = forced | (best_iou >= overlap_t)
        match = jnp.where(forced_gt >= 0, forced_gt,
                          best_gt.astype(jnp.int32))
        num_pos = jnp.sum(pos)

        # localization: smooth-L1 between predicted and encoded target
        targets = encode(gtb)                         # [M,P,4]
        tgt = targets[match, jnp.arange(P)]           # [P,4]
        d = loc_i - tgt
        ad = jnp.abs(d)
        smooth = jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5)
        loc_loss = jnp.sum(jnp.where(pos[:, None], smooth, 0.0))

        # confidence: softmax CE vs matched label (background for negs)
        tgt_cls = jnp.where(pos, gtl[match], background)
        logp = jax.nn.log_softmax(conf_i, axis=-1)
        ce = -jnp.take_along_axis(logp, tgt_cls[:, None], axis=1)[:, 0]
        # hard negative mining: top (neg_ratio * num_pos) negatives by loss
        neg_ce = jnp.where(pos, -jnp.inf, ce)
        order = jnp.argsort(-neg_ce)                  # desc
        rank = jnp.zeros((P,), jnp.int32).at[order].set(
            jnp.arange(P, dtype=jnp.int32))
        num_neg = jnp.minimum((neg_ratio * num_pos).astype(jnp.int32),
                              P - num_pos)
        neg = (~pos) & (rank < num_neg)
        conf_loss = jnp.sum(jnp.where(pos | neg, ce, 0.0))

        denom = jnp.maximum(num_pos.astype(loc_i.dtype), 1.0)
        return (loc_w * loc_loss + conf_w * conf_loss) / denom

    loss = jax.vmap(one)(loc, conf, gt_box, gt_label)
    return {"Loss": loss[:, None]}


@register_op("multiclass_nms", "detection_output")
def _detection_output(ctx, ins, attrs):
    """detection_output_op (math/detection_util.h GetDetectionOutput):
    decode + per-class NMS, static-shape TPU version.

    Inputs: Scores [N, num_priors, C] (post-softmax), BBoxes
    [N, num_priors, 4] (decoded corner-form boxes).  Greedy NMS runs as a
    fixed-length fori_loop with masking — no dynamic shapes; suppressed or
    sub-threshold slots return label -1 (the reference emits a ragged
    LoDTensor; here the fixed [N, keep_top_k, 6] tensor carries (label,
    score, x1, y1, x2, y2) rows padded with -1).
    """
    from jax import lax

    scores, boxes = ins["Scores"][0], ins["BBoxes"][0]
    score_thresh = attrs.get("score_threshold", 0.01)
    nms_thresh = attrs.get("nms_threshold", 0.45)
    nms_top_k = int(attrs.get("nms_top_k", 64))
    keep_top_k = int(attrs.get("keep_top_k", 16))
    background = int(attrs.get("background_label", 0))
    N, P, C = scores.shape

    def iou(b, ref):
        x1 = jnp.maximum(b[..., 0], ref[..., 0])
        y1 = jnp.maximum(b[..., 1], ref[..., 1])
        x2 = jnp.minimum(b[..., 2], ref[..., 2])
        y2 = jnp.minimum(b[..., 3], ref[..., 3])
        inter = jnp.clip(x2 - x1, 0) * jnp.clip(y2 - y1, 0)
        area = lambda v: jnp.clip(v[..., 2] - v[..., 0], 0) * \
            jnp.clip(v[..., 3] - v[..., 1], 0)
        return inter / jnp.maximum(area(b) + area(ref) - inter, 1e-10)

    def nms_one_class(cls_scores, cls_boxes):
        k = min(nms_top_k, P)
        top_s, top_i = lax.top_k(cls_scores, k)
        cand = cls_boxes[top_i]                       # [k,4]
        alive = top_s > score_thresh

        def body(i, keep):
            ref = cand[i]
            sup = (iou(cand, ref[None]) > nms_thresh) & \
                  (jnp.arange(k) > i) & keep[i]
            return keep & ~sup
        keep = lax.fori_loop(0, k, body, alive)
        return top_s * keep, cand, keep

    def one_image(s, b):
        all_s, all_b, all_l = [], [], []
        for c in range(C):
            if c == background:
                continue
            ks, kb, keep = nms_one_class(s[:, c], b)
            all_s.append(jnp.where(keep, ks, -1.0))
            all_b.append(kb)
            all_l.append(jnp.full(ks.shape, c, jnp.float32))
        cs = jnp.concatenate(all_s)
        cb = jnp.concatenate(all_b)
        cl = jnp.concatenate(all_l)
        k2 = min(keep_top_k, cs.shape[0])
        fs, fi = lax.top_k(cs, k2)
        lab = jnp.where(fs > score_thresh, cl[fi], -1.0)
        row = jnp.concatenate([lab[:, None], fs[:, None], cb[fi]], axis=1)
        if k2 < keep_top_k:
            row = jnp.pad(row, ((0, keep_top_k - k2), (0, 0)),
                          constant_values=-1.0)
        return row

    out = jax.vmap(one_image)(scores, boxes)
    return {"Out": out}


# ---------------------------------------------------------------------------
# Static shape/dtype rules.  The detection lowerings above are static-shape
# TPU redesigns (padded ground truth, fixed keep_top_k NMS slabs) — so
# unlike the reference's ragged LoD outputs their shapes ARE statically
# known, and each op gets an exact rule mirroring its lowering instead of a
# SHAPE_INFER_ALLOWLIST entry.
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import ShapeError, VarInfo, first  # noqa: E402
from ..core.registry import register_shape_fn  # noqa: E402


@register_shape_fn("iou_similarity")
def _iou_similarity_shape(op, ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    for name, v in (("X", x), ("Y", y)):
        if v.shape is not None and len(v.shape) >= 1 and \
                v.shape[-1] >= 0 and v.shape[-1] != 4:
            raise ShapeError(
                f"iou_similarity: {name} boxes must be [*, 4], got "
                f"{list(v.shape)}")
    n = x.shape[0] if x.shape is not None else -1
    m = y.shape[0] if y.shape is not None else -1
    return {"Out": VarInfo((n, m), x.dtype)}


@register_shape_fn("roi_pool")
def _roi_pool_shape(op, ins, attrs):
    x, rois = first(ins, "X"), first(ins, "ROIs")
    if rois.shape is not None and len(rois.shape) == 2 and \
            rois.shape[-1] >= 0 and rois.shape[-1] != 5:
        raise ShapeError(
            f"roi_pool: ROIs must be [R, 5] (batch_idx, x1, y1, x2, y2), "
            f"got {list(rois.shape)}")
    r = rois.shape[0] if rois.shape is not None else -1
    c = x.shape[1] if x.shape is not None and len(x.shape) == 4 else -1
    shape = (r, c, int(attrs["pooled_height"]), int(attrs["pooled_width"]))
    return {"Out": VarInfo(shape, x.dtype),
            "Argmax": VarInfo(shape, "int64")}


@register_shape_fn("prior_box")
def _prior_box_shape(op, ins, attrs):
    feat = first(ins, "Input")
    min_sizes = list(attrs["min_sizes"])
    max_sizes = list(attrs.get("max_sizes", []))
    ars = list(attrs.get("aspect_ratios", [1.0]))
    flip = attrs.get("flip", True)
    # mirror the lowering's box enumeration exactly
    full_ars = []
    for ar in ars:
        full_ars.append(ar)
        if flip and ar != 1.0:
            full_ars.append(1.0 / ar)
    nb = len(min_sizes) * (
        1 + len(max_sizes) + sum(1 for ar in full_ars if ar != 1.0))
    fh = feat.shape[2] if feat.shape is not None and \
        len(feat.shape) == 4 else -1
    fw = feat.shape[3] if feat.shape is not None and \
        len(feat.shape) == 4 else -1
    dt = feat.dtype if feat.dtype is not None else "float32"
    info = VarInfo((fh, fw, nb, 4), dt)
    return {"Boxes": info, "Variances": info}


@register_shape_fn("box_coder")
def _box_coder_shape(op, ins, attrs):
    prior, target = first(ins, "PriorBox"), first(ins, "TargetBox")
    for name, v in (("PriorBox", prior), ("TargetBox", target)):
        if v.shape is not None and len(v.shape) >= 1 and \
                v.shape[-1] >= 0 and v.shape[-1] != 4:
            raise ShapeError(
                f"box_coder: {name} must be [*, 4], got {list(v.shape)}")
    return {"OutputBox": target}


@register_shape_fn("ssd_loss")
def _ssd_loss_shape(op, ins, attrs):
    conf, loc = first(ins, "Confidence"), first(ins, "Location")
    if conf.shape is not None and len(conf.shape) != 3:
        raise ShapeError(
            f"ssd_loss: Confidence must be [N, P, C], got "
            f"{list(conf.shape)}")
    if loc.shape is not None and len(loc.shape) >= 1 and \
            loc.shape[-1] >= 0 and loc.shape[-1] != 4:
        raise ShapeError(
            f"ssd_loss: Location must be [N, P, 4], got "
            f"{list(loc.shape)}")
    n = conf.shape[0] if conf.shape is not None else \
        (loc.shape[0] if loc.shape is not None else -1)
    dt = loc.dtype if loc.dtype is not None else conf.dtype
    return {"Loss": VarInfo((n, 1), dt)}


@register_shape_fn("multiclass_nms", "detection_output")
def _detection_output_shape(op, ins, attrs):
    scores = first(ins, "Scores")
    if scores.shape is not None and len(scores.shape) != 3:
        raise ShapeError(
            f"detection_output: Scores must be [N, num_priors, C], got "
            f"{list(scores.shape)}")
    n = scores.shape[0] if scores.shape is not None else -1
    keep = int(attrs.get("keep_top_k", 16))
    dt = scores.dtype if scores.dtype is not None else "float32"
    return {"Out": VarInfo((n, keep, 6), dt)}


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop): detection heads keep
# the image/ROI batch sharding; priors replicate (they are per-feature-map
# constants).
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import (shard_batch_only,  # noqa: E402
                                   shard_replicated)
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("prior_box")(shard_replicated("Boxes", "Variances"))
register_shard_fn("iou_similarity", "box_coder")(shard_replicated(
    "Out", "OutputBox"))
register_shard_fn("ssd_loss", "multiclass_nms", "detection_output",
                  "roi_pool")(shard_batch_only(
                      "Location", out="Loss",
                      fallbacks=("Scores", "X"),
                      also=("Out", "Argmax")))
