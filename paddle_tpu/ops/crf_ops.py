"""Structured-prediction ops: linear-chain CRF, Viterbi decoding, CTC loss,
chunk/edit-distance evaluation.

Reference: linear_chain_crf_op.cc + crf_decoding_op.cc (fluid),
LinearChainCRF.cpp / CRFLayer.cpp (v1), WarpCTCLayer.cpp + warpctc wrapper
(hl_warpctc_wrap.cc), chunk_eval_op.cc, edit_distance_op.cc.

TPU-native: the forward/Viterbi/CTC recursions are lax.scan programs in
log-space over the padded+lengths batch — fully differentiable via jax.vjp,
so there is no handwritten backward (the reference implements analytic
gradients in C++; warp-ctc is an external CUDA lib).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .sequence_ops import _mask, _seq_lens_or_full

NEG = -1e30


@register_op("linear_chain_crf")
def _linear_chain_crf(ctx, ins, attrs):
    """Emission [B,T,D]; Transition [D+2,D] (row 0: start, row 1: end,
    rows 2..: pairwise w[prev, cur]); Label [B,T] or [B,T,1].
    LogLikelihood [B,1] (negative log-likelihood, matching the reference's
    use as a minimized cost via its sign convention: it returns -logp)."""
    em = ins["Emission"][0]
    trans = ins["Transition"][0]
    label = ins["Label"][0].astype(jnp.int32)
    if label.ndim == 3:
        label = label.squeeze(-1)
    lens = _seq_lens_or_full(ctx, em, slot="Emission")
    B, T, D = em.shape
    start, end, w = trans[0], trans[1], trans[2:]
    m = _mask(lens, T, em.dtype)                      # [B,T]

    # partition function: alpha recursion in log space
    def fwd(alpha, inp):
        e_t, m_t = inp                                # [B,D], [B]
        scores = alpha[:, :, None] + w[None] + e_t[:, None, :]
        new = jax.nn.logsumexp(scores, axis=1)
        keep = m_t[:, None]
        return keep * new + (1 - keep) * alpha, None

    alpha0 = start[None] + em[:, 0]
    em_t = jnp.swapaxes(em, 0, 1)
    alpha, _ = lax.scan(fwd, alpha0, (em_t[1:], m.T[1:]))
    logZ = jax.nn.logsumexp(alpha + end[None], axis=1)   # [B]

    # gold score
    t_idx = jnp.arange(T)
    gold_em = jnp.take_along_axis(em, label[..., None], axis=2).squeeze(-1)
    gold_em = jnp.sum(gold_em * m, axis=1)
    prev = label[:, :-1]
    cur = label[:, 1:]
    pair = w[prev, cur] * m[:, 1:]
    gold_tr = jnp.sum(pair, axis=1)
    last = jnp.take_along_axis(label, jnp.maximum(lens - 1, 0)[:, None],
                               axis=1).squeeze(1)
    gold = gold_em + gold_tr + start[label[:, 0]] + end[last]
    nll = (logZ - gold)[:, None]
    ctx.set_len(ctx.op.outputs["LogLikelihood"][0],
                jnp.ones((B,), jnp.int32))
    return {"LogLikelihood": nll, "Alpha": alpha,
            "EmissionExps": jnp.exp(em), "TransitionExps": jnp.exp(trans)}


@register_op("crf_decoding")
def _crf_decoding(ctx, ins, attrs):
    """Viterbi decode (crf_decoding_op.cc).  With Label given, emits
    correctness indicators like the reference."""
    em = ins["Emission"][0]
    trans = ins["Transition"][0]
    lens = _seq_lens_or_full(ctx, em, slot="Emission")
    B, T, D = em.shape
    start, end, w = trans[0], trans[1], trans[2:]
    m = _mask(lens, T, em.dtype)

    def fwd(carry, inp):
        score = carry
        e_t, m_t = inp
        cand = score[:, :, None] + w[None]
        best_prev = jnp.argmax(cand, axis=1)
        new = jnp.max(cand, axis=1) + e_t
        keep = m_t[:, None]
        score_out = keep * new + (1 - keep) * score
        return score_out, best_prev.astype(jnp.int32)

    score0 = start[None] + em[:, 0]
    em_t = jnp.swapaxes(em, 0, 1)
    final, backptr = lax.scan(fwd, score0, (em_t[1:], m.T[1:]))
    final = final + end[None]
    last_tag = jnp.argmax(final, axis=1).astype(jnp.int32)   # [B]

    # backtrace from each sequence's last position
    def back(carry, inp):
        tag, t = carry
        bp_t, step = inp  # bp for transition into position step+1
        # active if step+1 <= len-1  i.e. step < len-1
        active = (step < lens - 1)
        prev = bp_t[jnp.arange(B), tag]
        tag_new = jnp.where(active, prev, tag)
        return (tag_new, t - 1), tag_new

    steps = jnp.arange(T - 2, -1, -1)
    (_, _), tags_rev = lax.scan(
        back, (last_tag, T - 2), (backptr[::-1], steps))
    # tags_rev[i] is the tag at position steps[i]; build full path
    path = jnp.concatenate([tags_rev[::-1].T, last_tag[:, None]], axis=1)
    # positions beyond len-1 hold garbage; mask to 0
    path = jnp.where(m.astype(bool), path, 0)
    # reference writes the tag at position len-1 = last_tag:
    path = jnp.where(
        (jnp.arange(T)[None] == (lens - 1)[:, None]), last_tag[:, None], path)
    out_name = ctx.op.outputs["ViterbiPath"][0]
    ctx.set_len(out_name, lens)
    out = {"ViterbiPath": path.astype(jnp.int64)}
    if "Label" in ctx.op.inputs and ctx.op.inputs["Label"]:
        label = ins["Label"][0].astype(jnp.int64)
        if label.ndim == 3:
            label = label.squeeze(-1)
        out["ViterbiPath"] = (path == label).astype(jnp.int64) * \
            m.astype(jnp.int64)
    return out


@register_op("warpctc")
def _warpctc(ctx, ins, attrs):
    """CTC loss via the standard alpha recursion in log space.

    Logits [B,T,C] (pre-softmax); Label [B,L] padded with lens companion (or
    -1 padding).  Returns Loss [B,1].  Replaces the external warp-ctc CUDA
    library with a scan the XLA scheduler pipelines.
    """
    logits = ins["Logits"][0]
    label = ins["Label"][0].astype(jnp.int32)
    if label.ndim == 3:
        label = label.squeeze(-1)
    blank = attrs.get("blank", 0)
    B, T, C = logits.shape
    L = label.shape[1]
    in_lens = _seq_lens_or_full(ctx, logits, slot="Logits")
    lab_lens = ctx.get_len(ctx.op.inputs["Label"][0])
    if lab_lens is None:
        lab_lens = jnp.sum((label >= 0).astype(jnp.int32), axis=1)
    label = jnp.where(label < 0, 0, label)

    logp = jax.nn.log_softmax(logits, axis=-1)
    S = 2 * L + 1
    # extended label sequence: blank l1 blank l2 ... blank
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    s_pos = jnp.arange(S)
    valid_s = s_pos[None, :] < (2 * lab_lens + 1)[:, None]
    # can skip from s-2 when ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], 1)
    can_skip = (s_pos[None, :] % 2 == 1) & (ext != ext_m2)

    def step(alpha, inp):
        lp_t, t = inp                                  # [B,C], scalar
        e = jnp.take_along_axis(lp_t, ext, axis=1)     # [B,S]
        a_m1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
        a_m2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1)
        a_m2 = jnp.where(can_skip, a_m2, NEG)
        new = jnp.logaddexp(jnp.logaddexp(alpha, a_m1), a_m2) + e
        new = jnp.where(valid_s, new, NEG)
        active = (t < in_lens)[:, None]
        return jnp.where(active, new, alpha), None

    alpha0 = jnp.full((B, S), NEG)
    e0 = jnp.take_along_axis(logp[:, 0], ext, axis=1)
    alpha0 = alpha0.at[:, 0].set(e0[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(lab_lens > 0, e0[:, 1], NEG))
    lp_t = jnp.swapaxes(logp, 0, 1)
    alpha, _ = lax.scan(step, alpha0, (lp_t[1:], jnp.arange(1, T)))
    end1 = jnp.take_along_axis(alpha, (2 * lab_lens)[:, None], axis=1)
    end2 = jnp.take_along_axis(alpha, jnp.maximum(2 * lab_lens - 1, 0)[:, None],
                               axis=1)
    ll = jnp.logaddexp(end1, end2).squeeze(1)
    loss = -ll[:, None]
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(in_lens, 1)[:, None].astype(loss.dtype)
    return {"Loss": loss}


@register_op("edit_distance")
def _edit_distance(ctx, ins, attrs):
    """edit_distance_op: Levenshtein distance between hyp and ref id rows."""
    hyp = ins["Hyps"][0].astype(jnp.int32)
    ref = ins["Refs"][0].astype(jnp.int32)
    if hyp.ndim == 3:
        hyp = hyp.squeeze(-1)
    if ref.ndim == 3:
        ref = ref.squeeze(-1)
    h_lens = ctx.get_len(ctx.op.inputs["Hyps"][0])
    r_lens = ctx.get_len(ctx.op.inputs["Refs"][0])
    B, H = hyp.shape
    R = ref.shape[1]
    if h_lens is None:
        h_lens = jnp.full((B,), H, jnp.int32)
    if r_lens is None:
        r_lens = jnp.full((B,), R, jnp.int32)

    def row(carry, inp):
        prev = carry                                   # [B, R+1]
        h_tok, i = inp
        first = jnp.full((B, 1), 0, jnp.int32) + i + 1
        sub = prev[:, :-1] + (ref != h_tok[:, None]).astype(jnp.int32)
        # dp scan across the row (sequential in R): use associative min trick
        # simple loop over R (static, small label lengths)
        def col(c, j):
            dele = prev[:, j + 1] + 1
            ins_ = c + 1
            best = jnp.minimum(jnp.minimum(dele, ins_), sub[:, j])
            return best, best
        _, cols = lax.scan(col, first[:, 0], jnp.arange(R))
        new = jnp.concatenate([first, cols.T], axis=1)
        active = (i < h_lens)[:, None]
        return jnp.where(active, new, prev), None

    init = jnp.broadcast_to(jnp.arange(R + 1, dtype=jnp.int32), (B, R + 1))
    final, _ = lax.scan(row, init, (hyp.T, jnp.arange(H)))
    d = jnp.take_along_axis(final, r_lens[:, None], axis=1).astype(jnp.float32)
    if attrs.get("normalized", True):
        d = d / jnp.maximum(r_lens, 1)[:, None].astype(jnp.float32)
    return {"Out": d, "SequenceNum": jnp.asarray([B], jnp.int64)}


@register_op("chunk_eval")
def _chunk_eval(ctx, ins, attrs):
    """chunk_eval_op: chunk-level precision/recall/F1 for IOB-style tagging.
    Simplified to the common IOB scheme with chunk start at tag%2==0."""
    inf = ins["Inference"][0].astype(jnp.int32)
    label = ins["Label"][0].astype(jnp.int32)
    if inf.ndim == 3:
        inf = inf.squeeze(-1)
    if label.ndim == 3:
        label = label.squeeze(-1)
    lens = ctx.get_len(ctx.op.inputs["Label"][0])
    B, T = label.shape
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    m = _mask(lens, T, jnp.float32)
    num_chunk_types = attrs.get("num_chunk_types", 1)

    def starts(tags):
        # IOB2: B-tag = even ids start chunks (scheme-dependent; IOB plain)
        prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32),
                                tags[:, :-1]], 1)
        is_b = (tags % 2 == 0) & (tags < 2 * num_chunk_types)
        return is_b

    # exact-match chunks: a position contributes a correct chunk when the
    # full chunk span matches.  Approximate with per-position segment ids.
    same = (inf == label).astype(jnp.float32) * m
    lab_chunks = jnp.sum(starts(label).astype(jnp.float32) * m, axis=None)
    inf_chunks = jnp.sum(starts(inf).astype(jnp.float32) * m, axis=None)
    correct = jnp.sum(starts(label).astype(jnp.float32) * same, axis=None)
    prec = correct / jnp.maximum(inf_chunks, 1.0)
    rec = correct / jnp.maximum(lab_chunks, 1.0)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-6)
    return {"Precision": prec.reshape(1), "Recall": rec.reshape(1),
            "F1-Score": f1.reshape(1),
            "NumInferChunks": inf_chunks.astype(jnp.int64).reshape(1),
            "NumLabelChunks": lab_chunks.astype(jnp.int64).reshape(1),
            "NumCorrectChunks": correct.astype(jnp.int64).reshape(1)}


@register_op("copy_len")
def _copy_len(ctx, ins, attrs):
    """Forward the @LEN (and nested @LEN2) companions from input to output
    (framework helper)."""
    name_in = ctx.op.inputs["X"][0]
    lens = ctx.get_len(name_in)
    if lens is not None:
        ctx.set_len(ctx.op.outputs["Out"][0], lens)
    lens2 = ctx.get_len2(name_in)
    if lens2 is not None:
        ctx.set_len2(ctx.op.outputs["Out"][0], lens2)
    return {}


# ---------------------------------------------------------------------------
# Static shape/dtype rules (analysis.shape_infer).
# ---------------------------------------------------------------------------
from ..analysis.shape_infer import (ShapeError, VarInfo, dim_ok,  # noqa: E402
                                    first, no_outputs)
from ..core.registry import register_shape_fn  # noqa: E402

register_shape_fn("copy_len")(no_outputs())


@register_shape_fn("linear_chain_crf")
def _linear_chain_crf_shape(op, ins, attrs):
    em, trans = first(ins, "Emission"), first(ins, "Transition")
    if em.shape is not None and trans.shape is not None and \
            len(em.shape) == 3 and len(trans.shape) == 2 and \
            em.shape[-1] >= 0 and trans.shape[-1] >= 0:
        d = em.shape[-1]
        if trans.shape[-1] != d or (trans.shape[0] >= 0
                                    and trans.shape[0] != d + 2):
            raise ShapeError(
                f"linear_chain_crf: Transition {list(trans.shape)} must be "
                f"[D+2, D] for Emission D={d}")
    b = em.shape[0] if em.shape is not None else -1
    d = em.shape[-1] if em.shape is not None else -1
    return {"LogLikelihood": VarInfo((b, 1), em.dtype),
            "Alpha": VarInfo((b, d), em.dtype),
            "EmissionExps": em, "TransitionExps": trans}


@register_shape_fn("crf_decoding")
def _crf_decoding_shape(op, ins, attrs):
    em = first(ins, "Emission")
    if em.shape is None or len(em.shape) < 2:
        return {"ViterbiPath": VarInfo(None, "int64")}
    return {"ViterbiPath": VarInfo(em.shape[:2], "int64")}


@register_shape_fn("warpctc")
def _warpctc_shape(op, ins, attrs):
    logits = first(ins, "Logits")
    b = logits.shape[0] if logits.shape is not None else -1
    return {"Loss": VarInfo((b, 1), logits.dtype)}


@register_shape_fn("edit_distance")
def _edit_distance_shape(op, ins, attrs):
    hyp = first(ins, "Hyps")
    b = hyp.shape[0] if hyp.shape is not None else -1
    return {"Out": VarInfo((b, 1), "float32"),
            "SequenceNum": VarInfo((1,), "int64")}


@register_shape_fn("chunk_eval")
def _chunk_eval_shape(op, ins, attrs):
    inf, lab = first(ins, "Inference"), first(ins, "Label")
    if inf.shape is not None and lab.shape is not None and \
            not dim_ok(inf.shape[0], lab.shape[0]):
        raise ShapeError(
            f"chunk_eval: batch mismatch Inference {list(inf.shape)} vs "
            f"Label {list(lab.shape)}")
    f = VarInfo((1,), "float32")
    i = VarInfo((1,), "int64")
    return {"Precision": f, "Recall": f, "F1-Score": f,
            "NumInferChunks": i, "NumLabelChunks": i,
            "NumCorrectChunks": i}


# ---------------------------------------------------------------------------
# Sharding-propagation rules (analysis.shard_prop): CRF ops keep the batch
# sharding of their emissions; copy_len is a metadata marker.
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import shard_batch_only, shard_noop  # noqa: E402
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("copy_len")(shard_noop())
register_shard_fn("crf_decoding")(
    shard_batch_only("Emission", out="ViterbiPath"))
register_shard_fn("linear_chain_crf")(
    shard_batch_only("Emission", out="LogLikelihood",
                     also=("EmissionExps", "Alpha")))
