"""beam_search op lowering (see layers/generation.py for the design notes;
reference: beam_search_op.h:88 BeamSearch::operator(), RecurrentGradientMachine
beamSearch, beam_search_decode_op trace-back)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

NEG_INF = -1e30


@register_op("beam_search")
def _beam_search(ctx, ins, attrs):
    sub_idx = attrs["sub_block"]
    token_name = attrs["token_name"]
    probs_name = attrs["probs_name"]
    mem_names = attrs["mem_step_names"]
    mem_update_names = attrs["mem_update_names"]
    K = int(attrs["beam_size"])
    bos = int(attrs["bos_id"])
    eos = int(attrs["eos_id"])
    T = int(attrs["max_len"])
    V = int(attrs["vocab_size"])
    lp = float(attrs.get("length_penalty", 0.0))
    hook = None
    if attrs.get("step_hook"):
        from ..layers.generation import get_beam_hook
        hook = get_beam_hook(attrs["step_hook"])

    ctx_names = attrs.get("ctx_step_names", [])
    init_in = ins.get("InitStates", [])
    ctx_in = ins.get("Contexts", [])
    inits = [jnp.repeat(v, K, axis=0) for v in init_in]
    ctxs = [jnp.repeat(v, K, axis=0) for v in ctx_in]
    # batch size from whichever input exists — a stateless decoder (no
    # memory()) legitimately has no InitStates
    if init_in:
        B = init_in[0].shape[0]
    elif ctx_in:
        B = ctx_in[0].shape[0]
    else:
        raise ValueError(
            "beam_search: cannot infer batch size — decoder registered "
            "neither memories (InitStates) nor context inputs (Contexts); "
            "pass at least one non-step input to BeamSearchDecoder")
    BK = B * K
    env = ctx.env

    def run_step(tokens_flat, mems):
        benv = ctx.child_env(sub_idx, env)
        benv.local[token_name] = tokens_flat
        for nm, v in zip(mem_names, mems):
            benv.local[nm] = v
        for nm, v in zip(ctx_names, ctxs):
            benv.local[nm] = v
        ctx.interpret_block(sub_idx, benv)
        probs = benv.get(probs_name)
        new_mems = tuple(benv.get(un) if un else old
                         for un, old in zip(mem_update_names, mems))
        return probs, new_mems

    def step(carry, t):
        tokens, cum, finished, mems, flens = carry
        # tokens [B,K] int32; cum [B,K] log-prob; finished [B,K] bool;
        # flens [B,K] generated length
        probs, new_mems = run_step(tokens.reshape(BK), mems)
        logp = jnp.log(jnp.maximum(probs, 1e-20)).reshape(B, K, V)
        # finished beams: freeze — only a virtual <pad>=eos continuation
        # with prob 1 so their score is carried unchanged
        frozen = jnp.full((B, K, V), NEG_INF).at[:, :, eos].set(0.0)
        logp = jnp.where(finished[..., None], frozen, logp)
        total = cum[..., None] + logp                      # [B,K,V]
        if hook is not None:
            # RecurrentGradientMachine drill-down analog: the hook sees the
            # candidate frontier and may bias/prune it (-inf) before top-k
            bias = hook(t, {"scores": total, "tokens": tokens,
                            "finished": finished})
            if bias is not None:
                total = total + bias
        # first step: all K beams are identical copies of bos — keep only
        # beam 0's candidates so the frontier isn't K duplicates
        first = (t == 0)
        dup_mask = jnp.where(
            first & (jnp.arange(K)[None, :, None] > 0), NEG_INF, 0.0)
        flat = (total + dup_mask).reshape(B, K * V)
        top_val, top_idx = lax.top_k(flat, K)              # [B,K]
        parent = (top_idx // V).astype(jnp.int32)
        token = (top_idx % V).astype(jnp.int32)
        b_idx = jnp.arange(B)[:, None]
        was_finished = finished[b_idx, parent]
        now_finished = was_finished | (token == eos)
        new_flens = jnp.where(was_finished, flens[b_idx, parent],
                              flens[b_idx, parent] + 1)
        # reindex memories to selected parents (flattened gather)
        flat_parent = (b_idx * K + parent).reshape(BK)
        mems_sel = tuple(m.reshape((B * K,) + m.shape[1:])[flat_parent]
                         for m in new_mems)
        return ((token, top_val, now_finished, mems_sel, new_flens),
                (token, parent))

    tokens0 = jnp.full((B, K), bos, jnp.int32)
    cum0 = jnp.zeros((B, K), jnp.float32)
    fin0 = jnp.zeros((B, K), bool)
    flens0 = jnp.zeros((B, K), jnp.int32)
    mems0 = tuple(inits)
    (tokens_f, cum_f, fin_f, _, flens_f), (tok_tab, par_tab) = lax.scan(
        step, (tokens0, cum0, fin0, mems0, flens0), jnp.arange(T))
    # tok_tab/par_tab: [T, B, K] — backtrace from final beams
    b_idx = jnp.arange(B)[:, None]

    def back(carry, t_rev):
        beam = carry                                       # [B,K] beam index
        tok = tok_tab[t_rev][b_idx, beam]
        par = par_tab[t_rev][b_idx, beam]
        return par, tok

    _, rev_ids = lax.scan(back, jnp.tile(jnp.arange(K)[None], (B, 1)),
                          jnp.arange(T - 1, -1, -1))
    ids = jnp.flip(jnp.transpose(rev_ids, (1, 2, 0)), axis=-1)  # [B,K,T]
    # mask everything after (and including) the first eos to eos
    hit = jnp.cumsum((ids == eos).astype(jnp.int32), axis=-1)
    ids = jnp.where(hit > 0, eos, ids)
    scores = cum_f
    if lp > 0:
        scores = scores / jnp.power(flens_f.astype(jnp.float32) + 1e-6, lp)
    return {"Ids": ids, "Scores": scores, "Lens": flens_f}


@register_op("beam_search_decode")
def _beam_search_decode(ctx, ins, attrs):
    """beam_search_decode_op compat: the beam_search lowering already
    performs the backtrace, so decode is a pass-through of (Ids, Scores)."""
    return {"SentenceIds": ins["Ids"][0], "SentenceScores": ins["Scores"][0]}


@register_op("recurrent")
def _recurrent_alias(ctx, ins, attrs):
    """RecurrentOp name-compat alias for the rnn lowering
    (recurrent_op.cc:39)."""
    from ..core.registry import get_op_impl
    return get_op_impl("rnn")(ctx, ins, attrs)


@register_op("attention_with_cache")
def _attention_with_cache(ctx, ins, attrs):
    """Causal attention over a fixed-shape KV-cache slab (the incremental
    decode-serving op; see serving/decode.py for the runtime around it).

    Inputs:
      Q, K, V    [B, Tq, D]   this dispatch's projections (Tq=Tmax for the
                              prefill program, Tq=1 for the decode step)
      CacheK/V   [B, Tmax, D] persistable state slabs — appended in place
                              (outputs wired back to the SAME var names,
                              the optimizer-op state-threading convention,
                              so the executor carries them as donated
                              state across dispatches)
      Len        [B] int32    valid cached tokens BEFORE this dispatch;
                              both the write offset and the causal-mask
                              base (query i may see keys j <= Len + i)
      WriteMask  [B] float32  rows > 0 commit their K/V writes; others
                              leave their slab rows untouched (decode
                              feeds the live-slot mask, prefill the admit
                              mask — dead/foreign slots are never written)

    Every output row depends only on that row of the inputs, which is
    what makes slot admit/evict churn unable to perturb a surviving
    sequence even at the bit level (pinned by tests/test_decode.py).
    Scores and softmax are computed in float32 regardless of the cache
    dtype; Out is cast back to Q's dtype.
    """
    import math

    q = ins["Q"][0]
    k = ins["K"][0]
    v = ins["V"][0]
    cache_k = ins["CacheK"][0]
    cache_v = ins["CacheV"][0]
    ln = ins["Len"][0].astype(jnp.int32)
    wm = ins["WriteMask"][0]
    Tq = q.shape[1]
    Tmax = cache_k.shape[1]
    scale = float(attrs.get("scale", 0.0)) or 1.0 / math.sqrt(q.shape[-1])

    # vmap'd per-row append at the row's own offset; dynamic_update_slice
    # clamps the start, so a (masked-out) write from a dead slot at
    # Len==Tmax is harmless rather than out of bounds
    def _write(cache, new):
        written = jax.vmap(
            lambda c, n, l: lax.dynamic_update_slice(c, n, (l, 0)))(
                cache, new.astype(cache.dtype), ln)
        return jnp.where((wm > 0)[:, None, None], written, cache)

    ck_new = _write(cache_k, k)
    cv_new = _write(cache_v, v)
    scores = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                        ck_new.astype(jnp.float32)) * scale
    jpos = jnp.arange(Tmax, dtype=jnp.int32)[None, None, :]
    ipos = jnp.arange(Tq, dtype=jnp.int32)[None, :, None]
    visible = jpos <= (ln[:, None, None] + ipos)
    probs = jax.nn.softmax(jnp.where(visible, scores, NEG_INF), axis=-1)
    out = jnp.einsum("bqk,bkd->bqd", probs,
                     cv_new.astype(jnp.float32)).astype(q.dtype)
    return {"Out": out, "CacheKOut": ck_new, "CacheVOut": cv_new}


# ---------------------------------------------------------------------------
# Sharding propagation (analysis.shard_prop): beam search is decode-time
# data-dependent machinery — registering the explicit noop states that its
# outputs are treated replicated (beams are small; sharding them is
# never the plan), rather than leaving a PT042 blind spot.
# ---------------------------------------------------------------------------
from ..analysis.shard_prop import shard_noop  # noqa: E402
from ..core.registry import register_shard_fn  # noqa: E402

register_shard_fn("beam_search", "beam_search_decode")(shard_noop())

# attention_with_cache: the decode slot pool is a single-host serving
# construct — its batch axis is the slot axis and the cache slabs are
# session state, neither of which is ever mesh-sharded (the on-chip plan
# shards heads/hidden inside a slot, a future op variant).  Replicated
# outputs, stated explicitly.
register_shard_fn("attention_with_cache")(shard_noop())

from ..analysis.shape_infer import first  # noqa: E402
from ..core.registry import register_shape_fn  # noqa: E402


@register_shape_fn("attention_with_cache")
def _attention_with_cache_shape(op, ins, attrs):
    # Out mirrors Q; the cache outputs mirror their state slabs (the
    # optimizer-op ParamOut <- Param convention for in-place threading)
    return {"Out": first(ins, "Q"),
            "CacheKOut": first(ins, "CacheK"),
            "CacheVOut": first(ins, "CacheV")}
