"""Weight-decay regularizers appended as grad-graph ops (reference:
fluid/regularizer.py append_regularization_ops)."""
from __future__ import annotations

from .layer_helper import LayerHelper


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, param, grad):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(
            param.dtype, param.shape)
        helper.append_op(type="scale", inputs={"X": [param]},
                         outputs={"Out": [decay]},
                         attrs={"scale": self._coeff})
        out = helper.create_variable_for_type_inference(
            param.dtype, param.shape)
        helper.append_op(type="elementwise_add",
                         inputs={"X": [grad], "Y": [decay]},
                         outputs={"Out": [out]})
        return out


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, param, grad):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(
            param.dtype, param.shape)
        helper.append_op(type="sign", inputs={"X": [param]},
                         outputs={"Out": [sign]})
        decay = helper.create_variable_for_type_inference(
            param.dtype, param.shape)
        helper.append_op(type="scale", inputs={"X": [sign]},
                         outputs={"Out": [decay]},
                         attrs={"scale": self._coeff})
        out = helper.create_variable_for_type_inference(
            param.dtype, param.shape)
        helper.append_op(type="elementwise_add",
                         inputs={"X": [grad], "Y": [decay]},
                         outputs={"Out": [out]})
        return out


def append_regularization_ops(params_grads, regularization=None):
    out = []
    for param, grad in params_grads:
        reg = getattr(param, "regularizer", None) or regularization
        if reg is None:
            out.append((param, grad))
        else:
            out.append((param, reg.append_regularization_op(param, grad)))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
