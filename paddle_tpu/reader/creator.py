"""Reader creators (reference: python/paddle/v2/reader/creator.py —
np_array, text_file, recordio, cloud_reader).

``np_array``/``text_file`` are exact-parity generators.  The reference's
``recordio`` read Baidu's external RecordIO chunk files (a dependency
that lives outside the reference tree); this framework's chunked-record
format is the pickle part files ``dataset.common.split`` writes (one
pickled record stream per ``part-*.pickle``), so ``recordio`` here reads
those — same role, framework-native format.  ``cloud_reader`` keeps the
reference semantics (creator.py:91: fetch task chunks from the
fault-tolerant master, read each, mark done/failed) against
``distributed.master.MasterClient`` instead of an etcd lookup.
"""
from __future__ import annotations

import glob
import pickle
from typing import List, Sequence, Union

__all__ = ["np_array", "text_file", "recordio", "cloud_reader"]


def np_array(x):
    """Yield the rows of an ndarray (creator.py:22)."""
    import numpy as np

    def reader():
        arr = np.asarray(x)
        for row in arr:
            yield row

    return reader


def text_file(path):
    """Yield stripped lines of a text file (creator.py:42)."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def _read_part(path):
    # a split part file is ONE pickled list of samples
    # (dataset.common.split / cluster_files_reader format)
    with open(path, "rb") as f:
        yield from pickle.load(f)


def _expand_paths(paths: Union[str, Sequence[str]]) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        hit = sorted(glob.glob(p))
        if hit:
            files.extend(hit)
        elif glob.has_magic(p):
            # a zero-match PATTERN is a setup error: failing fast beats
            # seeding the literal pattern as a chunk (which would burn
            # the failure budget downstream and silently drop data)
            raise FileNotFoundError(f"no files match pattern {p!r}")
        else:
            files.append(p)      # literal path: open() reports precisely
    return files


def recordio(paths: Union[str, Sequence[str]], buf_size: int = 100):
    """Yield records from chunked part files (``dataset.common.split``
    output).  ``paths``: glob pattern or list of patterns/files;
    ``buf_size``: read-ahead records (the reference knob), honored via
    ``decorator.buffered``'s prefetch thread."""
    files = _expand_paths(paths)

    def reader():
        for path in files:
            yield from _read_part(path)

    if buf_size and buf_size > 0:
        from .decorator import buffered
        return buffered(reader, buf_size)
    return reader


def cloud_reader(paths: Union[str, Sequence[str]], master_address: str,
                 timeout_s: float = 30.0):
    """Fault-tolerant distributed reading: every record of every chunk is
    consumed once across ALL trainers sharing the master — a trainer
    pulls a task (one part file), streams its records, and marks it
    finished; a crash mid-task requeues the chunk for a survivor
    (distributed/master.py).  The reference's cloud_reader did the same
    against the Go master found via etcd (creator.py:91).

    Queue priming is the atomic ``set_dataset_if_empty`` RPC (the first
    trainer in partitions the dataset; concurrent joiners no-op).  An
    early-stopped generator (GeneratorExit — e.g. ``firstn`` or breaking
    a batch loop) RETURNS its in-flight task without burning the chunk's
    failure budget; only real exceptions count as failures."""
    from ..distributed.master import MasterClient

    files = _expand_paths(paths)

    def reader():
        from ..distributed.master import task_loop_reader

        client = MasterClient(master_address, timeout_s=timeout_s)
        try:
            if files:
                client.set_dataset_if_empty(files)
            yield from task_loop_reader(client, _read_part)()
        finally:
            client.close()

    return reader
