"""Reader composition toolkit (reference: python/paddle/v2/reader/ —
decorator.py: batch, shuffle, buffered, cache, chain, compose, firstn,
map_readers, xmap_readers with a thread pool; creator.py).

A *reader* is a zero-arg callable returning an iterable of samples — the
reference's protocol, kept verbatim.  ``xmap_readers``'s thread-pool
double-buffering (the PyDataProvider2 async pool role,
PyDataProvider2.cpp:195) is provided by ``buffered`` / ``xmap_readers`` over
``paddle_tpu.distributed.queue`` (native-backed when available).
"""
from . import creator
from . import decorator
from . import pipeline
from .decorator import (batch, buffered, cache, chain, compose, firstn,
                        map_readers, native_buffered, shuffle, xmap_readers)
from .pipeline import interleave, prefetch

__all__ = ["batch", "buffered", "cache", "chain", "compose", "firstn",
           "interleave", "map_readers", "native_buffered", "prefetch",
           "shuffle", "xmap_readers", "decorator", "pipeline"]
