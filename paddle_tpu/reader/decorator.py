"""Reader decorators (reference: python/paddle/v2/reader/decorator.py —
same protocol, re-implemented)."""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading


def native_buffered(reader, size=4):
    """C++ double-buffered prefetch (native AsyncBatcher — the
    PyDataProvider2 async-pool analog, PyDataProvider2.cpp:511).  The worker
    thread pulls from the Python reader under the GIL and parks results in a
    C++ bounded queue; falls back to the Python ``buffered`` when the native
    toolchain is unavailable.

    Lifecycle: a reader exception ends the batch stream and re-raises in
    the consumer (the native callback must not raise into C++); abandoning
    the generator early closes the batcher in the ``finally``, which stops
    and joins its worker."""
    from ..native import get_native
    native = get_native()
    if native is None:
        return buffered(reader, size)

    def new_reader():
        it = iter(reader())
        err = []

        def next_item():
            try:
                return (next(it),)      # wrap: None payloads stay distinct
            except StopIteration:
                return None
            except BaseException as e:  # don't raise across the C++ rim:
                err.append(e)           # surface it from the consumer side
                return None
        b = native.AsyncBatcher(next_item, capacity=size)
        try:
            while True:
                item = b.next_batch()
                if item is None:
                    if err:
                        raise err[0]
                    return
                yield item[0]
        finally:
            b.close()
    return new_reader


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    def new_reader():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return new_reader


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()
    return reader


def compose(*readers, check_alignment=True):
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        iters = itertools.zip_longest(*rs)
        for outputs in iters:
            if check_alignment and any(o is None for o in outputs):
                raise ValueError("readers not aligned in compose()")
            yield sum((make_tuple(o) for o in outputs), ())
    return reader


def buffered(reader, size):
    """Async prefetch through a bounded queue on a worker thread
    (the PyDataProvider2 double-buffer pool role).

    Now a thin wrapper over :mod:`paddle_tpu.reader.pipeline`'s engine,
    which fixes this decorator's historical lifecycle bugs: a worker
    exception re-raises in the consumer (it used to truncate the stream
    silently), abandoning the generator early stops the worker instead of
    leaving it blocked on a full queue forever, and teardown joins the
    thread.  Order-preserving (single worker)."""
    from .pipeline import prefetch
    return prefetch(reader, buffer_size=size, num_workers=1)


def batch(reader, batch_size, drop_last=True):
    """Group samples into lists of batch_size (v2 paddle.batch)."""
    def batch_reader():
        b = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return firstn_reader


def cache(reader):
    all_data = []

    def cache_reader():
        if not all_data:
            all_data.extend(reader())
        yield from all_data
    return cache_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (decorator.py
    xmap_readers)."""
    end = object()

    def data_reader():
        in_q: _queue.Queue = _queue.Queue(buffer_size)
        out_q: _queue.Queue = _queue.Queue(buffer_size)

        def read_worker():
            for d in reader():
                in_q.put(d)
            for _ in range(process_num):
                in_q.put(end)

        def map_worker():
            while True:
                d = in_q.get()
                if d is end:
                    out_q.put(end)
                    return
                out_q.put(mapper(d))

        threading.Thread(target=read_worker, daemon=True,
                         name="pt-reader-xmap-read").start()
        workers = [threading.Thread(target=map_worker, daemon=True,
                                    name=f"pt-reader-xmap-map-{i}")
                   for i in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        while finished < process_num:
            d = out_q.get()
            if d is end:
                finished += 1
            else:
                yield d
    return data_reader
