"""Asynchronous bounded multi-worker input pipeline.

tf.data-style prefetch/interleave for the v2 reader protocol (a *reader*
is a zero-arg callable returning an iterable of samples) — the TPU-native
successor of the PyDataProvider2 async pool: decode work moves off the
dispatch thread onto N workers feeding one bounded queue, so the compiled
step is never starved by the host.

Engine guarantees (the part the old ``buffered`` decorator got wrong):

* **bounded**: at most ``buffer_size`` decoded samples wait in the queue —
  a slow consumer exerts backpressure instead of buffering the epoch;
* **exception propagation**: a worker that raises forwards the exception
  to the consumer's ``next()`` call instead of dying silently (which
  looked like a truncated epoch) or hanging the consumer;
* **clean shutdown**: abandoning the output generator early (``break`` /
  ``close()`` / GC) stops every worker and joins it — no thread outlives
  its pipeline (tests/conftest.py fails any test that leaks one);
* **shard-aware interleave**: N readers (data shards) are spread over the
  workers round-robin, each worker cycling its shards so early output
  mixes shards instead of draining them in sequence.

``Executor.run_pipelined`` reuses this engine for its device-staging
stage: the same lifecycle rules apply to batches in flight.

**Instrumentation** (paddle_tpu.observability): with the ``observe`` flag
on — or ``instrument=True`` passed explicitly — the engine records
sampled queue depth and consumer stall time at every get, plus per-worker
busy/blocked seconds, making the host-parallelism story (worker busy
fraction, backpressure) a permanent in-framework signal.  Off by default
and entirely outside the data path when off.
"""
from __future__ import annotations

import queue as _queue
import threading
import time as _time
from typing import Callable, Optional, Sequence

from .. import observability as _obs
from ..testing import lockwatch as _lw
from ..core.registry import register_tunable

__all__ = ["prefetch", "interleave", "THREAD_NAME_PREFIX"]

# Autotuner knob declaration (paddle_tpu.tuning), next to the engine it
# controls.  num_workers trades decode parallelism against GIL/core
# contention (this container delivers ~1 effective core — PR 2's probe —
# so the winner is host-dependent by nature); buffer_size bounds decoded
# samples in flight (backpressure vs burst absorption).
register_tunable(
    "reader/prefetch", side="host",
    space={"num_workers": (1, 2, 4), "buffer_size": (2, 4, 8, 16)},
    default={"num_workers": 1, "buffer_size": 8},
    description="prefetch engine defaults: decode worker threads and the "
                "bounded decoded-sample queue.")

# Every worker thread the engine spawns carries this name prefix so test
# harnesses (tests/conftest.py) can detect leaked pipeline workers.
THREAD_NAME_PREFIX = "pt-input-pipeline"

_DATA, _DONE, _ERROR = 0, 1, 2
_POLL_S = 0.05          # worker put/stop poll; bounds shutdown latency
_FLUSH_EVERY = 32       # instrumented busy/wait counter flush cadence


def _offer(q: _queue.Queue, stop: threading.Event, msg) -> bool:
    """Blocking put that gives up when the pipeline is being torn down."""
    while not stop.is_set():
        try:
            q.put(msg, timeout=_POLL_S)
            return True
        except _queue.Full:
            continue
    return False


def _pump(source: Callable[[], object], q: _queue.Queue,
          stop: threading.Event, instrument: bool = False,
          span_parent=None, widx: int = 0):
    """Worker loop: drain one source iterable into the shared queue.

    ``instrument`` splits the loop's wall time into *busy* (producing —
    decode/stage work inside the source) and *wait* (blocked offering to
    a full queue — consumer backpressure); deltas flush into the counters
    every ``_FLUSH_EVERY`` items and at worker exit, so a live pipeline's
    periodic snapshots see current numbers while the loop still pays only
    two perf_counter reads per item and ~zero lock traffic.

    ``span_parent`` (instrumented runs): each produce emits a
    ``reader/item`` span — attached to this worker thread while the
    source runs, so spans the source itself creates (run_pipelined's
    ``pipeline/stage``) nest under the item that carried them."""
    busy = wait = 0.0
    n = 0
    try:
        if not instrument:
            for item in source():
                if not _offer(q, stop, (_DATA, item)):
                    return
        else:
            it = iter(source())
            while True:
                sp = None
                if span_parent is not None:
                    sp = _obs.tracing.start_span(
                        "reader/item", parent=span_parent,
                        worker=widx, seq=n)
                t0 = _time.perf_counter()
                try:
                    if sp is not None:
                        with _obs.tracing.attach(sp):
                            item = next(it)
                    else:
                        item = next(it)
                except StopIteration:
                    busy += _time.perf_counter() - t0
                    if sp is not None:
                        sp.cancel()      # the final empty pull: no span
                    break
                if sp is not None:
                    sp.end()
                t1 = _time.perf_counter()
                busy += t1 - t0
                ok = _offer(q, stop, (_DATA, item))
                wait += _time.perf_counter() - t1
                if not ok:
                    return
                n += 1
                if n % _FLUSH_EVERY == 0:
                    _obs.inc_counter("pipeline/worker_busy_s", busy)
                    _obs.inc_counter("pipeline/worker_wait_s", wait)
                    busy = wait = 0.0
    except BaseException as e:          # noqa: BLE001 — forwarded, not eaten
        _offer(q, stop, (_ERROR, e))
    finally:
        if instrument and (busy or wait):
            _obs.inc_counter("pipeline/worker_busy_s", busy)
            _obs.inc_counter("pipeline/worker_wait_s", wait)
        _offer(q, stop, (_DONE, None))


def _resolve_instrument(instrument: Optional[bool]) -> bool:
    """None defers to the global ``observe`` flag; resolved ONCE at
    pipeline start (a mid-stream flag flip doesn't change a live run)."""
    return _obs.enabled() if instrument is None else bool(instrument)


def _run(sources: Sequence[Callable], buffer_size: int,
         instrument: Optional[bool] = None, trace_parent=None):
    """Generator over the merged output of ``sources``, each drained by its
    own worker thread through one bounded queue.

    Instrumented runs get a ``reader/pipeline`` root span (parented to
    ``trace_parent`` when the caller supplies one — run_pipelined joins
    its staging engine into the pipelined trace this way) with one
    ``reader/item`` child span per produced item."""
    instrument = _resolve_instrument(instrument)
    root_sp = _obs.tracing.start_span(
        "reader/pipeline", parent=trace_parent,
        workers=len(sources), buffer_size=int(buffer_size)) \
        if instrument else None
    q: _queue.Queue = _queue.Queue(maxsize=max(1, buffer_size))
    stop = threading.Event()
    threads = [
        threading.Thread(target=_pump,
                         args=(src, q, stop, instrument, root_sp, i),
                         daemon=True, name=f"{THREAD_NAME_PREFIX}-{i}")
        for i, src in enumerate(sources)]
    for t in threads:
        t.start()
    done = 0
    try:
        while done < len(threads):
            if instrument:
                t0 = _time.perf_counter()
                tag, payload = q.get()
                _obs.observe_hist("pipeline/consumer_stall_ms",
                                  (_time.perf_counter() - t0) * 1e3)
                _obs.observe_hist("pipeline/queue_depth", q.qsize())
            else:
                tag, payload = q.get()
            if tag == _DATA:
                yield payload
            elif tag == _ERROR:
                raise payload
            else:
                done += 1
    finally:
        # break / close() / error / normal end all land here: wake every
        # blocked putter, then join — consumer exit means worker exit
        stop.set()
        while True:
            try:
                q.get_nowait()
            except _queue.Empty:
                break
        for t in threads:
            t.join(timeout=5.0)
        if root_sp is not None:
            root_sp.end()


def _tuned_defaults(buffer_size: Optional[int], num_workers: Optional[int]):
    """Resolve omitted prefetch knobs: the hand-picked (8, 1) — or, when
    the ``autotune`` flag is on, the persisted ``reader/prefetch`` winner
    (lazy import; the untuned path never loads the tuning package).  An
    explicit argument always wins."""
    if buffer_size is not None and num_workers is not None:
        return buffer_size, num_workers
    from ..core.registry import resolve_tuned
    cfg = resolve_tuned("reader/prefetch",
                        {"buffer_size": 8, "num_workers": 1})
    return (cfg["buffer_size"] if buffer_size is None else buffer_size,
            cfg["num_workers"] if num_workers is None else num_workers)


def prefetch(reader: Callable, buffer_size: Optional[int] = None,
             num_workers: Optional[int] = None,
             mapper: Optional[Callable] = None,
             instrument: Optional[bool] = None,
             trace_parent=None) -> Callable:
    """Decode-ahead through ``num_workers`` threads and a bounded queue.

    Workers share the source iterator (pulls are serialized under a lock);
    ``mapper``, when given, runs OUTSIDE the lock — that is where parallel
    decode happens, so put the expensive per-sample work (parsing,
    augmentation, tokenization) in ``mapper`` and keep the reader a cheap
    record source.  With ``num_workers == 1`` sample order is preserved
    (drop-in for the old ``buffered``); with more workers, relative order
    across workers is not guaranteed.  ``instrument``: queue-depth/stall/
    busy metrics into the observability registry plus ``reader/pipeline``
    + per-item ``reader/item`` tracing spans (None = follow the global
    ``observe`` flag); ``trace_parent`` joins those spans into a caller's
    trace.  ``buffer_size``/``num_workers`` default to
    (8, 1) — or the persisted ``reader/prefetch`` autotuner winner when
    the ``autotune`` flag is on.
    """
    buffer_size, num_workers = _tuned_defaults(buffer_size, num_workers)
    if num_workers < 1:
        raise ValueError(f"prefetch: num_workers must be >= 1, "
                         f"got {num_workers}")

    def data_reader():
        it = iter(reader())
        lock = _lw.make_lock("pipeline.shared_source")
        exhausted = object()

        def source():
            while True:
                with lock:
                    # a pull that raises also poisons the shared iterator
                    # (a raised generator is closed), so the other workers
                    # wind down with StopIteration while the engine
                    # forwards this exception to the consumer
                    item = next(it, exhausted)
                if item is exhausted:
                    return
                yield mapper(item) if mapper is not None else item

        yield from _run([source] * num_workers, buffer_size,
                        instrument=instrument, trace_parent=trace_parent)
    return data_reader


def interleave(readers: Sequence[Callable], buffer_size: int = 8,
               num_workers: Optional[int] = None,
               mapper: Optional[Callable] = None,
               instrument: Optional[bool] = None,
               trace_parent=None) -> Callable:
    """Merge N shard readers through parallel workers (tf.data interleave).

    Shards are assigned to workers round-robin (worker ``i`` owns shards
    ``i, i+W, ...``) and each worker CYCLES its shards one sample at a
    time, so the merged stream mixes shards from the first batch on —
    shard-aware in both placement and output mixing.  ``num_workers``
    defaults to one per shard.
    """
    readers = list(readers)
    if not readers:
        raise ValueError("interleave: need at least one reader")
    W = min(num_workers or len(readers), len(readers))
    if W < 1:
        raise ValueError(f"interleave: num_workers must be >= 1, got {W}")

    def data_reader():
        def make_source(widx):
            shards = readers[widx::W]

            def source():
                iters = [iter(r()) for r in shards]
                while iters:
                    alive = []
                    for it in iters:
                        try:
                            item = next(it)
                        except StopIteration:
                            continue
                        yield mapper(item) if mapper is not None else item
                        alive.append(it)
                    iters = alive
            return source

        yield from _run([make_source(i) for i in range(W)], buffer_size,
                        instrument=instrument, trace_parent=trace_parent)
    return data_reader
