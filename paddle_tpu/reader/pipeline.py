"""Asynchronous bounded multi-worker input pipeline.

tf.data-style prefetch/interleave for the v2 reader protocol (a *reader*
is a zero-arg callable returning an iterable of samples) — the TPU-native
successor of the PyDataProvider2 async pool: decode work moves off the
dispatch thread onto N workers feeding one bounded queue, so the compiled
step is never starved by the host.

Engine guarantees (the part the old ``buffered`` decorator got wrong):

* **bounded**: at most ``buffer_size`` decoded samples wait in the queue —
  a slow consumer exerts backpressure instead of buffering the epoch;
* **exception propagation**: a worker that raises forwards the exception
  to the consumer's ``next()`` call instead of dying silently (which
  looked like a truncated epoch) or hanging the consumer;
* **clean shutdown**: abandoning the output generator early (``break`` /
  ``close()`` / GC) stops every worker and joins it — no thread outlives
  its pipeline (tests/conftest.py fails any test that leaks one);
* **shard-aware interleave**: N readers (data shards) are spread over the
  workers round-robin, each worker cycling its shards so early output
  mixes shards instead of draining them in sequence.

``Executor.run_pipelined`` reuses this engine for its device-staging
stage: the same lifecycle rules apply to batches in flight.
"""
from __future__ import annotations

import queue as _queue
import threading
from typing import Callable, Optional, Sequence

__all__ = ["prefetch", "interleave", "THREAD_NAME_PREFIX"]

# Every worker thread the engine spawns carries this name prefix so test
# harnesses (tests/conftest.py) can detect leaked pipeline workers.
THREAD_NAME_PREFIX = "pt-input-pipeline"

_DATA, _DONE, _ERROR = 0, 1, 2
_POLL_S = 0.05          # worker put/stop poll; bounds shutdown latency


def _offer(q: _queue.Queue, stop: threading.Event, msg) -> bool:
    """Blocking put that gives up when the pipeline is being torn down."""
    while not stop.is_set():
        try:
            q.put(msg, timeout=_POLL_S)
            return True
        except _queue.Full:
            continue
    return False


def _pump(source: Callable[[], object], q: _queue.Queue,
          stop: threading.Event):
    """Worker loop: drain one source iterable into the shared queue."""
    try:
        for item in source():
            if not _offer(q, stop, (_DATA, item)):
                return
    except BaseException as e:          # noqa: BLE001 — forwarded, not eaten
        _offer(q, stop, (_ERROR, e))
    finally:
        _offer(q, stop, (_DONE, None))


def _run(sources: Sequence[Callable], buffer_size: int):
    """Generator over the merged output of ``sources``, each drained by its
    own worker thread through one bounded queue."""
    q: _queue.Queue = _queue.Queue(maxsize=max(1, buffer_size))
    stop = threading.Event()
    threads = [
        threading.Thread(target=_pump, args=(src, q, stop), daemon=True,
                         name=f"{THREAD_NAME_PREFIX}-{i}")
        for i, src in enumerate(sources)]
    for t in threads:
        t.start()
    done = 0
    try:
        while done < len(threads):
            tag, payload = q.get()
            if tag == _DATA:
                yield payload
            elif tag == _ERROR:
                raise payload
            else:
                done += 1
    finally:
        # break / close() / error / normal end all land here: wake every
        # blocked putter, then join — consumer exit means worker exit
        stop.set()
        while True:
            try:
                q.get_nowait()
            except _queue.Empty:
                break
        for t in threads:
            t.join(timeout=5.0)


def prefetch(reader: Callable, buffer_size: int = 8, num_workers: int = 1,
             mapper: Optional[Callable] = None) -> Callable:
    """Decode-ahead through ``num_workers`` threads and a bounded queue.

    Workers share the source iterator (pulls are serialized under a lock);
    ``mapper``, when given, runs OUTSIDE the lock — that is where parallel
    decode happens, so put the expensive per-sample work (parsing,
    augmentation, tokenization) in ``mapper`` and keep the reader a cheap
    record source.  With ``num_workers == 1`` sample order is preserved
    (drop-in for the old ``buffered``); with more workers, relative order
    across workers is not guaranteed.
    """
    if num_workers < 1:
        raise ValueError(f"prefetch: num_workers must be >= 1, "
                         f"got {num_workers}")

    def data_reader():
        it = iter(reader())
        lock = threading.Lock()
        exhausted = object()

        def source():
            while True:
                with lock:
                    # a pull that raises also poisons the shared iterator
                    # (a raised generator is closed), so the other workers
                    # wind down with StopIteration while the engine
                    # forwards this exception to the consumer
                    item = next(it, exhausted)
                if item is exhausted:
                    return
                yield mapper(item) if mapper is not None else item

        yield from _run([source] * num_workers, buffer_size)
    return data_reader


def interleave(readers: Sequence[Callable], buffer_size: int = 8,
               num_workers: Optional[int] = None,
               mapper: Optional[Callable] = None) -> Callable:
    """Merge N shard readers through parallel workers (tf.data interleave).

    Shards are assigned to workers round-robin (worker ``i`` owns shards
    ``i, i+W, ...``) and each worker CYCLES its shards one sample at a
    time, so the merged stream mixes shards from the first batch on —
    shard-aware in both placement and output mixing.  ``num_workers``
    defaults to one per shard.
    """
    readers = list(readers)
    if not readers:
        raise ValueError("interleave: need at least one reader")
    W = min(num_workers or len(readers), len(readers))
    if W < 1:
        raise ValueError(f"interleave: num_workers must be >= 1, got {W}")

    def data_reader():
        def make_source(widx):
            shards = readers[widx::W]

            def source():
                iters = [iter(r()) for r in shards]
                while iters:
                    alive = []
                    for it in iters:
                        try:
                            item = next(it)
                        except StopIteration:
                            continue
                        yield mapper(item) if mapper is not None else item
                        alive.append(it)
                    iters = alive
            return source

        yield from _run([make_source(i) for i in range(W)], buffer_size)
    return data_reader
