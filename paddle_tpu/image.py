"""Image utilities (reference: python/paddle/v2/image.py — load/resize/
crop/flip/transform helpers + batch_images_from_tar; HWC in, optional CHW
out).

PIL-backed instead of cv2 (not in this environment); same function
surface and semantics: `load_image*` return HWC uint8 (BGR channel order,
matching the reference's cv2 convention, so published per-channel means
transfer verbatim), `simple_transform` resizes the short side, crops
(random+flip when training, center otherwise), converts to CHW float32
and subtracts the mean."""
from __future__ import annotations

import io
import os
import pickle
import tarfile

import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
    "ImageClassificationDatasetCreater",
]


def _to_bgr(arr, is_color):
    if not is_color:
        return arr
    return arr[:, :, ::-1]            # PIL decodes RGB; reference is BGR


def load_image_bytes(bytes, is_color=True):  # noqa: A002 (reference name)
    """Decode an image from a bytes blob into HWC uint8 (BGR when color)
    (image.py:98)."""
    from PIL import Image

    img = Image.open(io.BytesIO(bytes))
    img = img.convert("RGB" if is_color else "L")
    return _to_bgr(np.asarray(img), is_color)


def load_image(file, is_color=True):  # noqa: A002
    """Load an image file into HWC uint8 (BGR when color) (image.py:122)."""
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def resize_short(im, size):
    """Resize so the SHORTER edge becomes ``size`` (image.py:150)."""
    from PIL import Image

    h, w = im.shape[:2]
    scale = size / min(h, w)
    nh, nw = int(round(h * scale)), int(round(w * scale))
    return np.asarray(Image.fromarray(im).resize((nw, nh), Image.BILINEAR))


def to_chw(im, order=(2, 0, 1)):
    """HWC → CHW (image.py:177)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """Center-crop a size×size window (image.py:201)."""
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True):
    """Random size×size window (image.py:229)."""
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im):
    """Horizontal mirror (image.py:257).  NB: the 2-D (grayscale) branch
    flips VERTICALLY — reproduced bug-for-bug from the reference; do not
    'fix' without breaking parity with models trained against it."""
    return im[:, ::-1] if len(im.shape) == 3 else im[::-1, :]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short → (random crop + coin-flip mirror | center crop) →
    CHW float32 → mean subtract (image.py:277)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size)
        if np.random.randint(2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1:
            mean = mean[:, np.newaxis, np.newaxis]
        else:
            assert len(mean.shape) == len(im.shape)
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """load_image + simple_transform (image.py:331)."""
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Repack tar members named in ``img2label`` into pickle batch files
    of {'data': [jpeg bytes], 'label': [...]} and return the meta-file
    listing them (image.py:35) — the cluster data-prep step the flowers
    reader used."""
    batch_dir = data_file + "_batch"
    out_path = os.path.join(batch_dir, dataset_name)
    meta_file = os.path.join(batch_dir, dataset_name + ".txt")
    # the meta file is the commit marker (written last, atomically):
    # a run killed mid-repack leaves no meta and is redone from scratch
    if os.path.exists(meta_file):
        return meta_file
    if os.path.exists(out_path):
        import shutil
        shutil.rmtree(out_path)       # partial prior attempt
    os.makedirs(out_path)

    def dump(data, labels, file_id):
        with open(os.path.join(out_path, f"batch_{file_id}"), "wb") as f:
            pickle.dump({"label": labels, "data": data}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)

    data, labels, file_id = [], [], 0
    with tarfile.open(data_file) as tf:
        for mem in tf.getmembers():
            if mem.name in img2label:
                data.append(tf.extractfile(mem).read())
                labels.append(img2label[mem.name])
                if len(data) == num_per_batch:
                    dump(data, labels, file_id)
                    file_id += 1
                    data, labels = [], []
    if data:
        dump(data, labels, file_id)
    tmp = meta_file + ".part"
    with open(tmp, "w") as meta:
        for fn in sorted(os.listdir(out_path)):
            meta.write(os.path.abspath(os.path.join(out_path, fn)) + "\n")
    os.replace(tmp, meta_file)
    return meta_file


class ImageClassificationDatasetCreater:
    """v1 image-dataset preparation (utils/preprocess_img.py
    ImageClassificationDatasetCreater + preprocess_util.DatasetCreater):
    turn a ``data_path/{train,test}/<label>/*.jpg`` directory tree into
    the on-disk batch layout the v1 trainers consumed —
    ``batches/{train,test}_batches/batch-%05d.pickle`` part files (each
    one pickled list of (CHW float32 image, label_id) pairs, readable by
    ``reader.creator.recordio``), ``train.list``/``test.list``,
    ``labels.pkl`` and a ``batches.meta`` carrying the train-set mean
    image for input centering.
    """

    def __init__(self, data_path, target_size, color=True,
                 num_per_batch=1024, overwrite=False, seed=0):
        self.data_path = data_path
        self.target_size = target_size
        self.color = color
        self.num_per_batch = num_per_batch
        self.overwrite = overwrite
        self.seed = seed
        self.batch_dir = os.path.join(data_path, "batches")

    def _load(self, path):
        im = load_image(path, is_color=self.color)
        im = simple_transform(im, self.target_size, self.target_size,
                              is_train=False, is_color=self.color)
        # v1 convert_to_paddle_format: flattened CHW rows
        return im.astype("float32").ravel()

    _EXTS = ("jpg", "jpeg", "png", "bmp")

    def _scan_split(self, split, label_ids):
        root = os.path.join(self.data_path, split)
        items = []
        if not os.path.isdir(root):
            return items
        for label in sorted(os.listdir(root)):
            d = os.path.join(root, label)
            if not os.path.isdir(d):
                continue
            imgs = [fn for fn in sorted(os.listdir(d))
                    if fn.rsplit(".", 1)[-1].lower() in self._EXTS]
            if not imgs:
                continue     # artifact dirs must not claim a label id
            lid = label_ids.setdefault(label, len(label_ids))
            items.extend((os.path.join(d, fn), lid) for fn in imgs)
        return items

    def create_batches(self):
        """Build the batch layout; returns the batches directory.
        ``batches.meta`` is written LAST and is the completion marker: a
        partial tree from a crashed run (or overwrite=True) is cleared
        and rebuilt instead of being served incomplete/stale."""
        import pickle
        import random
        import shutil

        meta_path = os.path.join(self.batch_dir, "batches.meta")
        if os.path.exists(meta_path) and not self.overwrite:
            return self.batch_dir
        if os.path.isdir(self.batch_dir):
            shutil.rmtree(self.batch_dir)     # stale parts must not linger
        os.makedirs(self.batch_dir)
        label_ids = {}
        mean_acc, mean_n = None, 0
        for split in ("train", "test"):
            items = self._scan_split(split, label_ids)
            if split == "train":
                random.Random(self.seed).shuffle(items)
            out_dir = os.path.join(self.batch_dir, f"{split}_batches")
            os.makedirs(out_dir, exist_ok=True)
            paths = []
            for bi in range(0, len(items), self.num_per_batch):
                batch = []
                for path, lid in items[bi:bi + self.num_per_batch]:
                    im = self._load(path)
                    if split == "train":
                        mean_acc = im if mean_acc is None else mean_acc + im
                        mean_n += 1
                    batch.append((im, lid))
                p = os.path.abspath(os.path.join(
                    out_dir,
                    "batch-%05d.pickle" % (bi // self.num_per_batch)))
                with open(p, "wb") as f:
                    pickle.dump(batch, f)
                paths.append(p)
            with open(os.path.join(self.batch_dir, f"{split}.list"),
                      "w") as f:
                f.write("\n".join(paths) + ("\n" if paths else ""))
        with open(os.path.join(self.batch_dir, "labels.pkl"), "wb") as f:
            pickle.dump({v: k for k, v in label_ids.items()}, f)
        meta = {"mean_image": (mean_acc / max(mean_n, 1))
                if mean_acc is not None else None,
                "image_size": self.target_size, "color": self.color,
                "num_labels": len(label_ids)}
        with open(meta_path, "wb") as f:
            pickle.dump(meta, f)
        return self.batch_dir
