"""Program-state evaluators (reference: fluid/evaluator.py:21-90 Evaluator
base with state vars + reset program, Accuracy, ChunkEvaluator).

States are persistable scope vars accumulated by metric ops inside the main
program; ``eval`` computes the aggregate, ``reset`` zeroes the states.
"""
from __future__ import annotations

import numpy as np

from .core.program import default_main_program
from .core.scope import global_scope
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from . import layers


class Evaluator:
    def __init__(self, name, **kwargs):
        self.helper = LayerHelper(name, **kwargs)
        self.states = []
        self.metrics = []

    def _create_state(self, suffix, dtype, shape):
        var = self.helper.create_global_variable(
            list(shape), dtype, name=f"{self.helper.name}.{suffix}")
        self.helper.set_variable_initializer(var, ConstantInitializer(0.0))
        self.states.append(var)
        return var

    def reset(self, executor, reset_program=None, scope=None):
        import jax.numpy as jnp
        scope = global_scope() if scope is None else scope
        for s in self.states:
            if scope.has(s.name):
                scope.set(s.name, jnp.zeros_like(scope.get(s.name)))

    def eval(self, executor, eval_program=None, scope=None):
        raise NotImplementedError


class Accuracy(Evaluator):
    """Streaming accuracy (fluid evaluator.Accuracy)."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy_eval", **kwargs)
        self.total = self._create_state("total", "float32", [1])
        self.correct = self._create_state("correct", "float32", [1])
        topk_out, topk_idx = layers.topk(input, k)
        acc = self.helper.create_variable_for_type_inference("float32", (1,))
        bc = self.helper.create_variable_for_type_inference("int32")
        bt = self.helper.create_variable_for_type_inference("int32")
        self.helper.append_op(
            type="accuracy",
            inputs={"Out": [topk_out], "Indices": [topk_idx],
                    "Label": [label]},
            outputs={"Accuracy": [acc], "Correct": [bc], "Total": [bt]})
        # accumulate into states
        bcf = layers.cast(bc, "float32")
        btf = layers.cast(bt, "float32")
        layers.sums([self.total, btf], out=self.total)
        layers.sums([self.correct, bcf], out=self.correct)
        self.metrics.append(acc)
        self.batch_accuracy = acc

    def eval(self, executor, eval_program=None, scope=None):
        scope = global_scope() if scope is None else scope
        total = float(np.asarray(scope.get(self.total.name))[0])
        correct = float(np.asarray(scope.get(self.correct.name))[0])
        return np.array([correct / max(total, 1.0)], np.float32)


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (fluid evaluator.ChunkEvaluator; chunk_eval_op)."""

    def __init__(self, input, label, chunk_scheme="IOB", num_chunk_types=1,
                 **kwargs):
        super().__init__("chunk_eval", **kwargs)
        self.num_infer = self._create_state("num_infer", "float32", [1])
        self.num_label = self._create_state("num_label", "float32", [1])
        self.num_correct = self._create_state("num_correct", "float32", [1])
        prec = self.helper.create_variable_for_type_inference("float32")
        rec = self.helper.create_variable_for_type_inference("float32")
        f1 = self.helper.create_variable_for_type_inference("float32")
        ni = self.helper.create_variable_for_type_inference("int64")
        nl = self.helper.create_variable_for_type_inference("int64")
        nc = self.helper.create_variable_for_type_inference("int64")
        self.helper.append_op(
            type="chunk_eval",
            inputs={"Inference": [input], "Label": [label]},
            outputs={"Precision": [prec], "Recall": [rec], "F1-Score": [f1],
                     "NumInferChunks": [ni], "NumLabelChunks": [nl],
                     "NumCorrectChunks": [nc]},
            attrs={"chunk_scheme": chunk_scheme,
                   "num_chunk_types": num_chunk_types})
        layers.sums([self.num_infer, layers.cast(ni, "float32")],
                    out=self.num_infer)
        layers.sums([self.num_label, layers.cast(nl, "float32")],
                    out=self.num_label)
        layers.sums([self.num_correct, layers.cast(nc, "float32")],
                    out=self.num_correct)
        self.metrics.extend([prec, rec, f1])

    def eval(self, executor, eval_program=None, scope=None):
        scope = global_scope() if scope is None else scope
        ni = float(np.asarray(scope.get(self.num_infer.name))[0])
        nl = float(np.asarray(scope.get(self.num_label.name))[0])
        nc = float(np.asarray(scope.get(self.num_correct.name))[0])
        p = nc / max(ni, 1.0)
        r = nc / max(nl, 1.0)
        f1 = 2 * p * r / max(p + r, 1e-6)
        return np.array([p, r, f1], np.float32)


class RankAuc:
    """Streaming per-query rank-AUC (reference:
    gserver/evaluators/Evaluator.cpp:513 RankAucEvaluator).

    Each query contributes calcRankAuc(scores, clicks, pv): sort by score
    descending, sweep accumulating click mass vs (pv − click) mass with the
    trapezoid tie-correction for equal scores; AUC = area / (clickSum ·
    noClickSum).  ``eval`` is the mean over queries (the evaluator's
    totalScore/numSamples print).  Host-side streaming by design — metric
    aggregation has no MXU work.

    One deliberate deviation: the reference accumulates ``noClickSum +=
    noClick`` (the running within-tie-group sum), which inflates the
    denominator whenever scores tie and under-reports AUC; here the
    denominator is the exact pair mass clickSum · Σ(pv−click) — bit-identical
    to the reference for all-distinct scores, and the textbook value
    (tied pairs at half credit) under ties.
    """

    def __init__(self):
        self.reset()

    def reset(self, *a, **kw):
        self._total = 0.0
        self._count = 0

    @staticmethod
    def _query_auc(scores, clicks, pv):
        order = np.argsort(-np.asarray(scores, np.float64), kind="stable")
        scores = np.asarray(scores, np.float64)[order]
        clicks = np.asarray(clicks, np.float64)[order]
        pv = np.asarray(pv, np.float64)[order]
        auc = click_sum = old_click_sum = 0.0
        no_click = no_click_sum = 0.0
        last = scores[0] + 1.0
        for s, c, p in zip(scores, clicks, pv):
            if s != last:
                auc += (click_sum + old_click_sum) * no_click / 2.0
                old_click_sum = click_sum
                no_click = 0.0
                last = s
            no_click += p - c
            no_click_sum += p - c
            click_sum += c
        auc += (click_sum + old_click_sum) * no_click / 2.0
        denom = click_sum * no_click_sum
        return auc / denom if denom != 0.0 else 0.0

    def update(self, scores, clicks, pv=None, seq_lens=None):
        """Add one batch.  ``scores``/``clicks`` (and optional ``pv`` page
        views) are flat arrays; ``seq_lens`` splits them into queries
        (whole batch = one query when omitted — the non-sequence case)."""
        scores = np.asarray(scores).reshape(-1)
        clicks = np.asarray(clicks).reshape(-1)
        pv = (np.ones_like(scores) if pv is None
              else np.asarray(pv).reshape(-1))
        bounds = (np.cumsum([0] + list(seq_lens)) if seq_lens is not None
                  else np.array([0, len(scores)]))
        for a, b in zip(bounds[:-1], bounds[1:]):
            if b > a:
                self._total += self._query_auc(scores[a:b], clicks[a:b],
                                               pv[a:b])
                self._count += 1

    def eval(self, *a, **kw):
        return self._total / self._count if self._count else 0.0


class CTCError:
    """Streaming CTC sequence-error evaluator (reference:
    gserver/evaluators/CTCErrorEvaluator.cpp — best-path decode, collapse
    repeats/blanks (blank = num_classes−1, a repeat separated by blank is
    kept), Levenshtein alignment with substitution/deletion/insertion
    backtrace, per-sequence normalization by max(len(gt), len(rec))).

    ``eval`` returns the CER; ``results`` exposes the evaluator's full dict
    (error / deletion_error / insertion_error / substitution_error /
    sequence_error).
    """

    def __init__(self):
        self.reset()

    def reset(self, *a, **kw):
        self._dist = 0.0
        self._del = 0.0
        self._ins = 0.0
        self._sub = 0.0
        self._seq_err = 0
        self._count = 0

    @staticmethod
    def best_path(acts, blank):
        """argmax path → collapsed label string (path2String)."""
        path = np.asarray(acts).argmax(axis=-1)
        out = []
        prev = -1
        for lab in path:
            if lab != blank and (not out or lab != out[-1] or prev == blank):
                out.append(int(lab))
            prev = lab
        return out

    @staticmethod
    def _align(gt, rec):
        """(distance, subs, dels, ins) via Levenshtein backtrace preferring
        diagonal moves (stringAlignment)."""
        n, m = len(gt), len(rec)
        if n == 0:
            return m, 0, 0, m
        if m == 0:
            return n, 0, n, 0
        mat = np.zeros((n + 1, m + 1), np.int64)
        mat[:, 0] = np.arange(n + 1)
        mat[0, :] = np.arange(m + 1)
        for i in range(1, n + 1):
            for j in range(1, m + 1):
                cost = 0 if gt[i - 1] == rec[j - 1] else 1
                mat[i, j] = min(mat[i - 1, j] + 1, mat[i, j - 1] + 1,
                                mat[i - 1, j - 1] + cost)
        subs = dels = ins = 0
        i, j = n, m
        while i and j:
            if mat[i, j] == mat[i - 1, j - 1]:
                i -= 1; j -= 1
            elif mat[i, j] == mat[i - 1, j - 1] + 1:
                subs += 1; i -= 1; j -= 1
            elif mat[i, j] == mat[i - 1, j] + 1:
                dels += 1; i -= 1
            else:
                ins += 1; j -= 1
        dels += i
        ins += j
        return subs + dels + ins, subs, dels, ins

    def update(self, activations, labels, blank=None):
        """One sequence: ``activations`` [T, num_classes] (softmax or
        logits — only argmax matters), ``labels`` the ground-truth ids."""
        acts = np.asarray(activations)
        blank = acts.shape[-1] - 1 if blank is None else blank
        rec = self.best_path(acts, blank)
        gt = [int(x) for x in np.asarray(labels).reshape(-1)]
        dist, subs, dels, ins = self._align(gt, rec)
        max_len = max(len(gt), len(rec), 1)
        self._dist += dist / max_len
        self._sub += subs / max_len
        self._del += dels / max_len
        self._ins += ins / max_len
        if dist:
            self._seq_err += 1
        self._count += 1

    def results(self):
        n = max(self._count, 1)
        return {"error": self._dist / n,
                "deletion_error": self._del / n,
                "insertion_error": self._ins / n,
                "substitution_error": self._sub / n,
                "sequence_error": self._seq_err / n}

    def eval(self, *a, **kw):
        return self.results()["error"]


class DetectionMAP:
    """Detection mean-average-precision (reference:
    gserver/evaluators/DetectionMAPEvaluator.cpp; fluid detection_map_op).

    Host-side streaming evaluator over fetched detection outputs — metric
    aggregation has no MXU work, so it stays off-device by design (the
    reference's evaluator also runs on CPU).  Feed it the static-shape
    [N, K, 6] rows from ``layers.detection_output`` ((label, score, x1, y1,
    x2, y2), -1-padded) plus padded ground truth; padding rows (label < 0)
    are ignored.

    ap_version: '11point' (VOC07 interpolation, the v1 default) or
    'integral' (area under the raw PR curve).
    """

    def __init__(self, overlap_threshold=0.5, ap_version="11point",
                 evaluate_difficult=True):
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self.evaluate_difficult = evaluate_difficult
        self.reset()

    def reset(self, *a, **kw):
        self._dets = []      # (img_id, label, score, box)
        self._gts = []       # (img_id, label, box, difficult)
        self._img_count = 0

    def update(self, detections, gt_boxes, gt_labels, gt_difficult=None):
        """detections [N,K,6]; gt_boxes [N,M,4]; gt_labels [N,M] (pad<0)."""
        det = np.asarray(detections)
        gtb = np.asarray(gt_boxes)
        gtl = np.asarray(gt_labels)
        if gtl.ndim == 3:
            gtl = gtl[..., 0]
        gtd = (np.zeros_like(gtl, bool) if gt_difficult is None
               else np.asarray(gt_difficult).astype(bool))
        for i in range(det.shape[0]):
            img = self._img_count
            self._img_count += 1
            for row in det[i]:
                if row[0] >= 0:
                    self._dets.append((img, int(row[0]), float(row[1]),
                                       row[2:6].copy()))
            for m in range(gtb.shape[1]):
                if gtl[i, m] >= 0:
                    self._gts.append((img, int(gtl[i, m]), gtb[i, m].copy(),
                                      bool(gtd[i, m])))

    @staticmethod
    def _iou(a, b):
        x1 = max(a[0], b[0]); y1 = max(a[1], b[1])
        x2 = min(a[2], b[2]); y2 = min(a[3], b[3])
        inter = max(x2 - x1, 0.0) * max(y2 - y1, 0.0)
        ua = max(a[2] - a[0], 0) * max(a[3] - a[1], 0) + \
            max(b[2] - b[0], 0) * max(b[3] - b[1], 0) - inter
        return inter / ua if ua > 0 else 0.0

    def _ap(self, tp, fp, n_pos):
        if n_pos == 0:
            return None
        tp = np.cumsum(tp).astype(np.float64)
        fp = np.cumsum(fp).astype(np.float64)
        recall = tp / n_pos
        precision = tp / np.maximum(tp + fp, 1e-12)
        if self.ap_version == "11point":
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                p = precision[recall >= t].max() if (recall >= t).any() \
                    else 0.0
                ap += p / 11.0
            return ap
        # integral: sum precision deltas over recall steps
        ap = 0.0
        prev_r = 0.0
        for p, r in zip(precision, recall):
            ap += p * (r - prev_r)
            prev_r = r
        return ap

    def eval(self, *a, **kw):
        labels = sorted({g[1] for g in self._gts})
        aps = []
        for c in labels:
            gts = [g for g in self._gts if g[1] == c]
            n_pos = sum(1 for g in gts
                        if self.evaluate_difficult or not g[3])
            dets = sorted((d for d in self._dets if d[1] == c),
                          key=lambda d: -d[2])
            matched = set()
            tp = np.zeros(len(dets)); fp = np.zeros(len(dets))
            for k, (img, _, _, box) in enumerate(dets):
                # VOC protocol: each detection is assigned to its
                # MAX-overlap gt (matched or not); a duplicate hit on an
                # already-claimed gt is a false positive
                best, best_j = 0.0, -1
                for j, (gimg, _, gbox, _) in enumerate(gts):
                    if gimg != img:
                        continue
                    ov = self._iou(box, gbox)
                    if ov > best:
                        best, best_j = ov, j
                if best >= self.overlap_threshold and best_j >= 0:
                    if not self.evaluate_difficult and gts[best_j][3]:
                        pass       # matched a difficult gt: ignored
                    elif best_j not in matched:
                        matched.add(best_j)
                        tp[k] = 1
                    else:
                        fp[k] = 1  # duplicate detection of a claimed gt
                else:
                    fp[k] = 1
            ap = self._ap(tp, fp, n_pos)
            if ap is not None:
                aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0
