"""Program-state evaluators (reference: fluid/evaluator.py:21-90 Evaluator
base with state vars + reset program, Accuracy, ChunkEvaluator).

States are persistable scope vars accumulated by metric ops inside the main
program; ``eval`` computes the aggregate, ``reset`` zeroes the states.
"""
from __future__ import annotations

import numpy as np

from .core.program import default_main_program
from .core.scope import global_scope
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from . import layers


class Evaluator:
    def __init__(self, name, **kwargs):
        self.helper = LayerHelper(name, **kwargs)
        self.states = []
        self.metrics = []

    def _create_state(self, suffix, dtype, shape):
        var = self.helper.create_global_variable(
            list(shape), dtype, name=f"{self.helper.name}.{suffix}")
        self.helper.set_variable_initializer(var, ConstantInitializer(0.0))
        self.states.append(var)
        return var

    def reset(self, executor, reset_program=None, scope=None):
        import jax.numpy as jnp
        scope = global_scope() if scope is None else scope
        for s in self.states:
            if scope.has(s.name):
                scope.set(s.name, jnp.zeros_like(scope.get(s.name)))

    def eval(self, executor, eval_program=None, scope=None):
        raise NotImplementedError


class Accuracy(Evaluator):
    """Streaming accuracy (fluid evaluator.Accuracy)."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy_eval", **kwargs)
        self.total = self._create_state("total", "float32", [1])
        self.correct = self._create_state("correct", "float32", [1])
        topk_out, topk_idx = layers.topk(input, k)
        acc = self.helper.create_variable_for_type_inference("float32", (1,))
        bc = self.helper.create_variable_for_type_inference("int32")
        bt = self.helper.create_variable_for_type_inference("int32")
        self.helper.append_op(
            type="accuracy",
            inputs={"Out": [topk_out], "Indices": [topk_idx],
                    "Label": [label]},
            outputs={"Accuracy": [acc], "Correct": [bc], "Total": [bt]})
        # accumulate into states
        bcf = layers.cast(bc, "float32")
        btf = layers.cast(bt, "float32")
        layers.sums([self.total, btf], out=self.total)
        layers.sums([self.correct, bcf], out=self.correct)
        self.metrics.append(acc)
        self.batch_accuracy = acc

    def eval(self, executor, eval_program=None, scope=None):
        scope = global_scope() if scope is None else scope
        total = float(np.asarray(scope.get(self.total.name))[0])
        correct = float(np.asarray(scope.get(self.correct.name))[0])
        return np.array([correct / max(total, 1.0)], np.float32)


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (fluid evaluator.ChunkEvaluator; chunk_eval_op)."""

    def __init__(self, input, label, chunk_scheme="IOB", num_chunk_types=1,
                 **kwargs):
        super().__init__("chunk_eval", **kwargs)
        self.num_infer = self._create_state("num_infer", "float32", [1])
        self.num_label = self._create_state("num_label", "float32", [1])
        self.num_correct = self._create_state("num_correct", "float32", [1])
        prec = self.helper.create_variable_for_type_inference("float32")
        rec = self.helper.create_variable_for_type_inference("float32")
        f1 = self.helper.create_variable_for_type_inference("float32")
        ni = self.helper.create_variable_for_type_inference("int64")
        nl = self.helper.create_variable_for_type_inference("int64")
        nc = self.helper.create_variable_for_type_inference("int64")
        self.helper.append_op(
            type="chunk_eval",
            inputs={"Inference": [input], "Label": [label]},
            outputs={"Precision": [prec], "Recall": [rec], "F1-Score": [f1],
                     "NumInferChunks": [ni], "NumLabelChunks": [nl],
                     "NumCorrectChunks": [nc]},
            attrs={"chunk_scheme": chunk_scheme,
                   "num_chunk_types": num_chunk_types})
        layers.sums([self.num_infer, layers.cast(ni, "float32")],
                    out=self.num_infer)
        layers.sums([self.num_label, layers.cast(nl, "float32")],
                    out=self.num_label)
        layers.sums([self.num_correct, layers.cast(nc, "float32")],
                    out=self.num_correct)
        self.metrics.extend([prec, rec, f1])

    def eval(self, executor, eval_program=None, scope=None):
        scope = global_scope() if scope is None else scope
        ni = float(np.asarray(scope.get(self.num_infer.name))[0])
        nl = float(np.asarray(scope.get(self.num_label.name))[0])
        nc = float(np.asarray(scope.get(self.num_correct.name))[0])
        p = nc / max(ni, 1.0)
        r = nc / max(nl, 1.0)
        f1 = 2 * p * r / max(p + r, 1e-6)
        return np.array([p, r, f1], np.float32)
