"""Parameter initializers, realized as ops in the startup program
(reference: fluid/initializer.py — Constant/Uniform/Normal/Xavier/MSRA emit
fill_constant / uniform_random / gaussian_random startup ops)."""
from __future__ import annotations

import math

import numpy as np

from .core.program import Block, Variable, default_startup_program


class Initializer:
    def __call__(self, var: Variable, block: Block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op("fill_constant", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape),
                               "dtype": var.dtype.name,
                               "value": float(self.value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op("uniform_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape),
                               "dtype": var.dtype.name,
                               "min": float(self.low),
                               "max": float(self.high),
                               "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape),
                               "dtype": var.dtype.name,
                               "mean": float(self.loc),
                               "std": float(self.scale),
                               "seed": self.seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("truncated_gaussian_random",
                        outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape),
                               "dtype": var.dtype.name,
                               "mean": float(self.loc),
                               "std": float(self.scale),
                               "seed": self.seed})


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return (shape[0] if shape else 1,) * 2
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    # conv weights are [out, in, kh, kw]; fc weights are [in, out]
    if len(shape) == 2:
        return shape[0], shape[1]
    return shape[1] * receptive, shape[0] * receptive


class XavierInitializer(Initializer):
    """Glorot (fluid initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He initialization (fluid initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in or fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    """Initialize from a concrete array (assign-from-host)."""

    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op("assign_value", outputs={"Out": [var.name]},
                        attrs={"values": self.value,
                               "dtype": var.dtype.name,
                               "shape": list(self.value.shape)})


# fluid-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer

_global_weight_initializer = XavierInitializer()
_global_bias_initializer = ConstantInitializer(0.0)
