"""Multi-tenant inference server: dynamic batching with a robustness
envelope.

Data path, per model (one :class:`~paddle_tpu.serving.model.Model`
tenant each):

    submit ──admission──▶ bounded queue ──batcher──▶ staged batches
                                              │  (stack_feeds + pad to
                                              │   bucket, double-buffered)
                                              ▼
                                        dispatcher ──▶ model fn ──▶ split
                                                                    rows,
                                                                    complete

* **Batching** — the batcher coalesces same-signature requests up to
  ``max_batch``, waiting at most ``max_wait_ms`` after the first one; the
  stacked batch (:func:`~paddle_tpu.core.executor.stack_feeds`) is padded
  up to the next power-of-two **bucket** (:func:`~paddle_tpu.core.
  executor.pad_batch`) so compiled variants are bounded by the bucket
  list, not by every observed batch size.  A bounded staging queue
  between batcher and dispatcher double-buffers: batch N+1 is stacked
  and staged while batch N executes.
* **Deadlines** — a request expired at batch formation or at dispatch
  time completes with :class:`~paddle_tpu.faults.DeadlineExceeded` and is
  never computed.
* **Admission control / load shedding** — the queue is bounded; when
  full, the request with the soonest deadline (the one most likely to
  miss anyway — "oldest deadline first") is rejected with
  :class:`~paddle_tpu.faults.Overloaded`, so the p99 of *admitted*
  requests stays bounded by queue-capacity/throughput instead of every
  request timing out together.  ``shed=False`` + unbounded queue is the
  benchmark's control arm.
* **Circuit breaking** — dispatch failures route through
  ``faults.classify``: retryable ones (transient ``XlaRuntimeError``,
  injected transients) retry per ``retry_policy`` (default: once);
  persistent failures poison only the offending model — after
  ``breaker_threshold`` consecutive failed batches its breaker opens and
  requests fail fast with :class:`~paddle_tpu.faults.ModelUnavailable`
  until a cooldown probe succeeds.  Healthy co-tenants keep serving.
* **Health** — ``warming → ready → draining → stopped``;
  :meth:`Server.health` is the readiness surface.
* **Graceful drain** — :meth:`Server.shutdown` (``drain=True``) closes
  admission (:class:`~paddle_tpu.faults.ServerClosed`), lets the batcher
  and dispatcher finish every admitted request, then joins the threads:
  zero admitted requests are dropped.  The CLI wires SIGTERM to exactly
  this, composing with the PR 6 ``Supervisor`` for relaunch.

Everything is instrumented through the observability registry
(``serving/*`` metrics, frozen in ``METRIC_NAMES``) and the JSONL event
log, and every degradation path has a deterministic fault-injection site
(``serving.request``, ``serving.dispatch``).
"""
from __future__ import annotations

import collections
import logging
import queue as _queue_mod
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import faults as _faults
from .. import observability as obs
from ..core.executor import pad_batch, stack_feeds
from ..core.registry import register_tunable
from ..testing import faultinject as _fi
from ..testing import lockwatch as _lw
from .model import Model

logger = logging.getLogger("paddle_tpu")

# Autotuner knob declaration (paddle_tpu.tuning), next to the batcher it
# controls: max_batch bounds the coalescing window (and the compiled
# bucket list), max_wait_ms trades first-request latency against batch
# fill — the right point depends on model cost per row and offered load.
register_tunable(
    "serving/batcher", side="host",
    space={"max_batch": (8, 16, 32, 64), "max_wait_ms": (1.0, 2.0, 5.0,
                                                         10.0)},
    default={"max_batch": 32, "max_wait_ms": 5.0},
    description="serving batcher coalescing policy: maximum batch size "
                "and the wait after the first queued request.")

__all__ = ["Server", "PendingResponse", "ModelError"]

# health states, in lifecycle order
WARMING, READY, DRAINING, STOPPED = "warming", "ready", "draining", "stopped"


class ModelError(RuntimeError):
    """A dispatched batch failed fatally (after any retries); carries the
    underlying error string.  The request was computed-and-lost, not
    shed — distinguish it from the admission-side rejections."""


def _buckets(max_batch: int) -> List[int]:
    """Power-of-two bucket sizes up to (and always including) max_batch."""
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


def _bucket_for(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _feed_sig(feeds: Dict[str, np.ndarray]):
    return tuple(sorted((k, v.shape, str(v.dtype)) for k, v in feeds.items()))


class PendingResponse:
    """Future-like handle for one admitted request.  Terminal exactly
    once: either ``outputs`` (a list of per-request arrays) or a typed
    error.  ``result()`` blocks; ``add_done_callback`` fires on the
    completing thread (or immediately if already terminal)."""

    __slots__ = ("id", "model", "feeds", "sig", "deadline", "t_admit",
                 "outputs", "error", "span", "dispatch_ms", "_event",
                 "_callbacks", "_lock")

    def __init__(self, req_id, model: str, feeds, deadline: Optional[float]):
        self.id = req_id
        self.model = model
        self.feeds = feeds
        self.sig = _feed_sig(feeds)
        self.deadline = deadline          # time.monotonic() or None
        self.t_admit = time.monotonic()
        self.outputs = None
        self.error: Optional[BaseException] = None
        # model-dispatch wall of the batch that served this request (ms);
        # None for rejected/expired requests.  total latency minus this
        # is the queue/batch/staging wait — the fleet autoscaler's
        # scale-out signal (serving_budget's decomposition, live)
        self.dispatch_ms: Optional[float] = None
        # lifecycle tracing span (one trace per request), started at
        # admission on the submitting thread, ended by _complete on
        # whichever thread completes the request
        self.span = None
        self._event = threading.Event()
        self._callbacks: List[Callable] = []
        self._lock = _lw.make_lock("serving.request")

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                >= self.deadline)

    def done(self) -> bool:
        return self._event.is_set()

    def _complete(self, outputs=None, error: Optional[BaseException] = None):
        with self._lock:
            if self._event.is_set():
                return False
            self.outputs = outputs
            self.error = error
            cbs, self._callbacks = self._callbacks, []
            self._event.set()
        obs.observe_hist("serving/request_ms",
                         (time.monotonic() - self.t_admit) * 1e3)
        if self.span is not None:
            self.span.end(status="ok" if error is None
                          else type(error).__name__)
        for cb in cbs:
            try:
                cb(self)
            except Exception:
                logger.exception("serving: response callback failed")
        return True

    def add_done_callback(self, cb: Callable):
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb(self)

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.id!r}: no response within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.outputs


class _ModelRuntime:
    """Per-tenant state: admission queue, batcher + dispatcher threads,
    circuit breaker."""

    def __init__(self, model: Model, server: "Server"):
        self.model = model
        self.srv = server
        self.lock = _lw.make_lock("serving.rt")
        self.cond = _lw.make_condition("serving.rt", self.lock)
        self.queue: collections.deque = collections.deque()
        self.staging: _queue_mod.Queue = _queue_mod.Queue(
            maxsize=max(1, server.staging_depth))
        self.batcher: Optional[threading.Thread] = None
        self.dispatcher: Optional[threading.Thread] = None
        self.closed = False               # no more admissions (drain/stop)
        # breaker
        self.consecutive_failures = 0
        self.breaker_open = False
        self.breaker_open_until = 0.0     # monotonic; probe allowed after
        self.served = 0
        self.dispatched_batches = 0

    # -- breaker ------------------------------------------------------------
    def breaker_state(self, now: Optional[float] = None) -> str:
        with self.lock:
            if not self.breaker_open:
                return "closed"
            now = time.monotonic() if now is None else now
            return "half_open" if now >= self.breaker_open_until else "open"

    def _note_batch_failure(self, err: BaseException, span=None):
        opened = False
        with self.lock:
            self.consecutive_failures += 1
            if (self.consecutive_failures >= self.srv.breaker_threshold
                    and not self.breaker_open):
                self.breaker_open = True
                opened = True
            if self.breaker_open:
                self.breaker_open_until = (time.monotonic()
                                           + self.srv.breaker_cooldown_s)
        if opened:
            obs.inc_counter("serving/breaker_open")
            obs.emit_event("serving", event="breaker_open",
                           model=self.model.name,
                           error=f"{type(err).__name__}: {err}")
            if span is not None:
                span.event("breaker_open",
                           error=f"{type(err).__name__}: {err}")
            logger.error("serving: circuit breaker OPEN for model %r "
                         "after %d consecutive failures (%s: %s)",
                         self.model.name, self.consecutive_failures,
                         type(err).__name__, err)

    def _note_batch_success(self, span=None):
        closed = False
        with self.lock:
            self.consecutive_failures = 0
            if self.breaker_open:
                self.breaker_open = False
                closed = True
        if closed:
            obs.emit_event("serving", event="breaker_close",
                           model=self.model.name)
            if span is not None:
                span.event("breaker_close")
            logger.info("serving: circuit breaker closed for model %r "
                        "(probe succeeded)", self.model.name)


class Server:
    """In-process multi-tenant inference server (see module docstring).

    Minimal use::

        srv = Server(max_batch=8, max_wait_ms=2)
        srv.add_model(Model.from_artifact("/path/to/export"))
        srv.start()
        out = srv.infer({"img": example}, timeout=1.0)   # single tenant
        srv.shutdown()                                   # graceful drain

    ``deadline_ms=None`` disables deadlines; ``queue_capacity=None``
    disables admission bounds (with ``shed=False`` this is the
    no-robustness control arm the serving benchmark measures against).
    """

    def __init__(self, max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = 100.0,
                 queue_capacity: Optional[int] = 256,
                 shed: bool = True,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 staging_depth: int = 2,
                 retry_policy: Optional[_faults.RetryPolicy] = None,
                 warmup: bool = True,
                 warmup_buckets: Optional[Sequence[int]] = None,
                 autotune: Optional[bool] = None):
        # max_batch/max_wait_ms default to the hand-picked (32, 5.0) —
        # or, under the autotune opt-in (``autotune=True``, else the
        # `autotune` flag), the persisted serving/batcher winner for
        # this host.  Explicit arguments always win.
        if max_batch is None or max_wait_ms is None:
            from ..core.registry import resolve_tuned
            cfg = resolve_tuned("serving/batcher",
                                {"max_batch": 32, "max_wait_ms": 5.0},
                                autotune)
            if max_batch is None:
                max_batch = cfg["max_batch"]
            if max_wait_ms is None:
                max_wait_ms = cfg["max_wait_ms"]
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_capacity is not None and queue_capacity < 1:
            raise ValueError(
                f"queue_capacity must be >= 1 or None, got {queue_capacity}")
        if breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold must be >= 1, got {breaker_threshold}")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.default_deadline_ms = deadline_ms
        self.queue_capacity = queue_capacity
        self.shed = bool(shed)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.staging_depth = int(staging_depth)
        # "transient XlaRuntimeErrors retry once": 2 attempts total
        self.retry_policy = retry_policy if retry_policy is not None else \
            _faults.RetryPolicy(max_attempts=2, backoff_base_s=0.005,
                                backoff_max_s=0.1, seed=0)
        self.buckets = _buckets(self.max_batch)
        self.warmup = bool(warmup)
        self.warmup_buckets = list(warmup_buckets) if warmup_buckets \
            else [self.buckets[0], self.buckets[-1]]
        self._models: Dict[str, _ModelRuntime] = {}
        self._decode: Dict[str, object] = {}   # name -> DecodeRuntime
        self._state = WARMING
        self._state_lock = _lw.make_lock("serving.server.state")
        self._req_counter = 0
        self._started = False

    # -- lifecycle -----------------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    def _set_state(self, state: str):
        with self._state_lock:
            self._state = state
        obs.emit_event("serving", event="state", state=state)

    def ready(self) -> bool:
        return self._state == READY

    def add_model(self, model: Model):
        if self._started:
            raise RuntimeError("Server.add_model: server already started")
        if model.name in self._models:
            raise ValueError(f"duplicate model name {model.name!r}")
        self._models[model.name] = _ModelRuntime(model, self)

    def add_decode_model(self, engine, name: Optional[str] = None,
                         mode: str = "continuous",
                         step_wait_ms: Optional[float] = None,
                         retry_policy: Optional[_faults.RetryPolicy] = None,
                         autotune: Optional[bool] = None):
        """Mount an incremental-decode slot pool as a tenant: ``engine``
        is a :class:`~paddle_tpu.serving.decode.DecodeEngine`; requests
        go through :meth:`submit_decode`.  The pool inherits the server's
        deadline/shedding/breaker envelope and shares its lifecycle
        (start/drain/shutdown/health)."""
        from .decode import DecodeRuntime   # lazy: decode imports server
        if self._started:
            raise RuntimeError(
                "Server.add_decode_model: server already started")
        pool = DecodeRuntime(
            engine, name=name, mode=mode, step_wait_ms=step_wait_ms,
            default_deadline_ms=self.default_deadline_ms,
            queue_capacity=self.queue_capacity, shed=self.shed,
            breaker_threshold=self.breaker_threshold,
            breaker_cooldown_s=self.breaker_cooldown_s,
            retry_policy=(retry_policy if retry_policy is not None
                          else self.retry_policy),
            autotune=autotune)
        if pool.name in self._models or pool.name in self._decode:
            raise ValueError(f"duplicate model name {pool.name!r}")
        self._decode[pool.name] = pool
        return pool

    def start(self):
        """Warm up every tenant, spawn its batcher/dispatcher pair, flip
        to ready.  Warmup dispatches the model's example at the smallest
        and largest bucket so steady-state requests never pay a compile
        (other buckets compile on first use, tagged cold in telemetry)."""
        if self._started:
            raise RuntimeError("Server.start: already started")
        if not self._models and not self._decode:
            raise ValueError("Server.start: no models added")
        self._started = True
        self._set_state(WARMING)
        for rt in self._models.values():
            if self.warmup and rt.model.example is not None:
                for b in self.warmup_buckets:
                    stacked = pad_batch(
                        stack_feeds([rt.model.example]), b)
                    outs = rt.model(stacked)
                    for o in outs:                     # block: real warmup
                        if o is not None:
                            np.asarray(o)
            rt.batcher = threading.Thread(
                target=self._batch_loop, args=(rt,),
                name=f"pt-serving-batch-{rt.model.name}", daemon=True)
            rt.dispatcher = threading.Thread(
                target=self._dispatch_loop, args=(rt,),
                name=f"pt-serving-dispatch-{rt.model.name}", daemon=True)
            rt.batcher.start()
            rt.dispatcher.start()
        for pool in self._decode.values():
            pool.start(warmup=self.warmup)
        self._set_state(READY)
        return self

    def begin_drain(self):
        """Close admission; keep completing admitted work.  Idempotent."""
        if self._state in (DRAINING, STOPPED):
            return
        self._set_state(DRAINING)
        for rt in self._models.values():
            with rt.cond:
                rt.closed = True
                rt.cond.notify_all()
        for pool in self._decode.values():
            pool.close()

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the server.  ``drain=True`` (graceful): admission closes,
        every admitted request completes (results or typed errors), then
        threads join.  ``drain=False``: queued requests complete with
        :class:`~paddle_tpu.faults.ServerClosed` instead of being
        computed; the in-flight batch still finishes."""
        if not self._started:
            self._set_state(STOPPED)
            return
        if not drain:
            # abort queued work first, then drain the (now empty) queues
            self._set_state(DRAINING)
            for rt in self._models.values():
                with rt.cond:
                    rt.closed = True
                    aborted = list(rt.queue)
                    rt.queue.clear()
                    rt.cond.notify_all()
                for r in aborted:
                    r._complete(error=_faults.ServerClosed(
                        "server stopped before this request was dispatched"))
        else:
            self.begin_drain()
        deadline = None if timeout is None else time.monotonic() + timeout
        for rt in self._models.values():
            for t in (rt.batcher, rt.dispatcher):
                if t is None:
                    continue
                t.join(None if deadline is None
                       else max(0.0, deadline - time.monotonic()))
        for pool in self._decode.values():
            pool.shutdown(drain=drain,
                          timeout=None if deadline is None
                          else max(0.0, deadline - time.monotonic()))
        self._set_state(STOPPED)

    # -- admission -----------------------------------------------------------
    def _resolve_model(self, model: Optional[str]) -> _ModelRuntime:
        if model is None:
            if len(self._models) != 1:
                raise ValueError(
                    f"model name required (tenants: "
                    f"{sorted(self._models)})")
            return next(iter(self._models.values()))
        rt = self._models.get(model)
        if rt is None:
            raise ValueError(f"unknown model {model!r} "
                             f"(tenants: {sorted(self._models)})")
        return rt

    def submit(self, feeds: Dict[str, object], model: Optional[str] = None,
               deadline_ms: Optional[float] = -1.0,
               req_id=None, trace_parent=None) -> PendingResponse:
        """Admit one single-example request (feeds carry NO batch axis).

        Returns a :class:`PendingResponse` once admitted.  Admission
        failures raise typed errors immediately: ``ServerClosed``
        (draining/stopped), ``ModelUnavailable`` (breaker open),
        ``Overloaded`` (queue full and this request had the soonest
        deadline).  ``deadline_ms``: per-request override; the default
        sentinel (-1) means the server default, ``None`` means no
        deadline.  ``trace_parent``: a remote caller's extracted trace
        context (``tracing.RemoteParent``) — the request span parents
        onto it instead of starting a fresh trace, joining this
        replica's work to the submitting process's trace.
        """
        rt = self._resolve_model(model)
        if _fi.ENABLED:
            action = _fi.check("serving.request")
            if action is not None:
                if action.startswith("delay"):
                    _, _, ms = action.partition(":")
                    time.sleep((float(ms) if ms else 50.0) / 1e3)
                else:
                    _fi.raise_for(action, "serving.request")
        if req_id is None:
            with self._state_lock:
                self._req_counter += 1
                req_id = self._req_counter
        # one trace per request (ROOT forces it even if the submitting
        # thread is inside some other traced region — unless a remote
        # caller propagated its own context), started BEFORE the
        # admission checks so every typed rejection — ServerClosed,
        # breaker-open ModelUnavailable, feed-validation errors,
        # Overloaded shedding — reaches the log with its status; those
        # rejections are exactly what an overload trace needs to show.
        # The span ends at the terminal completion, or here on a
        # rejection raise.
        sp = obs.tracing.start_span(
            "serving/request",
            parent=trace_parent if trace_parent is not None
            else obs.tracing.ROOT,
            model=rt.model.name, id=req_id)
        try:
            if self._state != READY:
                raise _faults.ServerClosed(
                    f"server is {self._state}; admission closed")
            if rt.breaker_state() == "open":
                raise _faults.ModelUnavailable(
                    f"model {rt.model.name!r}: circuit breaker open "
                    f"(repeated fatal dispatch errors); retry after "
                    f"cooldown")
            if deadline_ms == -1.0:
                deadline_ms = self.default_deadline_ms
            now = time.monotonic()
            deadline = None if deadline_ms is None \
                else now + deadline_ms / 1e3
            req = PendingResponse(req_id, rt.model.name,
                                  rt.model.coerce_feeds(feeds), deadline)
            req.span = sp
            return self._admit(rt, req)
        except BaseException as e:
            sp.end(status=type(e).__name__)
            raise

    def _admit(self, rt: _ModelRuntime, req: PendingResponse):
        shed_req = None
        with rt.cond:
            if rt.closed:
                raise _faults.ServerClosed(
                    f"server is {self._state}; admission closed")
            if (self.queue_capacity is not None
                    and len(rt.queue) >= self.queue_capacity):
                if not self.shed:
                    # bounded queue without shedding: plain backpressure —
                    # reject the newcomer
                    obs.inc_counter("serving/shed")
                    obs.emit_event("serving", event="shed",
                                   model=rt.model.name, victim="incoming")
                    raise _faults.Overloaded(
                        f"model {rt.model.name!r}: queue full "
                        f"({self.queue_capacity})")
                # oldest-deadline-first: shed whoever is most likely to
                # miss — the soonest deadline among queued + incoming.
                # Deadline-less requests are never preferred as victims;
                # when NOBODY has a deadline this degrades to rejecting
                # the newcomer (plain backpressure).
                victim = min(
                    [r for r in list(rt.queue) + [req]
                     if r.deadline is not None],
                    key=lambda r: r.deadline,
                    default=req)
                if victim is req:
                    obs.inc_counter("serving/shed")
                    obs.emit_event("serving", event="shed",
                                   model=rt.model.name, victim="incoming")
                    raise _faults.Overloaded(
                        f"model {rt.model.name!r}: queue full "
                        f"({self.queue_capacity}) and this request has "
                        f"the soonest deadline")
                rt.queue.remove(victim)
                shed_req = victim
                rt.queue.append(req)
                rt.cond.notify()
            else:
                rt.queue.append(req)
                rt.cond.notify()
        if shed_req is not None:
            obs.inc_counter("serving/shed")
            obs.emit_event("serving", event="shed", model=rt.model.name,
                           victim="queued")
            shed_req._complete(error=_faults.Overloaded(
                f"model {rt.model.name!r}: shed under overload "
                f"(oldest deadline first)"))
        obs.inc_counter("serving/requests")
        return req

    def infer(self, feeds: Dict[str, object], model: Optional[str] = None,
              deadline_ms: Optional[float] = -1.0,
              timeout: Optional[float] = None):
        """Synchronous submit+wait; raises the typed error on rejection."""
        return self.submit(feeds, model=model,
                           deadline_ms=deadline_ms).result(timeout)

    def submit_decode(self, tokens, max_new_tokens: int,
                      model: Optional[str] = None,
                      deadline_ms: Optional[float] = -1.0,
                      req_id=None) -> PendingResponse:
        """Admit one generate request to a decode slot pool (see
        ``add_decode_model``).  Completes with ``{"tokens", "finish",
        "ttft_ms", "inter_token_ms"}``; admission rejections raise the
        same typed errors as :meth:`submit`."""
        if model is None:
            if len(self._decode) != 1:
                raise ValueError(
                    f"decode model name required (decode tenants: "
                    f"{sorted(self._decode)})")
            pool = next(iter(self._decode.values()))
        else:
            pool = self._decode.get(model)
            if pool is None:
                raise ValueError(
                    f"unknown decode model {model!r} (decode tenants: "
                    f"{sorted(self._decode)})")
        if self._state != READY:
            raise _faults.ServerClosed(
                f"server is {self._state}; admission closed")
        if deadline_ms == -1.0:
            deadline_ms = self.default_deadline_ms
        return pool.submit(tokens, max_new_tokens,
                           deadline_ms=deadline_ms, req_id=req_id)

    # -- health --------------------------------------------------------------
    def health(self) -> dict:
        models = {}
        for name, rt in self._models.items():
            with rt.lock:
                depth = len(rt.queue)
                served = rt.served
                batches = rt.dispatched_batches
            models[name] = {
                "breaker": rt.breaker_state(),
                "queue_depth": depth,
                "served": served,
                "batches": batches,
            }
        out = {"state": self._state, "ready": self.ready(),
               "models": models}
        if self._decode:
            out["decode"] = {name: pool.health()
                             for name, pool in self._decode.items()}
        return out

    # -- batcher -------------------------------------------------------------
    def _expire(self, req: PendingResponse, where: str) -> bool:
        """Complete an expired request with DeadlineExceeded; True if it
        was expired.  Never dispatched, never computed."""
        if not req.expired():
            return False
        obs.inc_counter("serving/deadline_expired")
        obs.emit_event("serving", event="deadline_expired",
                       model=req.model, where=where)
        req._complete(error=_faults.DeadlineExceeded(
            f"request {req.id!r}: deadline expired before {where}"))
        return True

    def _batch_loop(self, rt: _ModelRuntime):
        """Coalesce queued requests into staged batches until drained."""
        try:
            while True:
                with rt.cond:
                    while not rt.queue and not rt.closed:
                        rt.cond.wait(timeout=0.1)
                    if not rt.queue and rt.closed:
                        break
                    obs.observe_hist("serving/queue_depth", len(rt.queue))
                    first = rt.queue.popleft()
                if self._expire(first, "batching"):
                    continue
                batch = [first]
                wait_until = time.monotonic() + self.max_wait_s
                while len(batch) < self.max_batch:
                    with rt.cond:
                        # only same-signature requests can stack; others
                        # stay queued, order preserved
                        got = mismatched = None
                        for r in rt.queue:
                            if r.sig == first.sig:
                                got = r
                                break
                            mismatched = r
                        if got is not None:
                            rt.queue.remove(got)
                    if got is not None:
                        if not self._expire(got, "batching"):
                            batch.append(got)
                        continue
                    if mismatched is not None:
                        # a different signature is waiting: ship what we
                        # have now and start its batch next iteration
                        break
                    if rt.closed:       # draining: no waiting for stragglers
                        break
                    remaining = wait_until - time.monotonic()
                    if remaining <= 0:
                        break
                    with rt.cond:
                        if not rt.queue:
                            rt.cond.wait(timeout=remaining)
                live = [r for r in batch
                        if not self._expire(r, "batching")]
                if not live:
                    continue
                stacked = stack_feeds([r.feeds for r in live])
                padded = pad_batch(stacked,
                                   _bucket_for(len(live), self.buckets))
                rt.staging.put((live, padded))
        except BaseException:
            logger.exception("serving: batcher for model %r died",
                             rt.model.name)
        finally:
            rt.staging.put(None)        # dispatcher drain sentinel

    # -- dispatcher ----------------------------------------------------------
    def _dispatch_batch(self, rt: _ModelRuntime, padded, span=None):
        """One model call through the injection site + retry rim."""
        def attempt():
            if _fi.ENABLED:
                action = _fi.check("serving.dispatch")
                if action is not None:
                    if action == "fatal":
                        raise _faults.InjectedFault(
                            "injected fatal fault at serving.dispatch")
                    _fi.raise_for(action, "serving.dispatch")
            return rt.model(padded)

        def on_retry(i, e, d):
            obs.inc_counter("fault/retries")
            obs.emit_event("fault", event="retry", site="serving.dispatch",
                           attempt=i + 1, delay_s=round(d, 4),
                           error=f"{type(e).__name__}: {e}")
            if span is not None:
                span.event("retry", attempt=i + 1, delay_s=round(d, 4),
                           error=f"{type(e).__name__}: {e}")

        if self.retry_policy is None:
            return attempt()
        return _faults.retry_call(
            attempt, self.retry_policy,
            what=f"serving dispatch [{rt.model.name}]", on_retry=on_retry)

    def _dispatch_loop(self, rt: _ModelRuntime):
        while True:
            item = rt.staging.get()
            if item is None:
                break
            live, padded = item
            try:
                self._dispatch_one(rt, live, padded)
            except BaseException as e:   # noqa: BLE001 — containment:
                # a dispatcher death would wedge the staging queue, block
                # the batcher forever and hang shutdown(drain=True); any
                # request this batch carried gets a terminal error instead
                logger.exception("serving: dispatcher for model %r hit an "
                                 "unexpected error", rt.model.name)
                err = ModelError(
                    f"model {rt.model.name!r}: internal dispatch error "
                    f"({type(e).__name__}: {e})")
                for r in live:
                    r._complete(error=err)

    def _dispatch_one(self, rt: _ModelRuntime, live, padded):
        # deadline re-check at the dispatch rim: staging adds wait
        rows = [(i, r) for i, r in enumerate(live)
                if not self._expire(r, "dispatch")]
        if not rows:
            return
        if rt.breaker_state() == "open":
            for _, r in rows:
                r._complete(error=_faults.ModelUnavailable(
                    f"model {rt.model.name!r}: circuit breaker open"))
            return
        bucket = next((int(v.shape[0]) for v in padded.values()), 0)
        # batch span: its OWN trace (a batch is a join point, not a
        # child of any single request), linking every member request's
        # trace by id — retry attempts and breaker transitions attach as
        # span events, so a degraded batch's story reads in one record
        bsp = obs.tracing.start_span(
            "serving/batch", parent=obs.tracing.ROOT,
            model=rt.model.name, size=len(rows), bucket=bucket,
            requests=[r.id for _, r in rows],
            traces=[r.span.trace_id for _, r in rows
                    if r.span is not None])
        t0 = time.monotonic()
        try:
            outs = self._dispatch_batch(rt, padded, span=bsp)
            # materialize + split INSIDE the failure rim: a model whose
            # outputs are not row-wise indexable (scalar fetch, ragged
            # return) is a model failure, not a server crash
            split = [[None if o is None else np.asarray(o[i])
                      for o in outs] for i, _ in rows]
        except BaseException as e:
            rt._note_batch_failure(e, span=bsp)
            err = ModelError(
                f"model {rt.model.name!r}: dispatch failed "
                f"({type(e).__name__}: {e})")
            obs.emit_event("serving", event="error",
                           model=rt.model.name,
                           error=f"{type(e).__name__}: {e}")
            for _, r in rows:
                r._complete(error=err)
            bsp.end(status=type(e).__name__)
            return
        dispatch_ms = (time.monotonic() - t0) * 1e3
        rt._note_batch_success(span=bsp)
        obs.inc_counter("serving/batches")
        obs.observe_hist("serving/batch_size", len(rows))
        with rt.lock:
            rt.dispatched_batches += 1
            rt.served += len(rows)
        obs.emit_event("serving", event="batch", model=rt.model.name,
                       size=len(rows), bucket=bucket,
                       dispatch_ms=round(dispatch_ms, 3))
        for (_, r), out in zip(rows, split):
            r.dispatch_ms = dispatch_ms
            r._complete(outputs=out)
        bsp.end(status="ok", dispatch_ms=round(dispatch_ms, 3))
