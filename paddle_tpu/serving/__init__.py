"""Production serving runtime: dynamic batching with admission control,
deadlines, load shedding, circuit breaking, and graceful drain.

The reference framework shipped a dedicated deployment surface
(``paddle/capi`` — the C inference API) because training machinery is
the wrong rim for serving; this package is its TPU-native successor,
built on the substrate the repo already has: AOT ``Executor.compile()``
/ exported StableHLO artifacts for zero-compile warm start,
``stack_feeds`` for request coalescing, the observability registry for
per-request telemetry, and ``faults``/``faultinject`` for the
degradation paths.

* :class:`~paddle_tpu.serving.model.Model` — one servable tenant
  (artifact dir, ``CompiledProgram``, or live program).
* :class:`~paddle_tpu.serving.server.Server` — the multi-tenant server:
  bounded-queue admission, max-batch/max-wait batching into padded
  power-of-two buckets, per-request deadlines, oldest-deadline-first
  load shedding, per-model circuit breaking, warming/ready/draining
  health states, and graceful drain.
* ``python -m paddle_tpu serve --model DIR ...`` — the stdio-protocol
  process form (:mod:`paddle_tpu.serving.cli`): SIGTERM drains and
  exits 0, composing with ``distributed.supervisor`` for relaunch.
* :mod:`paddle_tpu.serving.decode` — continuous-batching incremental
  decode: KV-cache slot pools (``DecodeEngine`` + ``DecodeRuntime``)
  mounted as tenants via ``Server.add_decode_model`` /
  ``Server.submit_decode``, with per-token-step admit/evict.

ZERO COST WHEN UNUSED: ``import paddle_tpu`` must never import this
package (tier-1 pins that, plus byte-identical training-path behavior
with it loaded).  Typed rejections (``Overloaded``, ``DeadlineExceeded``,
``ServerClosed``, ``ModelUnavailable``) therefore live in
:mod:`paddle_tpu.faults`, importable without the server.
"""
from ..faults import (DeadlineExceeded, ModelUnavailable, Overloaded,
                      ServerClosed)
from .model import Model
from .server import ModelError, PendingResponse, Server
from .decode import DecodeEngine, DecodeRuntime

__all__ = [
    "Model", "Server", "PendingResponse", "ModelError",
    "DecodeEngine", "DecodeRuntime",
    "Overloaded", "DeadlineExceeded", "ServerClosed", "ModelUnavailable",
]
