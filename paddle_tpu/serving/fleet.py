"""Serving fleet: N server replicas behind a queue-depth-aware router,
with supervisor-backed relaunch and metric-driven replica autoscaling.

The reference framework's serving story is the C inference API deployed
behind a web service; one process, however robust (PR 8), is not a
fleet.  This module horizontally scales the serving runtime as library
code:

* **Replicas** — :class:`LocalReplica` wraps an in-process
  :class:`~paddle_tpu.serving.server.Server` (tests, single-process
  ``serve --http``); :class:`ProcessReplica` supervises one
  ``python -m paddle_tpu serve`` subprocess over its stdio JSON
  protocol, including the ``{"cmd": "health"}`` control-plane poll.
* **Router** (:class:`FleetRouter`) — load-balances ``submit()`` onto
  the *ready* replica with the lowest live ``serving/queue_depth``
  (health-polled, plus the requests routed since the last poll).
  Replicas leave the routable set (an **eviction**) when their health
  state leaves ``ready`` (draining/stopped), their circuit breaker
  opens, their health goes stale, or they die — and re-enter it when
  the condition clears.  A replica that dies with admitted requests
  in flight triggers **failover**: every lost request is resubmitted
  to a surviving replica (inference is stateless), so a SIGKILL under
  load drops zero admitted requests fleet-wide.  Signal-dead replicas
  are relaunched through the PR 6 supervisor's bounded-restart
  accounting (:meth:`~paddle_tpu.distributed.supervisor.Supervisor.
  relaunch_gate`) with exponential backoff.
* **Autoscaler** (:class:`AutoscalePolicy` + the router's autoscale
  thread) — scale-out triggers when the queue-wait share of the rolling
  p99 (the live form of the PR 10 ``serving_budget`` decomposition:
  ``wait = total - dispatch`` per completed request) exceeds a
  threshold: latency dominated by queueing means more replicas help;
  latency dominated by dispatch means they don't.  Sustained idle
  (empty queues, per-replica rate under a floor) scales in through
  graceful drain.  Every decision lands as a ``fleet`` JSONL event and
  a ``fleet/autoscale`` span, so ``trace``/``doctor``/``stats``
  attribute fleet behavior.

Clients only ever see the PR 8 typed rejections (``Overloaded``,
``DeadlineExceeded``, ``ServerClosed``, ``ModelUnavailable``) plus
``ModelError`` — replica loss is an internal failover, not a client
error.

ZERO COST WHEN UNUSED: nothing in ``paddle_tpu`` — including
``paddle_tpu.serving`` itself — imports this module at top level
(repo-lint enforced); only the ``fleet`` CLI and explicit imports pay
for it.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import queue as _queue_mod
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from .. import faults as _faults
from .. import observability as obs
from ..distributed.supervisor import Supervisor
from ..testing import lockwatch as _lw
from .server import Server
from .server import ModelError as _ModelError

logger = logging.getLogger("paddle_tpu")

__all__ = ["FleetRouter", "AutoscalePolicy", "LocalReplica",
           "ProcessReplica", "serve_argv"]

# replica lifecycle: the PR 8 health states plus the fleet-only terminal
DEAD = "dead"

# wire error name -> typed exception (the stdio protocol's error lines)
_WIRE_ERRORS = {
    "Overloaded": _faults.Overloaded,
    "DeadlineExceeded": _faults.DeadlineExceeded,
    "ServerClosed": _faults.ServerClosed,
    "ModelUnavailable": _faults.ModelUnavailable,
}


class ReplicaGone(_faults.TransientError):
    """Internal: the replica holding this request died before answering.
    Routed requests never surface this — the router fails over to a
    surviving replica or completes with a public typed error."""


def serve_argv(model_args: Sequence[str], *, max_batch: Optional[int] = None,
               max_wait_ms: Optional[float] = None,
               deadline_ms: Optional[float] = None,
               queue: Optional[int] = None, warmup_all: bool = False,
               extra: Sequence[str] = ()) -> List[str]:
    """The ``python -m paddle_tpu serve`` command line for one replica —
    the same artifacts/flags for every member of the fleet."""
    argv = [sys.executable, "-m", "paddle_tpu", "serve"]
    for m in model_args:
        argv += ["--model", m]
    if max_batch is not None:
        argv += ["--max-batch", str(max_batch)]
    if max_wait_ms is not None:
        argv += ["--max-wait-ms", str(max_wait_ms)]
    if deadline_ms is not None:
        argv += ["--deadline-ms", str(deadline_ms)]
    if queue is not None:
        argv += ["--queue", str(queue)]
    if warmup_all:
        argv += ["--warmup-all"]
    return argv + list(extra)


class FleetPending:
    """Future-like handle for one fleet-routed request.  Stable across
    failover: the client holds ONE handle while the router may carry the
    request through several replicas.  Terminal exactly once."""

    __slots__ = ("id", "model", "feeds", "deadline_ms", "outputs", "error",
                 "dispatch_ms", "t_admit", "attempts", "_event",
                 "_callbacks", "_lock", "ctx")

    def __init__(self, req_id, model: Optional[str], feeds,
                 deadline_ms, ctx: Optional[str] = None):
        self.id = req_id
        self.model = model
        self.feeds = feeds
        self.deadline_ms = deadline_ms
        # wire trace context captured at admission (None when the router
        # is not observing): survives failover, so a request re-routed
        # to a second replica still joins the same trace
        self.ctx = ctx
        self.outputs = None
        self.error: Optional[BaseException] = None
        self.dispatch_ms: Optional[float] = None
        self.t_admit = time.monotonic()
        self.attempts = 0            # replicas this request was offered to
        self._event = threading.Event()
        self._callbacks: List[Callable] = []
        self._lock = _lw.make_lock("fleet.request")

    def done(self) -> bool:
        return self._event.is_set()

    def _complete(self, outputs=None, error=None, dispatch_ms=None):
        with self._lock:
            if self._event.is_set():
                return False
            self.outputs = outputs
            self.error = error
            self.dispatch_ms = dispatch_ms
            cbs, self._callbacks = self._callbacks, []
            self._event.set()
        for cb in cbs:
            try:
                cb(self)
            except Exception:
                logger.exception("fleet: response callback failed")
        return True

    def add_done_callback(self, cb: Callable):
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb(self)

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.id!r}: no response within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.outputs


# ---------------------------------------------------------------------------
# replicas
# ---------------------------------------------------------------------------
class LocalReplica:
    """One in-process :class:`~paddle_tpu.serving.server.Server` as a
    fleet member — the fast path for tests and single-process fronts."""

    def __init__(self, server: Server, name: str = "local"):
        self.server = server
        self.name = name
        self.routed_since_poll = 0
        self.last_health: dict = {}
        self.last_health_ts = time.monotonic()
        self.last_metrics: Optional[dict] = None
        self.last_metrics_ts = 0.0
        self.last_identity: Optional[dict] = None
        self.restarts = 0
        self.cordoned = False

    # -- surface shared with ProcessReplica ---------------------------------
    @property
    def alive(self) -> bool:
        return self.server.state not in ("stopped",)

    @property
    def state(self) -> str:
        return self.server.state

    def poll_health(self, metrics: bool = False):
        self.last_health = self.server.health()
        self.last_health_ts = time.monotonic()
        self.routed_since_poll = 0
        if metrics:
            # in-process member: its registry IS this process's registry
            self.last_metrics = obs.metrics_snapshot()
            self.last_metrics_ts = self.last_health_ts
            self.last_identity = {"role": "local", "pid": os.getpid()}

    def queue_depth(self) -> int:
        models = (self.last_health or {}).get("models", {})
        return sum(int(m.get("queue_depth", 0)) for m in models.values())

    def breaker_open(self, model: Optional[str]) -> bool:
        models = (self.last_health or {}).get("models", {})
        if model is not None:
            return models.get(model, {}).get("breaker") == "open"
        return any(m.get("breaker") == "open" for m in models.values())

    def submit(self, fp: FleetPending):
        """Admit ``fp``; terminal results (or typed errors raised here at
        admission) propagate through the router's completion path."""
        pending = self.server.submit(
            fp.feeds, model=fp.model, deadline_ms=fp.deadline_ms,
            req_id=fp.id,
            trace_parent=obs.tracing.extract(fp.ctx)
            if fp.ctx is not None else None)
        self.routed_since_poll += 1

        def relay(p):
            err = p.error
            if isinstance(err, _faults.ServerClosed):
                # the replica aborted an admitted request (non-drain
                # shutdown / death): internal loss, let the router
                # fail it over instead of surfacing the abort
                err = ReplicaGone(str(err))
            if err is not None:
                self._terminal(fp, error=err)
            else:
                self._terminal(fp, outputs=p.outputs,
                               dispatch_ms=p.dispatch_ms)

        pending.add_done_callback(relay)

    def _terminal(self, fp, **kw):
        # bound by the router at registration; LocalReplica keeps the
        # hook so both replica kinds share one completion path
        self.on_terminal(fp, **kw)

    on_terminal: Callable = None    # set by the router

    def begin_drain(self):
        """Graceful: admission closes now; a background thread finishes
        the drain so the replica reaches ``stopped`` (and the router's
        reaper) once every admitted request completes — the in-process
        analog of the serve CLI's SIGTERM path."""
        self.server.begin_drain()
        threading.Thread(
            target=lambda: self.server.shutdown(drain=True),
            name=f"pt-fleet-drain-{self.name}", daemon=True).start()

    def stop(self, drain: bool = True):
        self.server.shutdown(drain=drain)

    def kill(self):
        """Abrupt death for tests: queued admitted work is aborted (the
        router sees ReplicaGone and fails over).  Bounded join: a
        dispatch wedged mid-batch must not block the killer."""
        self.server.shutdown(drain=False, timeout=5.0)


class ProcessReplica:
    """One ``python -m paddle_tpu serve`` subprocess as a fleet member,
    driven over its stdio JSON protocol.

    A reader thread dispatches stdout lines: responses complete routed
    requests, ``health`` answers refresh the routing signal, ``state``
    events track the replica lifecycle.  EOF with requests in flight
    marks the replica :data:`DEAD` and hands every lost request back to
    the router for failover.  ``cpu_affinity`` pins the child to fixed
    cores — the fleet benchmark's "identical per-replica resources"
    control."""

    def __init__(self, argv: Sequence[str], name: str,
                 env: Optional[dict] = None,
                 cpu_affinity: Optional[Sequence[int]] = None,
                 ready_timeout_s: float = 300.0):
        self.argv = list(argv)
        self.name = name
        self.env = dict(env) if env is not None else None
        self.cpu_affinity = list(cpu_affinity) if cpu_affinity else None
        self.ready_timeout_s = ready_timeout_s
        self.proc: Optional[subprocess.Popen] = None
        self.state = "warming"
        self.last_health: dict = {}
        self.last_health_ts = 0.0
        self.last_metrics: Optional[dict] = None
        self.last_metrics_ts = 0.0
        self.last_identity: Optional[dict] = None
        self.routed_since_poll = 0
        self.restarts = 0
        self.deliberate_stop = False
        self.cordoned = False
        self._wire = 0
        self._pending: Dict[str, FleetPending] = {}
        self._lock = _lw.make_lock("fleet.replica")
        self._reader: Optional[threading.Thread] = None
        # outbound lines drain on a dedicated writer thread: a full
        # stdin pipe (slow replica) must never block the router's
        # submit path — head-of-line blocking there throttles the whole
        # fleet to the slowest replica's pipe
        self._outq: Optional[_queue_mod.Queue] = None
        self._writer: Optional[threading.Thread] = None

    on_terminal: Callable = None    # set by the router
    on_death: Callable = None       # set by the router (lost fps)

    # -- lifecycle -----------------------------------------------------------
    def spawn(self):
        if self.proc is not None and self.proc.poll() is None:
            raise RuntimeError(f"replica {self.name}: already running")
        preexec = None
        if self.cpu_affinity and hasattr(os, "sched_setaffinity"):
            cores = set(self.cpu_affinity)

            def preexec():          # noqa: F811 — child-side pin
                os.sched_setaffinity(0, cores)
        self.state = "warming"
        self.deliberate_stop = False
        self.proc = subprocess.Popen(
            self.argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, env=self.env, preexec_fn=preexec)
        self._outq = _queue_mod.Queue()
        self._reader = threading.Thread(
            target=self._read_loop, name=f"pt-fleet-read-{self.name}",
            daemon=True)
        self._reader.start()
        self._writer = threading.Thread(
            target=self._write_loop, args=(self.proc, self._outq),
            name=f"pt-fleet-write-{self.name}", daemon=True)
        self._writer.start()
        obs.emit_event("fleet", event="replica_spawn", replica=self.name,
                       pid=self.proc.pid)
        return self

    def wait_ready(self, timeout_s: Optional[float] = None) -> bool:
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.ready_timeout_s)
        while time.monotonic() < deadline:
            if self.state == "ready":
                return True
            if self.state == DEAD:
                return False
            time.sleep(0.02)
        return False

    @property
    def alive(self) -> bool:
        return (self.proc is not None and self.proc.poll() is None
                and self.state not in (DEAD, "stopped"))

    # -- wire ----------------------------------------------------------------
    def _send(self, obj: dict) -> bool:
        """Enqueue one line for the writer thread; never blocks on the
        pipe.  False only when the replica is already known-dead (a
        line enqueued to a dying replica is recovered by the reader's
        EOF -> on_death failover, not here)."""
        proc, outq = self.proc, self._outq
        if proc is None or outq is None or proc.poll() is not None:
            return False
        outq.put(json.dumps(obj, default=repr))
        return True

    def _write_loop(self, proc, outq):
        try:
            while True:
                line = outq.get()
                if line is None:
                    return
                proc.stdin.write(line + "\n")
                proc.stdin.flush()
        except (BrokenPipeError, ValueError, OSError):
            return          # replica gone: reader EOF owns the cleanup

    def _read_loop(self):
        proc = self.proc
        try:
            for raw in proc.stdout:
                try:
                    msg = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                self._on_message(msg)
        except (ValueError, OSError):
            pass
        # EOF: drain or death — the exit status decides, the router's
        # monitor relaunches if it was a signal
        rc = proc.wait()
        if self._outq is not None:
            self._outq.put(None)        # retire this spawn's writer
        self.state = "stopped" if (rc == 0 or self.deliberate_stop) else DEAD
        lost = self._take_pending()
        if lost:
            logger.warning("fleet: replica %s exited rc=%s with %d "
                           "requests in flight", self.name, rc, len(lost))
        if self.on_death is not None:
            self.on_death(self, rc, lost)

    def _take_pending(self) -> List[FleetPending]:
        with self._lock:
            lost = list(self._pending.values())
            self._pending.clear()
        return lost

    def _on_message(self, msg: dict):
        if "health" in msg and isinstance(msg.get("health"), dict):
            self.last_health = msg["health"]
            self.last_health_ts = time.monotonic()
            self.routed_since_poll = 0
            if isinstance(msg.get("metrics"), dict):
                # opt-in piggyback answered by serve's health handler
                self.last_metrics = msg["metrics"]
                self.last_metrics_ts = self.last_health_ts
                self.last_identity = msg.get("identity")
            st = msg["health"].get("state")
            if st and self.state not in (DEAD,):
                self.state = st
            return
        if msg.get("event") == "state":
            st = msg.get("state")
            if st and self.state not in (DEAD,):
                self.state = st
            return
        if "id" not in msg or msg.get("event") is not None:
            return
        with self._lock:
            fp = self._pending.pop(msg["id"], None)
        if fp is None:
            return
        if "error" in msg:
            err_cls = _WIRE_ERRORS.get(msg["error"])
            message = msg.get("message", msg["error"])
            if err_cls is not None:
                err = err_cls(message)
                if isinstance(err, _faults.ServerClosed):
                    # admitted-then-aborted: internal loss -> failover
                    err = ReplicaGone(message)
            elif msg["error"] == "BadRequest":
                err = ValueError(message)
            else:
                err = _ModelError(f"{msg['error']}: {message}")
            self.on_terminal(fp, error=err)
        else:
            outs = msg.get("outputs") or []
            self.on_terminal(fp, outputs=outs,
                             dispatch_ms=msg.get("dispatch_ms"))

    # -- router surface ------------------------------------------------------
    @property
    def local_backlog(self) -> int:
        """Requests accepted by :meth:`submit` but still waiting in the
        writer queue — part of the routing score (a fresh health poll
        resets routed_since_poll, but these are not on the wire yet)."""
        outq = self._outq
        return outq.qsize() if outq is not None else 0

    def poll_health(self, metrics: bool = False):
        msg = {"cmd": "health"}
        if metrics:
            msg["metrics"] = True
        if not self._send(msg):
            return
        # answer arrives asynchronously on the reader thread

    def queue_depth(self) -> int:
        models = (self.last_health or {}).get("models", {})
        return sum(int(m.get("queue_depth", 0)) for m in models.values())

    def breaker_open(self, model: Optional[str]) -> bool:
        models = (self.last_health or {}).get("models", {})
        if model is not None:
            return models.get(model, {}).get("breaker") == "open"
        return any(m.get("breaker") == "open" for m in models.values())

    def submit(self, fp: FleetPending):
        self._wire += 1
        wire_id = f"{self.name}-{self._wire}"
        msg = {"id": wire_id, "feeds": _wire_feeds(fp.feeds)}
        if fp.model is not None:
            msg["model"] = fp.model
        if fp.deadline_ms != -1.0:      # -1 = replica default, omit
            msg["deadline_ms"] = fp.deadline_ms
        if fp.ctx is not None:          # observing caller: propagate
            msg["ctx"] = fp.ctx
        with self._lock:
            self._pending[wire_id] = fp
        if not self._send(msg):
            with self._lock:
                self._pending.pop(wire_id, None)
            raise ReplicaGone(f"replica {self.name}: not accepting input")
        self.routed_since_poll += 1

    def begin_drain(self):
        """Graceful: SIGTERM — the serve CLI stops admission, completes
        every admitted request, exits 0."""
        self.deliberate_stop = True
        proc = self.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except (ProcessLookupError, OSError):
                pass

    def stop(self, drain: bool = True, timeout_s: float = 60.0):
        self.begin_drain()
        proc = self.proc
        if proc is None:
            return
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            logger.warning("fleet: replica %s ignored SIGTERM for %.0fs; "
                           "killing", self.name, timeout_s)
            proc.kill()
            proc.wait(timeout=10)

    def kill(self):
        """SIGKILL, the chaos case: no handler runs, requests in flight
        are lost at the replica and failed over by the router."""
        proc = self.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except (ProcessLookupError, OSError):
                pass


def _wire_feeds(feeds) -> dict:
    """JSON form of one request's feeds (arrays -> nested lists)."""
    out = {}
    for k, v in feeds.items():
        out[k] = v.tolist() if hasattr(v, "tolist") else v
    return out


# ---------------------------------------------------------------------------
# autoscaling policy
# ---------------------------------------------------------------------------
class AutoscalePolicy:
    """Pure decision function over a fleet snapshot — separated from the
    router so tests drive the matrix without threads or clocks.

    Scale-out: the queue-wait share of the rolling p99 exceeds
    ``wait_share_threshold`` (and p99 itself exceeds ``p99_floor_ms`` so
    an idle-but-jittery fleet never scales on noise).  Queue wait is
    ``total - dispatch`` per completed request — the live form of the
    PR 10 ``serving_budget`` decomposition: when most of the p99 is
    waiting, capacity (not the model) is the bottleneck and a replica
    helps; when dispatch dominates, it won't.

    Scale-in: sustained idle — total queue depth zero AND per-replica
    served rate under ``idle_rate_per_replica`` for at least
    ``idle_for_s`` — drains one replica.

    ``cooldown_s`` spaces decisions so a scale-out's effect is observed
    before the next one; ``min_replicas``/``max_replicas`` bound the
    fleet."""

    def __init__(self, *, wait_share_threshold: float = 0.5,
                 p99_floor_ms: float = 20.0,
                 idle_rate_per_replica: float = 0.5,
                 idle_for_s: float = 10.0,
                 min_replicas: int = 1, max_replicas: int = 8,
                 cooldown_s: float = 5.0):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}")
        self.wait_share_threshold = float(wait_share_threshold)
        self.p99_floor_ms = float(p99_floor_ms)
        self.idle_rate_per_replica = float(idle_rate_per_replica)
        self.idle_for_s = float(idle_for_s)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.cooldown_s = float(cooldown_s)

    def decide(self, snap: dict) -> Optional[dict]:
        """``snap``: replicas (live process count — the resource the
        min/max bounds cap), routable_replicas, p99_ms, wait_share_p99,
        queue_depth, served_per_s, idle_s, since_last_decision_s.
        Returns {"action": "scale_out"|"scale_in", "reason": ...} or
        None."""
        n = int(snap.get("replicas", 0))
        if snap.get("since_last_decision_s", 1e9) < self.cooldown_s:
            return None
        p99 = snap.get("p99_ms")
        share = snap.get("wait_share_p99")
        if (n < self.max_replicas and p99 is not None
                and share is not None and p99 >= self.p99_floor_ms
                and share >= self.wait_share_threshold):
            return {"action": "scale_out",
                    "reason": f"queue-wait share of p99 "
                              f"{share:.2f} >= {self.wait_share_threshold} "
                              f"(p99 {p99:.1f}ms)",
                    "p99_ms": round(p99, 3),
                    "wait_share_p99": round(share, 4)}
        rate = snap.get("served_per_s", 0.0) or 0.0
        if (n > self.min_replicas
                and int(snap.get("queue_depth", 0)) == 0
                and rate < self.idle_rate_per_replica * n
                and snap.get("idle_s", 0.0) >= self.idle_for_s):
            return {"action": "scale_in",
                    "reason": f"idle {snap.get('idle_s', 0.0):.1f}s "
                              f"(rate {rate:.2f}/s over {n} replicas)",
                    "served_per_s": round(rate, 3)}
        return None


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------
class FleetRouter:
    """Queue-depth-aware load balancer + replica lifecycle manager.

    ::

        router = FleetRouter(replica_factory=make_replica, replicas=2)
        router.start()
        out = router.submit(feeds).result(timeout=5)
        router.shutdown()

    ``replica_factory(index) -> replica`` builds members
    (:class:`LocalReplica` or :class:`ProcessReplica`); ``autoscale``
    (an :class:`AutoscalePolicy`) enables the scaling thread.  The
    router exposes the server surface (``submit``/``health``/``state``)
    so :class:`~paddle_tpu.serving.http.HttpFront` fronts a fleet the
    same way it fronts one server."""

    def __init__(self, replica_factory: Callable[[int], object],
                 replicas: int = 1, *,
                 autoscale: Optional[AutoscalePolicy] = None,
                 poll_interval_s: float = 0.2,
                 health_stale_s: float = 5.0,
                 max_restarts: int = 3,
                 restart_backoff_base_s: float = 0.5,
                 default_deadline_ms: Optional[float] = -1.0,
                 failover_attempts: Optional[int] = None,
                 backlog_limit: Optional[int] = None,
                 failover_wait_s: float = 10.0):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.replica_factory = replica_factory
        self.initial_replicas = int(replicas)
        self.policy = autoscale
        self.poll_interval_s = float(poll_interval_s)
        self.health_stale_s = float(health_stale_s)
        self.max_restarts = int(max_restarts)
        self.restart_backoff_base_s = float(restart_backoff_base_s)
        self.default_deadline_ms = default_deadline_ms
        self.failover_attempts = failover_attempts
        # how long a failover may wait for SOME replica to become
        # routable again before failing the admitted request: a dying
        # replica and a momentarily-stale survivor often overlap (the
        # health poll that would re-admit it is in flight), and an
        # admitted request must not lose that race
        self.failover_wait_s = float(failover_wait_s)
        # fleet-rim admission control: when every routable replica's
        # live score (queue depth + in-flight since poll) is at or past
        # this bound, reject with Overloaded HERE — the replica-side
        # shed would first pay wire+parse on a core that should be
        # serving admitted work (measured: replica-side shed under 2x
        # overload cost ~40% of fleet throughput)
        self.backlog_limit = backlog_limit
        self.replicas: List[object] = []
        self._next_index = 0
        self._lock = _lw.make_rlock("fleet.router")
        self._state = "warming"
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._sups: Dict[str, Supervisor] = {}
        self._routable_before: Dict[str, bool] = {}
        # rolling latency window for the autoscaler: (total_ms,
        # dispatch_ms) of completed-ok routed requests
        self._window = collections.deque(maxlen=512)
        self._served = 0
        self._served_window_t0 = time.monotonic()
        self._served_window_n = 0
        self._last_decision_ts = 0.0
        self._idle_since: Optional[float] = None
        self._req_counter = 0
        # observe resolved ONCE at construction (the PR 10 discipline):
        # off -> no ctx is captured at admission and no ctx field ever
        # reaches a replica's stdio wire
        self._observe = obs.enabled()

    # -- lifecycle -----------------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    def start(self, wait_ready: bool = True,
              ready_timeout_s: float = 600.0) -> "FleetRouter":
        for _ in range(self.initial_replicas):
            self._add_replica(wait_ready=False)
        if wait_ready:
            deadline = time.monotonic() + ready_timeout_s
            while time.monotonic() < deadline:
                self._poll_all()
                if self._routable():
                    break
                time.sleep(0.05)
            else:
                raise RuntimeError(
                    f"fleet: no replica became ready within "
                    f"{ready_timeout_s}s")
        self._state = "ready"
        t = threading.Thread(target=self._poll_loop, name="pt-fleet-poll",
                             daemon=True)
        t.start()
        self._threads.append(t)
        if self.policy is not None:
            t2 = threading.Thread(target=self._autoscale_loop,
                                  name="pt-fleet-autoscale", daemon=True)
            t2.start()
            self._threads.append(t2)
        return self

    def _new_replica(self):
        with self._lock:
            idx = self._next_index
            self._next_index += 1
        rep = self.replica_factory(idx)
        rep.on_terminal = self._on_terminal
        if hasattr(rep, "on_death"):
            rep.on_death = self._on_death
        return rep

    def _add_replica(self, wait_ready: bool = True):
        rep = self._new_replica()
        if hasattr(rep, "spawn"):
            rep.spawn()
        with self._lock:
            self.replicas.append(rep)
            self._sups[rep.name] = Supervisor(
                max_restarts=self.max_restarts,
                backoff_base_s=self.restart_backoff_base_s,
                jitter=0.1, seed=len(self._sups))
        if wait_ready and hasattr(rep, "wait_ready"):
            rep.wait_ready()
        self._set_replica_gauges()
        return rep

    def _set_replica_gauges(self):
        counts = {st: 0 for st in ("warming", "ready", "draining",
                                   "stopped", DEAD)}
        with self._lock:
            for r in self.replicas:
                counts[r.state] = counts.get(r.state, 0) + 1
        for st, n in counts.items():   # zeros too: relaunch clears "dead"
            obs.set_gauge("fleet/replicas", n, label=st)

    def begin_drain(self):
        """Close fleet admission and drain every replica gracefully."""
        if self._state in ("draining", "stopped"):
            return
        self._state = "draining"
        obs.emit_event("fleet", event="state", state="draining")
        with self._lock:
            reps = list(self.replicas)
        for r in reps:
            r.begin_drain()

    def shutdown(self, drain: bool = True, timeout_s: float = 120.0):
        self.begin_drain()
        self._stop.set()
        with self._lock:
            reps = list(self.replicas)
        for r in reps:
            r.stop(drain=drain)
        for t in self._threads:
            t.join(timeout=10)
        self._state = "stopped"
        obs.emit_event("fleet", event="state", state="stopped")

    # -- health / routing ----------------------------------------------------
    def _fresh(self, rep) -> bool:
        return (time.monotonic() - getattr(rep, "last_health_ts", 0.0)
                < self.health_stale_s)

    def _is_routable(self, rep, model: Optional[str] = None) -> bool:
        return (rep.alive and rep.state == "ready"
                and not getattr(rep, "cordoned", False)
                and self._fresh(rep)
                and not rep.breaker_open(model))

    def cordon(self, name: str, cordoned: bool = True):
        """Administratively remove (or re-add) a replica from the
        routable set without touching its process — maintenance,
        canarying, or A/B capacity measurement.  Admitted work keeps
        completing; only NEW routing skips it."""
        with self._lock:
            reps = [r for r in self.replicas if r.name == name]
        if not reps:
            raise ValueError(f"fleet: no replica named {name!r}")
        reps[0].cordoned = bool(cordoned)
        obs.emit_event("fleet", event="cordon" if cordoned
                       else "uncordon", replica=name)

    def _routable(self, model: Optional[str] = None) -> List[object]:
        with self._lock:
            reps = list(self.replicas)
        return [r for r in reps if self._is_routable(r, model)]

    def _poll_all(self):
        with self._lock:
            reps = list(self.replicas)
        for r in reps:
            try:
                r.poll_health()
            except Exception:
                logger.exception("fleet: health poll of %s failed", r.name)
        # eviction accounting: routable -> unroutable transitions.  A
        # replica seen for the first time (fresh spawn, still warming)
        # just records its state — it was never routable, so counting
        # it as an eviction would poison fleet/evictions at every cold
        # start and scale-out
        for r in reps:
            now_routable = self._is_routable(r)
            was = self._routable_before.get(r.name)
            if was is None:
                self._routable_before[r.name] = now_routable
                continue
            if was and not now_routable:
                obs.inc_counter("fleet/evictions")
                obs.emit_event(
                    "fleet", event="evict", replica=r.name,
                    state=r.state,
                    breaker_open=bool(r.breaker_open(None)),
                    stale=not self._fresh(r))
            elif not was and now_routable:
                obs.emit_event("fleet", event="readd", replica=r.name)
            self._routable_before[r.name] = now_routable
        self._set_replica_gauges()

    def _poll_loop(self):
        while not self._stop.wait(self.poll_interval_s):
            self._poll_all()
            self._reap_stopped()

    def _reap_stopped(self):
        """Drop replicas that finished a deliberate drain (scale-in or
        fleet drain)."""
        with self._lock:
            gone = [r for r in self.replicas
                    if r.state == "stopped" and not r.alive]
            for r in gone:
                self.replicas.remove(r)
                self._routable_before.pop(r.name, None)

    def health(self) -> dict:
        with self._lock:
            reps = list(self.replicas)
        out_reps = {}
        depth = 0
        for r in reps:
            d = r.queue_depth()
            depth += d
            out_reps[r.name] = {
                "state": r.state, "alive": r.alive, "queue_depth": d,
                "routable": self._is_routable(r),
                "restarts": getattr(r, "restarts", 0),
            }
        ready = self._state == "ready" and any(
            v["routable"] for v in out_reps.values())
        return {"state": self._state, "ready": ready,
                "queue_depth": depth, "replicas": out_reps}

    def metrics_snapshots(self, timeout_s: float = 2.0) -> Dict[str, dict]:
        """One metrics-piggybacked health poll of every replica, gathered:
        ``{replica_name: {"metrics": snapshot, "identity": {...}|None}}``.
        Process replicas answer asynchronously on their reader threads,
        so this waits (bounded) for replies newer than the ask; members
        that don't answer in time are simply absent — the fleet
        collector labels what it got, it never blocks on a wedged
        replica."""
        with self._lock:
            reps = list(self.replicas)
        t_ask = time.monotonic()
        for r in reps:
            try:
                r.poll_health(metrics=True)
            except Exception:
                logger.exception("fleet: metrics poll of %s failed",
                                 r.name)
        deadline = t_ask + timeout_s
        while time.monotonic() < deadline:
            if all(getattr(r, "last_metrics_ts", 0.0) >= t_ask
                   or not r.alive for r in reps):
                break
            time.sleep(0.02)
        out = {}
        for r in reps:
            if getattr(r, "last_metrics", None) is not None \
                    and getattr(r, "last_metrics_ts", 0.0) >= t_ask:
                out[r.name] = {"metrics": r.last_metrics,
                               "identity": getattr(r, "last_identity",
                                                   None)}
        return out

    # -- submission ----------------------------------------------------------
    def submit(self, feeds, model: Optional[str] = None,
               deadline_ms: Optional[float] = -1.0,
               req_id=None) -> FleetPending:
        """Route one request to the least-loaded ready replica.  Raises
        the typed rejection when the fleet cannot admit it."""
        if self._state != "ready":
            raise _faults.ServerClosed(
                f"fleet is {self._state}; admission closed")
        if deadline_ms == -1.0:
            deadline_ms = self.default_deadline_ms
        if req_id is None:
            with self._lock:
                self._req_counter += 1
                req_id = self._req_counter
        fp = FleetPending(
            req_id, model, feeds, deadline_ms,
            ctx=obs.tracing.inject() if self._observe else None)
        obs.inc_counter("fleet/requests")
        self._route(fp, exclude=())
        return fp

    def infer(self, feeds, model: Optional[str] = None,
              deadline_ms: Optional[float] = -1.0,
              timeout: Optional[float] = None):
        return self.submit(feeds, model=model,
                           deadline_ms=deadline_ms).result(timeout)

    def _score(self, rep) -> float:
        # live signal: last polled queue depth, plus what we routed at
        # it since that poll answered, plus lines not yet on the wire
        return (rep.queue_depth() + rep.routed_since_poll
                + getattr(rep, "local_backlog", 0))

    def _route(self, fp: FleetPending, exclude: Sequence[str],
               admitted: bool = False):
        """Offer ``fp`` to routable replicas, least-loaded first; raises
        the last typed rejection when every candidate refuses.
        ``admitted``: failover resubmission of an already-admitted
        request — exempt from the fleet-rim backlog shed."""
        candidates = [r for r in self._routable(fp.model)
                      if r.name not in exclude]
        candidates.sort(key=self._score)
        if (not admitted and self.backlog_limit is not None and candidates
                and self._score(candidates[0]) >= self.backlog_limit):
            obs.inc_counter("fleet/router_shed")
            obs.emit_event("fleet", event="router_shed", request=fp.id,
                           best_score=self._score(candidates[0]))
            raise _faults.Overloaded(
                f"fleet saturated: every ready replica is at the "
                f"backlog limit ({self.backlog_limit})")
        limit = (self.failover_attempts if self.failover_attempts
                 is not None else max(2, len(candidates)))
        last_exc: Optional[BaseException] = None
        for rep in candidates[:limit]:
            fp.attempts += 1
            try:
                rep.submit(fp)
                return
            except (ReplicaGone, _faults.ServerClosed,
                    _faults.ModelUnavailable, _faults.Overloaded) as e:
                last_exc = e
                continue
        if last_exc is not None and not isinstance(last_exc, ReplicaGone):
            raise last_exc
        raise _faults.ModelUnavailable(
            "fleet: no ready replica available"
            + (f" (excluded: {sorted(exclude)})" if exclude else ""))

    # -- completion / failover ----------------------------------------------
    def _on_terminal(self, fp: FleetPending, outputs=None, error=None,
                     dispatch_ms=None):
        if error is not None and isinstance(error, ReplicaGone):
            self._failover(fp, error)
            return
        if error is not None:
            fp._complete(error=error)
            return
        total_ms = (time.monotonic() - fp.t_admit) * 1e3
        with self._lock:
            self._window.append((total_ms, dispatch_ms))
            self._served += 1
            self._served_window_n += 1
        fp._complete(outputs=outputs, dispatch_ms=dispatch_ms)

    def _on_death(self, rep, rc, lost: List[FleetPending]):
        """A replica process exited.  Fail admitted requests over to
        survivors, then relaunch through the supervisor's bounded-restart
        gate when the death was not deliberate."""
        retry_until = time.monotonic() + self.failover_wait_s
        for fp in lost:
            self._failover(fp, ReplicaGone(
                f"replica {rep.name} exited rc={rc}"),
                retry_until=retry_until)
        if rep.state != DEAD or self._stop.is_set():
            return
        obs.emit_event("fleet", event="replica_death", replica=rep.name,
                       rc=rc)
        sup = self._sups.get(rep.name)
        if sup is None or not sup.relaunch_gate(
                f"fleet replica {rep.name}", f"exit status {rc}"):
            logger.error("fleet: replica %s exhausted its restart budget; "
                         "leaving it dead", rep.name)
            obs.emit_event("fleet", event="replica_abandoned",
                           replica=rep.name)
            self._set_replica_gauges()
            return
        rep.restarts += 1
        obs.inc_counter("fleet/relaunches")
        obs.emit_event("fleet", event="relaunch", replica=rep.name,
                       attempt=rep.restarts)
        try:
            rep.spawn()
        except Exception:
            logger.exception("fleet: relaunch of %s failed", rep.name)
        self._set_replica_gauges()

    def _failover(self, fp: FleetPending, cause: BaseException,
                  retry_until: Optional[float] = None):
        """Resubmit an admitted-but-lost request to a surviving replica
        — the fleet-wide zero-drop path.  No-candidate windows are
        WAITED OUT up to ``failover_wait_s``: right after a death the
        survivor's health is often one poll away from fresh, and an
        admitted request must not lose that race."""
        if fp.done():
            return
        if self._state != "ready":
            fp._complete(error=_faults.ServerClosed(
                f"fleet draining; request lost by a dying replica "
                f"({cause})"))
            return
        obs.inc_counter("fleet/failovers")
        obs.emit_event("fleet", event="failover", request=fp.id,
                       cause=str(cause), attempts=fp.attempts)
        if retry_until is None:
            retry_until = time.monotonic() + self.failover_wait_s
        while True:
            try:
                self._route(fp, exclude=(), admitted=True)
                return
            except (ReplicaGone, _faults.ModelUnavailable,
                    _faults.Overloaded, _faults.ServerClosed) as e:
                if self._state != "ready" \
                        or time.monotonic() >= retry_until:
                    fp._complete(
                        error=e if not isinstance(e, ReplicaGone)
                        else _faults.ModelUnavailable(
                            f"fleet: request lost and no surviving "
                            f"replica ({cause})"))
                    return
                time.sleep(0.05)        # poller refreshes health
            except BaseException as e:  # unexpected: surface typed
                fp._complete(error=e)
                return

    # -- autoscaling ---------------------------------------------------------
    def autoscale_snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            window = list(self._window)
            n_window = self._served_window_n
            t0 = self._served_window_t0
            self._served_window_n = 0
            self._served_window_t0 = now
        p99 = wait_share = None
        if window:
            totals = sorted(t for t, _ in window)
            p99 = totals[min(len(totals) - 1, int(len(totals) * 0.99))]
            waits = sorted(
                max(0.0, t - (d or 0.0)) for t, d in window)
            wait_p99 = waits[min(len(waits) - 1, int(len(waits) * 0.99))]
            wait_share = (wait_p99 / p99) if p99 > 0 else 0.0
        h = self.health()
        depth = h["queue_depth"]
        rate = n_window / max(1e-6, now - t0)
        # "replicas" is the RESOURCE count (every live process, routable
        # or not): the policy's min/max bounds cap processes, and a
        # transiently-evicted replica still holds its core — counting
        # only routables would let scale-out overshoot max_replicas
        n_live = len(h["replicas"])
        # the idle clock must mirror the policy's own scale-in rate
        # threshold, or fleets with a higher idle_rate_per_replica than
        # this clock's floor never accumulate idle_s and never scale in
        idle_rate = (self.policy.idle_rate_per_replica
                     if self.policy is not None else 1.0)
        if depth == 0 and rate < idle_rate * max(1, n_live):
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None
        return {
            "replicas": n_live,
            "routable_replicas": sum(1 for v in h["replicas"].values()
                                     if v["routable"]),
            "p99_ms": p99, "wait_share_p99": wait_share,
            "queue_depth": depth, "served_per_s": rate,
            "idle_s": 0.0 if self._idle_since is None
            else now - self._idle_since,
            "since_last_decision_s": now - self._last_decision_ts,
        }

    def _autoscale_loop(self):
        interval = max(self.poll_interval_s, 0.5)
        while not self._stop.wait(interval):
            try:
                snap = self.autoscale_snapshot()
                decision = self.policy.decide(snap)
                if decision is not None:
                    self.apply_decision(decision, snap)
            except Exception:
                logger.exception("fleet: autoscale tick failed")

    def apply_decision(self, decision: dict, snap: dict):
        """Execute one policy decision (public so tests and the bench
        drive it without the timer thread)."""
        self._last_decision_ts = time.monotonic()
        sp = obs.tracing.start_span(
            "fleet/autoscale", parent=obs.tracing.ROOT,
            action=decision["action"], replicas=snap.get("replicas"))
        sp.event("decision", **decision)
        obs.emit_event("fleet", event=decision["action"],
                       reason=decision.get("reason"), **{
                           k: v for k, v in snap.items()
                           if isinstance(v, (int, float)) or v is None})
        try:
            if decision["action"] == "scale_out":
                rep = self._add_replica(wait_ready=True)
                obs.inc_counter("fleet/scale_outs")
                sp.end(status="ok", replica=rep.name)
            elif decision["action"] == "scale_in":
                victim = self._pick_scale_in_victim()
                if victim is None:
                    sp.end(status="no_victim")
                    return
                victim.begin_drain()     # reaped once it stops
                obs.inc_counter("fleet/scale_ins")
                sp.end(status="ok", replica=victim.name)
            else:
                sp.end(status="unknown_action")
        except Exception as e:
            sp.end(status=type(e).__name__)
            raise

    def _pick_scale_in_victim(self):
        routable = self._routable()
        if len(routable) <= (self.policy.min_replicas
                             if self.policy else 1):
            return None
        return min(routable, key=self._score)


# ---------------------------------------------------------------------------
# CLI: python -m paddle_tpu fleet
# ---------------------------------------------------------------------------
def fleet_main(argv=None) -> int:
    """``python -m paddle_tpu fleet --model DIR --replicas N --http PORT``
    — N supervised ``serve`` replicas behind the queue-depth router and
    the HTTP front, with optional autoscaling.  SIGTERM/SIGINT drains the
    whole fleet gracefully and exits 0."""
    import argparse

    from .http import HttpFront

    ap = argparse.ArgumentParser(
        prog="paddle_tpu fleet",
        description="horizontally scaled serving: N `paddle_tpu serve` "
                    "replica processes behind a queue-depth-aware router "
                    "and an HTTP/1.1 front, with supervisor-backed "
                    "relaunch and optional metric-driven autoscaling.")
    ap.add_argument("--model", action="append", required=True,
                    metavar="[NAME=]DIR",
                    help="artifact directory each replica serves "
                         "(repeatable, forwarded to `serve`)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="initial fleet size (default 2)")
    ap.add_argument("--http", type=int, default=0, metavar="PORT",
                    help="HTTP front port (default 0 = ephemeral, "
                         "printed on the ready line)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--token", action="append", metavar="TOKEN[=MODEL]",
                    help="auth token, optionally bound to one model "
                         "(repeatable; omit for an open front)")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--queue", type=int, default=None)
    ap.add_argument("--poll-interval-s", type=float, default=0.2,
                    help="router health-poll period (default 0.2)")
    ap.add_argument("--backlog-limit", type=int, default=None,
                    help="fleet-rim admission bound: reject Overloaded "
                         "at the router once every ready replica's "
                         "live backlog reaches this (default: off; "
                         "replica-side shedding still applies)")
    ap.add_argument("--max-restarts", type=int, default=3,
                    help="bounded relaunches per replica (default 3)")
    ap.add_argument("--autoscale", action="store_true",
                    help="enable the replica autoscaler")
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=8)
    ap.add_argument("--wait-share-threshold", type=float, default=0.5,
                    help="queue-wait share of p99 that triggers "
                         "scale-out (default 0.5)")
    ap.add_argument("--idle-for-s", type=float, default=30.0,
                    help="sustained idle before scale-in (default 30)")
    ap.add_argument("--cooldown-s", type=float, default=10.0)
    args = ap.parse_args(argv)

    obs.set_process_identity("fleet")
    argv_tpl = serve_argv(args.model, max_batch=args.max_batch,
                          max_wait_ms=args.max_wait_ms,
                          deadline_ms=args.deadline_ms, queue=args.queue)

    def factory(i):
        # --replica-index stamps the child's JSONL identity line, so a
        # multi-file trace/stats merge labels its events "serve:i"
        return ProcessReplica(argv_tpl + ["--replica-index", str(i)],
                              name=f"replica{i}")

    policy = None
    if args.autoscale:
        policy = AutoscalePolicy(
            wait_share_threshold=args.wait_share_threshold,
            idle_for_s=args.idle_for_s, cooldown_s=args.cooldown_s,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas)

    router = FleetRouter(factory, replicas=args.replicas,
                         autoscale=policy,
                         poll_interval_s=args.poll_interval_s,
                         max_restarts=args.max_restarts,
                         backlog_limit=args.backlog_limit)

    drain = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: drain.set())

    def emit(obj):
        sys.stdout.write(json.dumps(obj) + "\n")
        sys.stdout.flush()

    emit({"event": "state", "state": "warming",
          "replicas": args.replicas})
    router.start()
    tokens = None
    if args.token:
        tokens = {}
        for t in args.token:
            tok, sep, model = t.partition("=")
            tokens[tok] = model if sep else None
    front = HttpFront(router, host=args.host, port=args.http,
                      tokens=tokens).start()
    host, port = front.address
    emit({"event": "state", "state": "ready", "host": host, "port": port,
          "replicas": args.replicas})
    while not drain.is_set():
        drain.wait(0.1)
    emit({"event": "state", "state": "draining"})
    # admission closes fleet-wide first: late HTTP requests get typed
    # 503 + Connection: close while admitted work completes
    router.begin_drain()
    router.shutdown(drain=True)
    front.stop()
    emit({"event": "state", "state": "stopped"})
    emit({"event": "stopped", "health": router.health()})
    return 0
