"""Servable model handles: one calling convention over every deploy path.

A :class:`Model` is (name, infer fn, input specs): the fn takes ONE
stacked feed dict ``{name: array[B, ...]}`` and returns the output list
``[array[B, ...], ...]`` — exactly the row-wise batch contract the
server's batcher needs to coalesce independent requests.  Three
constructors cover the substrate the repo already ships:

* :meth:`Model.from_artifact` — an ``export_compiled_model`` directory
  (serialized StableHLO + manifest, the deploy ABI).  The deserialized
  ``Exported.call`` is wrapped in ``jax.jit`` so each batch bucket
  compiles once and then replays — the symbolic-batch artifact serves
  every bucket from one file.
* :meth:`Model.from_compiled` — an AOT :class:`CompiledProgram` from
  ``Executor.compile()``: the pre-compiled variant serves its own batch
  size with zero compiles; other buckets route through the same
  executor's content-fingerprinted cache (and its persistent layer, so
  a warmed cache dir makes every bucket a zero-compile start).
* :meth:`Model.from_program` — a live (executor, program, fetch_list,
  scope), for in-process serving and tests.

``example`` (a single-example feed dict, no batch axis) drives server
warmup; artifact manifests synthesize one automatically from their
declared input shapes.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Model"]


def _example_from_specs(specs: Dict[str, dict]) -> Optional[Dict[str, np.ndarray]]:
    """Single-example feeds from manifest input specs ({name: {shape,
    dtype}}); None when any non-batch dim is symbolic/unknown."""
    out: Dict[str, np.ndarray] = {}
    for name, spec in specs.items():
        shape = list(spec["shape"])
        if shape and shape[0] in (None, -1):
            shape = shape[1:]
        if any(d is None or int(d) < 0 for d in shape):
            return None
        dtype = np.dtype(spec["dtype"])
        if dtype.kind in "iu":
            out[name] = np.zeros(tuple(int(d) for d in shape), dtype)
        else:
            out[name] = np.full(tuple(int(d) for d in shape), 0.5, dtype)
    return out


class Model:
    """One servable tenant: a batched infer fn plus its calling
    convention.  ``fn({name: [B, ...]}) -> [out[B, ...], ...]`` must be
    row-wise (row i of every output depends only on row i of the feeds)
    — that is what makes coalescing and pad-row slicing correct."""

    def __init__(self, name: str, fn: Callable, *,
                 input_specs: Optional[Dict[str, dict]] = None,
                 output_names: Optional[Sequence[str]] = None,
                 example: Optional[Dict[str, np.ndarray]] = None):
        if not name:
            raise ValueError("Model: name must be non-empty")
        self.name = str(name)
        self._fn = fn
        self.input_specs = dict(input_specs or {})
        self.output_names = list(output_names or [])
        if example is None and self.input_specs:
            example = _example_from_specs(self.input_specs)
        self.example = example

    def __call__(self, feeds_stacked: Dict[str, np.ndarray]) -> List:
        return self._fn(feeds_stacked)

    def coerce_feeds(self, feeds: Dict[str, object]) -> Dict[str, np.ndarray]:
        """One request's feeds (wire form: nested lists/arrays, no batch
        axis) -> arrays with declared dtypes.

        When the model carries input specs (artifact manifests do), an
        unknown, MISSING, or mis-shaped input raises here — at the
        ADMISSION rim, as a per-request rejection.  Letting it through
        would surface at dispatch as a fatal batch error and feed the
        model's circuit breaker: one malformed client could open the
        breaker and take the tenant down for everyone."""
        out: Dict[str, np.ndarray] = {}
        for k, v in feeds.items():
            spec = self.input_specs.get(k)
            if self.input_specs and spec is None:
                raise ValueError(
                    f"model {self.name!r} has no input {k!r} "
                    f"(inputs: {sorted(self.input_specs)})")
            dtype = np.dtype(spec["dtype"]) if spec else None
            arr = np.asarray(v, dtype=dtype)
            if spec is not None:
                shape = list(spec["shape"])
                if shape and (shape[0] is None or int(shape[0]) < 0):
                    shape = shape[1:]        # per-example: drop batch dim
                want = tuple(None if d is None or int(d) < 0 else int(d)
                             for d in shape)
                ok = len(arr.shape) == len(want) and all(
                    w is None or a == w for a, w in zip(arr.shape, want))
                if not ok:
                    raise ValueError(
                        f"model {self.name!r} input {k!r}: example shape "
                        f"{arr.shape} does not match declared {want}")
            out[k] = arr
        if self.input_specs:
            missing = sorted(set(self.input_specs) - set(out))
            if missing:
                raise ValueError(
                    f"model {self.name!r}: missing inputs {missing}")
        return out

    def __repr__(self):
        return (f"Model({self.name!r}, inputs={sorted(self.input_specs)}, "
                f"outputs={self.output_names})")

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_artifact(cls, dirname: str, name: Optional[str] = None):
        """Load an ``export_compiled_model`` directory (the deploy ABI).
        The symbolic-batch StableHLO artifact serves every bucket; the
        ``jax.jit`` wrapper caches one executable per concrete bucket
        shape."""
        import jax

        from ..export_model import load_compiled_model

        run, manifest = load_compiled_model(dirname)
        name = name or os.path.basename(os.path.normpath(dirname))
        jrun = jax.jit(run)

        def fn(feeds):
            return list(jrun(feeds))

        return cls(name, fn, input_specs=manifest.get("inputs"),
                   output_names=manifest.get("outputs"))

    @classmethod
    def from_compiled(cls, compiled, name: Optional[str] = None,
                      scope=None,
                      example: Optional[Dict[str, np.ndarray]] = None):
        """Wrap an AOT :class:`~paddle_tpu.core.compile_cache.
        CompiledProgram`: its pre-compiled bucket is free; other buckets
        go through the owning executor's cache on the same program."""
        return cls.from_program(
            compiled.executor, compiled.program, compiled.fetch_names,
            scope=scope, name=name, is_test=compiled.is_test,
            example=example)

    @classmethod
    def from_program(cls, executor, program, fetch_list, scope=None,
                     name: Optional[str] = None, is_test: bool = True,
                     example: Optional[Dict[str, np.ndarray]] = None):
        """Serve a live Program through ``executor.run`` (one compiled
        variant per bucket, shared content-fingerprinted cache)."""
        from ..core.program import Variable

        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in fetch_list]
        name = name or f"program-{id(program):x}"

        def fn(feeds):
            return executor.run(program, feed=feeds,
                                fetch_list=fetch_names, scope=scope,
                                return_numpy=False, is_test=is_test)

        # no input_specs: executor.run already coerces feeds to the
        # program's declared var dtypes, the same rim every caller gets
        return cls(name, fn, output_names=fetch_names, example=example)
