"""Continuous-batching autoregressive decode serving: KV-cache session
state across dispatches + per-token-step admit/evict in the batcher.

The PR 8 server batches independent single-shot requests; a *generate*
request is a session — each emitted token depends on every token before
it.  Serving it through the request batcher means either recomputing the
whole prefix per token (quadratic waste) or holding a rigid batch
hostage to its slowest member (padded-token waste).  This module is the
iteration-level scheduler (Orca, OSDI'22) over the repo's one-big-jit
executor:

* :class:`DecodeEngine` — EXACTLY two compiled step functions sharing
  one scope.  **Prefill** (batch 1, ``Tq = bucket``) runs the prompt
  through ``attention_with_cache`` writing per-layer K/V scratch slabs
  and emits the first generated token; the host inserts the scratch rows
  into the slot slabs (per-row bit independence makes the relocation
  exact).  **Decode** (batch S, ``Tq = 1``) advances every live slot one
  token, reading + appending the ``[S, Tmax, D]`` cache slabs that ride
  as DONATED persistable state across dispatches.  Every feed shape is
  fixed — slot admit/evict and sequence growth change VALUES only, so
  steady-state decode is zero-retrace (``retrace_guard`` pins it).
  Slabs are bucketed by max-len (:data:`DEFAULT_LEN_BUCKETS`), so the
  PR 3 compile-cache fingerprints cover re-instantiations.
* :class:`DecodeRuntime` — the slot pool: S concurrent sequences occupy
  fixed slots; at each token-step boundary the loop evicts finished
  (EOS/max-len) sequences and completes them immediately, admits queued
  requests into the freed slots, expires deadlines, and applies the
  PR 8 oldest-deadline shedding per STEP instead of per request.  The
  per-model circuit breaker and retry rim match the request server's
  semantics; the ``serving.decode_step`` fault-injection site fires
  INSIDE the retry rim but BEFORE the executor dispatch, so an injected
  transient retries without ever touching the donated slabs.
* ``Server.add_decode_model`` / ``Server.submit_decode`` (server.py)
  mount a runtime next to the request tenants: shared lifecycle
  (warmup/ready/drain), shared health surface, same typed rejections.

Greedy incremental decode is pinned BIT-identical to a full-recompute-
per-token oracle (tests/test_decode.py): the oracle replays the prefix
from reset state through the SAME two compiled functions — on this
substrate XLA's accumulation order is shape-dependent (a ``[1,D]``
matvec and a ``[T,D]`` matmul round differently at the ulp), so
recompute-at-the-same-shapes is the strongest oracle that can hold at
the bit level, and it is exactly the property continuous batching puts
at risk: state carried across dispatches vs state rebuilt from scratch.

``static`` mode (admit only into an EMPTY pool, then run the whole
batch to its slowest member) is the benchmark's control arm — identical
compiled functions, scheduler-only difference.
"""
from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import faults as _faults
from .. import observability as obs
from ..core.registry import register_tunable
from ..testing import faultinject as _fi
from ..testing import lockwatch as _lw
from .server import ModelError, PendingResponse

logger = logging.getLogger("paddle_tpu")

__all__ = ["DecodeEngine", "DecodeRuntime", "DEFAULT_LEN_BUCKETS"]

# Max-len buckets for the KV slabs: a request's prompt+generation budget
# is snapped UP to a bucket, so two engines with nearby limits share
# compile-cache fingerprints instead of minting per-length variants.
DEFAULT_LEN_BUCKETS = (32, 64, 128, 256, 512)

DECODE_SLOTS_DEFAULT = {"slots": 8, "step_wait_ms": 1.0}

# Autotuner knob (PR 15 convention: ctor knobs omitted by the caller are
# replayed from the persisted winner under the autotune opt-in).  The
# slot count is the compiled decode batch — more slots amortize the
# per-step dispatch over more live sequences but pay more padded compute
# when the offered load can't fill them; step_wait_ms bounds the idle
# poll when the pool is empty.
register_tunable(
    "serving/decode_slots", side="host",
    space={"slots": (2, 4, 8, 16), "step_wait_ms": (0.5, 1.0, 2.0, 5.0)},
    default=dict(DECODE_SLOTS_DEFAULT),
    description="decode slot pool: concurrent KV-cache slots (the "
                "compiled decode batch) and the idle-pool step wait.")


def bucket_for_len(max_len: int,
                   buckets: Sequence[int] = DEFAULT_LEN_BUCKETS) -> int:
    """Smallest bucket >= max_len (max_len itself when it exceeds every
    bucket — one oversized engine beats a rejected workload)."""
    for b in buckets:
        if max_len <= b:
            return int(b)
    return int(max_len)


class DecodeEngine:
    """The two-program incremental-decode executor state machine.

    Builds a small causal transformer LM (embedding -> n_layers x
    [QKV projections -> attention_with_cache -> relu projection ->
    residual] -> vocab head) TWICE over shared weights: a batch-1
    prefill at ``Tq = bucket`` and a batch-S decode at ``Tq = 1``.
    Weights live in one :class:`~paddle_tpu.core.scope.Scope` under
    explicit ``ParamAttr`` names; the per-layer cache slabs are
    persistable vars in the same scope, so the executor threads them as
    donated state.  Host-side the engine owns NO lengths — ``cache_len``
    is a feed, because the scheduler (the slot pool) is the authority on
    sequence lengths.
    """

    def __init__(self, vocab_size: int, hidden_dim: int = 32,
                 n_layers: int = 1, slots: Optional[int] = None,
                 max_len: int = 64,
                 len_buckets: Sequence[int] = DEFAULT_LEN_BUCKETS,
                 eos_id: Optional[int] = None, seed: int = 0,
                 name: str = "decode", autotune: Optional[bool] = None):
        if slots is None:
            from ..core.registry import resolve_tuned
            slots = int(resolve_tuned("serving/decode_slots",
                                      dict(DECODE_SLOTS_DEFAULT),
                                      autotune)["slots"])
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if vocab_size < 2:
            raise ValueError(f"vocab_size must be >= 2, got {vocab_size}")
        self.name = str(name)
        self.vocab_size = int(vocab_size)
        self.hidden_dim = int(hidden_dim)
        self.n_layers = int(n_layers)
        self.slots = int(slots)
        self.bucket = bucket_for_len(int(max_len), len_buckets)
        self.eos_id = eos_id
        self.seed = int(seed)
        self._build()

    # -- program construction ------------------------------------------------
    def _net(self, batch: int, tq: int, cache_prefix: str):
        from .. import layers
        from ..param_attr import ParamAttr
        from ..core.program import default_main_program

        D, V, p = self.hidden_dim, self.vocab_size, self.name
        tok = layers.data("tokens", shape=[batch, tq, 1], dtype="int64",
                          append_batch_size=False)
        cl = layers.data("cache_len", shape=[batch], dtype="int32",
                         append_batch_size=False)
        wm = layers.data("write_mask", shape=[batch], dtype="float32",
                         append_batch_size=False)
        x = layers.embedding(tok, size=[V, D],
                             param_attr=ParamAttr(name=f"{p}/emb"))
        gb = default_main_program().global_block()
        for i in range(self.n_layers):
            q = layers.fc(x, D, num_flatten_dims=2, bias_attr=False,
                          param_attr=ParamAttr(name=f"{p}/l{i}/wq"))
            k = layers.fc(x, D, num_flatten_dims=2, bias_attr=False,
                          param_attr=ParamAttr(name=f"{p}/l{i}/wk"))
            v = layers.fc(x, D, num_flatten_dims=2, bias_attr=False,
                          param_attr=ParamAttr(name=f"{p}/l{i}/wv"))
            ck = gb.create_var(name=f"{cache_prefix}_k{i}",
                               shape=(batch, self.bucket, D),
                               dtype="float32", persistable=True)
            cv = gb.create_var(name=f"{cache_prefix}_v{i}",
                               shape=(batch, self.bucket, D),
                               dtype="float32", persistable=True)
            a = layers.attention_with_cache(q, k, v, ck, cv, cl, wm)
            h = layers.fc(a, D, num_flatten_dims=2, act="relu",
                          param_attr=ParamAttr(name=f"{p}/l{i}/wp"),
                          bias_attr=ParamAttr(name=f"{p}/l{i}/bp"))
            x = layers.elementwise_add(x, h)
        return layers.fc(x, V, num_flatten_dims=2, bias_attr=False,
                         param_attr=ParamAttr(name=f"{p}/wo"))

    def _build(self):
        from ..core import Executor, Scope
        from ..core.program import Program, program_guard

        self.scope = Scope()
        self.executor = Executor()
        self.prefill_prog, startup_p = Program(), Program()
        startup_p.random_seed = self.seed
        self.prefill_prog.random_seed = self.seed
        with program_guard(self.prefill_prog, startup_p):
            self._pf_logits = self._net(1, self.bucket, f"{self.name}/pf")
        self.decode_prog, startup_d = Program(), Program()
        startup_d.random_seed = self.seed
        self.decode_prog.random_seed = self.seed
        with program_guard(self.decode_prog, startup_d):
            self._dec_logits = self._net(self.slots, 1, f"{self.name}/kv")
        # ONE startup run initializes the shared weights (both builds
        # declare identical ParamAttr names); the second program finds
        # them in the scope as persistable state
        self.executor.run(startup_p, feed={}, fetch_list=[],
                          scope=self.scope)
        self._slab_names = (
            [f"{self.name}/pf_{c}{i}" for i in range(self.n_layers)
             for c in ("k", "v")]
            + [f"{self.name}/kv_{c}{i}" for i in range(self.n_layers)
               for c in ("k", "v")])
        self.reset()

    # -- state ---------------------------------------------------------------
    def reset(self):
        """Zero every cache slab (prefill scratch + slot slabs) — the
        from-scratch state the recompute oracle replays from, and the
        recovery hygiene after a fatal mid-dispatch error (a dispatch
        that died after donation may have consumed the old buffers)."""
        import jax.numpy as jnp

        for nm in self._slab_names:
            batch = 1 if "/pf_" in nm else self.slots
            self.scope.set(nm, jnp.zeros(
                (batch, self.bucket, self.hidden_dim), jnp.float32))

    def warmup(self):
        """Compile both step functions once (dummy dispatches), then
        reset — steady-state traffic never pays a trace."""
        self.prefill(0, [0])
        self.decode_step(np.zeros(self.slots, np.int64),
                         np.zeros(self.slots, np.int32),
                         np.zeros(self.slots, np.float32))
        self.reset()

    # -- the two compiled steps ----------------------------------------------
    def prefill(self, slot: int, tokens: Sequence[int]):
        """Run the prompt through the batch-1 prefill program, insert the
        scratch K/V rows into ``slot``'s slab rows, and return
        ``(first_generated_token, logits_row [V] float32)``."""
        plen = len(tokens)
        if not 1 <= plen <= self.bucket:
            raise ValueError(
                f"prompt length {plen} outside [1, {self.bucket}] "
                f"(bucket={self.bucket})")
        padded = np.zeros((1, self.bucket, 1), np.int64)
        padded[0, :plen, 0] = np.asarray(tokens, np.int64)
        (logits,) = self.executor.run(
            self.prefill_prog,
            feed={"tokens": padded,
                  "cache_len": np.zeros(1, np.int32),
                  "write_mask": np.ones(1, np.float32)},
            fetch_list=[self._pf_logits], scope=self.scope,
            return_numpy=False, is_test=True)
        for i in range(self.n_layers):
            for c in ("k", "v"):
                slab = self.scope.get(f"{self.name}/kv_{c}{i}")
                scratch = self.scope.get(f"{self.name}/pf_{c}{i}")
                self.scope.set(f"{self.name}/kv_{c}{i}",
                               slab.at[slot].set(scratch[0]))
        row = np.asarray(logits[0, plen - 1], np.float32)
        return int(row.argmax()), row

    def decode_step(self, tokens: np.ndarray, lens: np.ndarray,
                    active: np.ndarray) -> np.ndarray:
        """One token step for every slot: ``tokens``/``lens``/``active``
        are [S] arrays (dead slots: token 0, active 0.0 — their slabs are
        never written).  Returns logits [S, 1, V] float32."""
        (logits,) = self.executor.run(
            self.decode_prog,
            feed={"tokens": np.asarray(tokens, np.int64)
                  .reshape(self.slots, 1, 1),
                  "cache_len": np.asarray(lens, np.int32),
                  "write_mask": np.asarray(active, np.float32)},
            fetch_list=[self._dec_logits], scope=self.scope,
            is_test=True)
        return logits


class _Seq:
    """One generate request riding the pool: queued, then slotted."""

    __slots__ = ("req", "prompt", "max_new", "tokens", "slot",
                 "t_first", "t_last", "inter_ms")

    def __init__(self, req: PendingResponse, prompt: List[int],
                 max_new: int):
        self.req = req
        self.prompt = prompt
        self.max_new = max_new
        self.tokens: List[int] = []
        self.slot: Optional[int] = None
        self.t_first: Optional[float] = None   # first token (TTFT)
        self.t_last: Optional[float] = None
        self.inter_ms: List[float] = []


class DecodeRuntime:
    """The continuous-batching slot pool over one :class:`DecodeEngine`.

    Usable standalone (``start()`` / ``submit()`` / ``shutdown()``) or
    mounted on a :class:`~paddle_tpu.serving.server.Server` via
    ``add_decode_model`` (shared lifecycle + health).  ``mode="static"``
    is the whole-batch-waits-for-slowest control arm.
    """

    def __init__(self, engine: DecodeEngine, name: Optional[str] = None,
                 mode: str = "continuous",
                 step_wait_ms: Optional[float] = None,
                 default_deadline_ms: Optional[float] = None,
                 queue_capacity: Optional[int] = None, shed: bool = True,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 retry_policy: Optional[_faults.RetryPolicy] = None,
                 autotune: Optional[bool] = None):
        if mode not in ("continuous", "static"):
            raise ValueError(
                f"mode must be 'continuous' or 'static', got {mode!r}")
        if step_wait_ms is None:
            from ..core.registry import resolve_tuned
            step_wait_ms = float(resolve_tuned(
                "serving/decode_slots", dict(DECODE_SLOTS_DEFAULT),
                autotune)["step_wait_ms"])
        self.engine = engine
        self.name = str(name or engine.name)
        self.mode = mode
        self.step_wait_s = float(step_wait_ms) / 1e3
        self.default_deadline_ms = default_deadline_ms
        self.queue_capacity = queue_capacity
        self.shed = bool(shed)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.retry_policy = retry_policy if retry_policy is not None else \
            _faults.RetryPolicy(max_attempts=2, backoff_base_s=0.005,
                                backoff_max_s=0.1, seed=0)
        # RLock: submit() consults breaker_state() while holding the
        # admission condition, which shares this lock
        self.lock = _lw.make_rlock("serving.decode")
        self.cond = _lw.make_condition("serving.decode", self.lock)
        self.queue: collections.deque = collections.deque()
        self.slots: List[Optional[_Seq]] = [None] * engine.slots
        self.closed = False
        self.consecutive_failures = 0
        self.breaker_open = False
        self.breaker_open_until = 0.0
        self.steps = 0
        self.tokens_done = 0
        self.served = 0
        self.t_start = time.monotonic()
        self._req_counter = 0
        self._thread: Optional[threading.Thread] = None

    # -- breaker (request-server semantics) ----------------------------------
    def breaker_state(self, now: Optional[float] = None) -> str:
        with self.lock:
            if not self.breaker_open:
                return "closed"
            now = time.monotonic() if now is None else now
            return "half_open" if now >= self.breaker_open_until else "open"

    def _note_failure(self, err: BaseException, span=None):
        opened = False
        with self.lock:
            self.consecutive_failures += 1
            if (self.consecutive_failures >= self.breaker_threshold
                    and not self.breaker_open):
                self.breaker_open = True
                opened = True
            if self.breaker_open:
                self.breaker_open_until = (time.monotonic()
                                           + self.breaker_cooldown_s)
        if opened:
            obs.inc_counter("serving/breaker_open")
            obs.emit_event("serving", event="breaker_open",
                           model=self.name,
                           error=f"{type(err).__name__}: {err}")
            if span is not None:
                span.event("breaker_open",
                           error=f"{type(err).__name__}: {err}")
            logger.error("serving: circuit breaker OPEN for decode model "
                         "%r after %d consecutive failures (%s: %s)",
                         self.name, self.consecutive_failures,
                         type(err).__name__, err)

    def _note_success(self, span=None):
        closed = False
        with self.lock:
            self.consecutive_failures = 0
            if self.breaker_open:
                self.breaker_open = False
                closed = True
        if closed:
            obs.emit_event("serving", event="breaker_close",
                           model=self.name)
            if span is not None:
                span.event("breaker_close")
            logger.info("serving: circuit breaker closed for decode "
                        "model %r (probe succeeded)", self.name)

    # -- lifecycle -----------------------------------------------------------
    def start(self, warmup: bool = True):
        if self._thread is not None:
            raise RuntimeError("DecodeRuntime.start: already started")
        if warmup:
            self.engine.warmup()
        self.t_start = time.monotonic()
        self._thread = threading.Thread(
            target=self._step_loop, name=f"pt-decode-{self.name}",
            daemon=True)
        self._thread.start()
        return self

    def close(self):
        """Close admission; the loop drains queued + active work."""
        with self.cond:
            self.closed = True
            self.cond.notify_all()

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None):
        if not drain:
            with self.cond:
                self.closed = True
                aborted = list(self.queue)
                self.queue.clear()
                actives = [s for s in self.slots if s is not None]
                self.slots = [None] * self.engine.slots
                self.cond.notify_all()
            err = _faults.ServerClosed(
                "server stopped before this request completed")
            for w in aborted + actives:
                w.req._complete(error=err)
        else:
            self.close()
        if self._thread is not None:
            self._thread.join(timeout)

    # -- admission -----------------------------------------------------------
    def submit(self, tokens: Sequence[int], max_new_tokens: int,
               deadline_ms: Optional[float] = -1.0,
               req_id=None) -> PendingResponse:
        """Admit one generate request: ``tokens`` is the prompt (ints),
        ``max_new_tokens`` bounds generation (EOS may end it earlier).
        Completes with ``{"tokens": [...], "finish": "eos"|"length",
        "ttft_ms": float, "inter_token_ms": [...]}``.  Shedding happens
        at token-step boundaries (per STEP), not here; admission only
        rejects closed/breaker-open/malformed requests — plus plain
        backpressure when ``shed=False`` and the queue is at capacity.
        """
        prompt = [int(t) for t in tokens]
        max_new = int(max_new_tokens)
        if not prompt:
            raise ValueError("submit: empty prompt")
        if max_new < 1:
            raise ValueError(f"submit: max_new_tokens must be >= 1, "
                             f"got {max_new}")
        if len(prompt) + max_new > self.engine.bucket:
            raise ValueError(
                f"decode model {self.name!r}: prompt ({len(prompt)}) + "
                f"max_new_tokens ({max_new}) exceeds the engine's "
                f"max-len bucket ({self.engine.bucket})")
        if req_id is None:
            with self.lock:
                self._req_counter += 1
                req_id = f"{self.name}-{self._req_counter}"
        sp = obs.tracing.start_span(
            "serving/request", parent=obs.tracing.ROOT,
            model=self.name, id=req_id)
        try:
            if deadline_ms == -1.0:
                deadline_ms = self.default_deadline_ms
            deadline = None if deadline_ms is None \
                else time.monotonic() + deadline_ms / 1e3
            req = PendingResponse(
                req_id, self.name,
                {"tokens": np.asarray(prompt, np.int64)}, deadline)
            req.span = sp
            w = _Seq(req, prompt, max_new)
            with self.cond:
                if self.closed:
                    raise _faults.ServerClosed(
                        f"decode model {self.name!r}: admission closed")
                if self.breaker_state() == "open":
                    raise _faults.ModelUnavailable(
                        f"decode model {self.name!r}: circuit breaker "
                        f"open; retry after cooldown")
                if (not self.shed and self.queue_capacity is not None
                        and len(self.queue) >= self.queue_capacity):
                    obs.inc_counter("serving/shed")
                    obs.emit_event("serving", event="shed",
                                   model=self.name, victim="incoming",
                                   where="decode_admission")
                    raise _faults.Overloaded(
                        f"decode model {self.name!r}: queue full "
                        f"({self.queue_capacity})")
                self.queue.append(w)
                self.cond.notify()
            obs.inc_counter("serving/requests")
            return req
        except BaseException as e:
            sp.end(status=type(e).__name__)
            raise

    # -- health --------------------------------------------------------------
    def health(self) -> dict:
        with self.lock:
            active = sum(1 for s in self.slots if s is not None)
            return {"breaker": ("closed" if not self.breaker_open else
                                "open"),
                    "slots": self.engine.slots,
                    "active": active,
                    "queue_depth": len(self.queue),
                    "served": self.served,
                    "steps": self.steps,
                    "tokens": self.tokens_done,
                    "mode": self.mode}

    # -- step loop -----------------------------------------------------------
    def _expire(self, w: _Seq, where: str) -> bool:
        if not w.req.expired():
            return False
        obs.inc_counter("serving/deadline_expired")
        obs.emit_event("serving", event="deadline_expired",
                       model=self.name, where=where)
        w.req._complete(error=_faults.DeadlineExceeded(
            f"request {w.req.id!r}: deadline expired before {where}"))
        return True

    def _shed_locked(self):
        """PR 8 oldest-deadline-first shedding applied at the token-step
        boundary: while the queue is over capacity, the queued request
        most likely to miss anyway (soonest deadline) is completed
        ``Overloaded``.  Deadline-less requests are never preferred —
        with none carrying deadlines this degrades to shedding the
        newest arrival (plain backpressure)."""
        if self.queue_capacity is None or not self.shed:
            return
        shed = []
        while len(self.queue) > self.queue_capacity:
            with_dl = [w for w in self.queue
                       if w.req.deadline is not None]
            victim = (min(with_dl, key=lambda w: w.req.deadline)
                      if with_dl else self.queue[-1])
            self.queue.remove(victim)
            shed.append(victim)
        for w in shed:
            obs.inc_counter("serving/shed")
            obs.emit_event("serving", event="shed", model=self.name,
                           victim="queued", where="decode_step")
            w.req._complete(error=_faults.Overloaded(
                f"decode model {self.name!r}: shed at step boundary "
                f"(oldest deadline first)"))

    def _pick_admits_locked(self) -> List[_Seq]:
        if self.breaker_open \
                and time.monotonic() < self.breaker_open_until:
            return []
        if self.mode == "static" \
                and any(s is not None for s in self.slots):
            return []
        free = [i for i, s in enumerate(self.slots) if s is None]
        admits: List[_Seq] = []
        while free and self.queue:
            w = self.queue.popleft()
            if self._expire(w, "decode_admit"):
                continue
            w.slot = free.pop(0)
            admits.append(w)
        return admits

    def _finish(self, w: _Seq, finish: str, now: float):
        if w.slot is not None and self.slots[w.slot] is w:
            self.slots[w.slot] = None
        with self.lock:
            self.served += 1
        ttft = None if w.t_first is None \
            else (w.t_first - w.req.t_admit) * 1e3
        obs.emit_event("serving", event="decode_done", model=self.name,
                       id=w.req.id, tokens=len(w.tokens), finish=finish,
                       ttft_ms=None if ttft is None else round(ttft, 3))
        w.req._complete(outputs={
            "tokens": list(w.tokens), "finish": finish,
            "ttft_ms": ttft, "inter_token_ms": list(w.inter_ms)})

    def _fail_active(self, err: BaseException):
        """Complete every ACTIVE sequence with a typed error and reset
        the engine slabs — a dispatch that died after donation may have
        consumed the old buffers, and the evicted sessions' state is
        unrecoverable anyway.  Queued requests are untouched."""
        with self.cond:
            actives = [s for s in self.slots if s is not None]
            self.slots = [None] * self.engine.slots
        for w in actives:
            w.req._complete(error=err)
        self.engine.reset()

    def _do_prefill(self, w: _Seq):
        try:
            tok, _ = self.engine.prefill(w.slot, w.prompt)
        except BaseException as e:   # noqa: BLE001 — containment: a
            # prefill crash fails THIS request (typed), counts toward the
            # breaker, and must not kill the step loop
            logger.exception("serving: prefill for decode model %r "
                             "failed", self.name)
            self._note_failure(e)
            w.req._complete(error=ModelError(
                f"decode model {self.name!r}: prefill failed "
                f"({type(e).__name__}: {e})"))
            return
        self._note_success()
        now = time.monotonic()
        w.tokens.append(tok)
        w.t_first = w.t_last = now
        self.slots[w.slot] = w
        with self.lock:
            self.tokens_done += 1
        obs.inc_counter("serving/decode_tokens")
        ttft = (now - w.req.t_admit) * 1e3
        obs.observe_hist("serving/decode_ttft_ms", ttft)
        obs.emit_event("serving", event="decode_admit", model=self.name,
                       id=w.req.id, slot=w.slot,
                       prompt_len=len(w.prompt),
                       ttft_ms=round(ttft, 3))
        if self.engine.eos_id is not None and tok == self.engine.eos_id:
            self._finish(w, "eos", now)
        elif len(w.tokens) >= w.max_new:
            self._finish(w, "length", now)

    def _dispatch(self, toks, lens, act, span=None):
        """The decode dispatch through the injection site + retry rim.
        The site fires BEFORE the executor call, so an injected transient
        retries with the donated slabs untouched (real executor failures
        after donation are fatal by classification and route through
        :meth:`_fail_active`)."""
        def attempt():
            if _fi.ENABLED:
                action = _fi.check("serving.decode_step")
                if action is not None:
                    if action == "fatal":
                        raise _faults.InjectedFault(
                            "injected fatal fault at serving.decode_step")
                    _fi.raise_for(action, "serving.decode_step")
            return self.engine.decode_step(toks, lens, act)

        def on_retry(i, e, d):
            obs.inc_counter("fault/retries")
            obs.emit_event("fault", event="retry",
                           site="serving.decode_step", attempt=i + 1,
                           delay_s=round(d, 4),
                           error=f"{type(e).__name__}: {e}")
            if span is not None:
                span.event("retry", attempt=i + 1, delay_s=round(d, 4),
                           error=f"{type(e).__name__}: {e}")

        if self.retry_policy is None:
            return attempt()
        return _faults.retry_call(
            attempt, self.retry_policy,
            what=f"decode step [{self.name}]", on_retry=on_retry)

    def _decode_step(self):
        S = self.engine.slots
        toks = np.zeros(S, np.int64)
        lens = np.zeros(S, np.int32)
        act = np.zeros(S, np.float32)
        live: List[_Seq] = []
        now = time.monotonic()
        for i, w in enumerate(self.slots):
            if w is None:
                continue
            if self._expire(w, "decode_step"):
                self.slots[i] = None
                continue
            toks[i] = w.tokens[-1]
            lens[i] = len(w.prompt) + len(w.tokens) - 1
            act[i] = 1.0
            live.append(w)
        if not live:
            return
        sp = obs.tracing.start_span(
            "serving/decode_step", parent=obs.tracing.ROOT,
            model=self.name, active=len(live), step=self.steps)
        t0 = time.monotonic()
        try:
            logits = self._dispatch(toks, lens, act, span=sp)
        except BaseException as e:
            self._note_failure(e, span=sp)
            obs.emit_event("serving", event="error", model=self.name,
                           error=f"{type(e).__name__}: {e}")
            self._fail_active(ModelError(
                f"decode model {self.name!r}: step dispatch failed "
                f"({type(e).__name__}: {e})"))
            sp.end(status=type(e).__name__)
            return
        dispatch_ms = (time.monotonic() - t0) * 1e3
        self._note_success(span=sp)
        now = time.monotonic()
        with self.lock:
            self.steps += 1
            self.tokens_done += len(live)
            tokens_done, t_start = self.tokens_done, self.t_start
        obs.inc_counter("serving/decode_tokens", len(live))
        obs.set_gauge("serving/decode_slot_occupancy",
                      len(live) / float(S))
        elapsed = max(now - t_start, 1e-9)
        obs.set_gauge("serving/decode_tokens_per_s",
                      tokens_done / elapsed)
        for w in live:
            nxt = int(np.argmax(logits[w.slot, 0]))
            w.tokens.append(nxt)
            gap = (now - w.t_last) * 1e3
            w.inter_ms.append(gap)
            w.t_last = now
            obs.observe_hist("serving/decode_inter_token_ms", gap)
            if self.engine.eos_id is not None \
                    and nxt == self.engine.eos_id:
                self._finish(w, "eos", now)
            elif len(w.tokens) >= w.max_new:
                self._finish(w, "length", now)
        with self.lock:
            queued = len(self.queue)
        obs.emit_event("serving", event="decode_step", model=self.name,
                       active=len(live), queued=queued,
                       dispatch_ms=round(dispatch_ms, 3))
        sp.end(status="ok", dispatch_ms=round(dispatch_ms, 3))

    def _step_loop(self):
        try:
            while True:
                with self.cond:
                    self._shed_locked()
                    if self.closed and not self.queue \
                            and not any(s is not None
                                        for s in self.slots):
                        break
                    admits = self._pick_admits_locked()
                for w in admits:
                    self._do_prefill(w)
                if not any(s is not None for s in self.slots):
                    if admits:
                        continue        # re-check the queue immediately
                    with self.cond:
                        if not self.queue and not self.closed:
                            self.cond.wait(self.step_wait_s)
                        elif self.queue and self.breaker_open:
                            # open breaker: nothing to dispatch until the
                            # cooldown admits a probe
                            self.cond.wait(self.step_wait_s)
                    continue
                self._decode_step()
        except BaseException:   # noqa: BLE001 — containment: a loop
            # death would strand every queued/active request; give them
            # terminal errors instead of a hang (mirrors _dispatch_loop)
            logger.exception("serving: decode step loop for model %r "
                             "died", self.name)
            err = ModelError(
                f"decode model {self.name!r}: internal step-loop error")
            with self.cond:
                stranded = list(self.queue)
                self.queue.clear()
                stranded += [s for s in self.slots if s is not None]
                self.slots = [None] * self.engine.slots
            for w in stranded:
                w.req._complete(error=err)