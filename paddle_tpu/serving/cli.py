"""``python -m paddle_tpu serve`` — the process form of the serving
runtime, speaking newline-delimited JSON over stdio.

Why stdio and not a socket: the contract under test is the *runtime*
(batching, deadlines, shedding, breakers, drain), and a pipe protocol
makes every degradation path deterministic for the chaos suite while
staying trivially bridgeable (an HTTP/gRPC front can own the socket and
pipe to this process, exactly how the reference's capi sat behind a
caller-owned host).

Protocol (one JSON object per line):

  stdin  →  {"id": <any>, "model": "<name>"?, "feeds": {name: nested
            list}, "deadline_ms": <float|null>?}
         |  {"cmd": "health", "id": <any>?}      (control-plane poll)
  stdout ←  {"id":..., "model":..., "outputs": [[...], ...], "ms": ...,
            "dispatch_ms": ...}
         |  {"id":..., "error": "<TypeName>", "message": "..."}
         |  {"id":..., "health": {"state":..., "models": {...}}}
         |  {"event": "state", "state": "warming|ready|draining|stopped"}
         |  {"event": "stopped", "served": N, ...}

``model`` may be omitted with a single tenant.  ``deadline_ms`` omitted
means the server default; ``null`` disables the deadline.

Lifecycle: models load + warm (``state: warming`` → ``ready``), requests
stream until stdin EOF or SIGTERM/SIGINT.  On SIGTERM: admission stops
(late lines get ``ServerClosed`` errors), in-flight batches complete,
``state: draining`` then ``stopped`` are emitted, and the process exits
0 — a supervisor (``distributed.supervisor``) relaunching the identical
command returns to ``ready`` and serves again.
"""
from __future__ import annotations

import argparse
import json
import os
import queue as _queue_mod
import signal
import sys
import threading
import time
from typing import Dict, Optional

from .. import faults as _faults
from ..observability import (metrics_snapshot, process_identity,
                             set_process_identity, tracing as _tracing)
from ..testing import lockwatch as _lw
from .model import Model
from .server import Server

__all__ = ["serve_main"]


class _Emitter:
    """Line-atomic JSON writer shared by the reader loop and the
    completion callbacks (which fire on dispatcher threads).  A broken
    pipe (the consuming parent — e.g. a fleet router — died) disables
    the writer instead of crashing the drain path: the following stdin
    EOF drains the server and exits 0."""

    def __init__(self, fh):
        self._fh = fh
        self._lock = _lw.make_lock("serving.cli.emitter")
        self._dead = False

    def emit(self, obj: dict):
        line = json.dumps(obj, default=repr)
        with self._lock:
            if self._dead:
                return
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
            except BrokenPipeError:
                self._dead = True


def _response_cb(emitter: _Emitter):
    def cb(pending):
        if pending.error is not None:
            emitter.emit({"id": pending.id, "model": pending.model,
                          "error": type(pending.error).__name__,
                          "message": str(pending.error)})
        else:
            # ms: admit -> complete server-side; dispatch_ms: the model
            # call of the serving batch.  Their difference is the
            # queue/batch wait — the fleet autoscaler's signal.
            emitter.emit({"id": pending.id, "model": pending.model,
                          "outputs": [None if o is None else o.tolist()
                                      for o in pending.outputs],
                          "ms": round((time.monotonic()
                                       - pending.t_admit) * 1e3, 3),
                          "dispatch_ms": None if pending.dispatch_ms is None
                          else round(pending.dispatch_ms, 3)})
    return cb


def _parse_models(entries):
    """--model DIR or --model name=DIR -> [(name|None, dir), ...]."""
    out = []
    for e in entries:
        name, sep, path = e.partition("=")
        out.append((name, path) if sep else (None, e))
    return out


def serve_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_tpu serve",
        description="multi-tenant inference server over exported "
                    "artifacts (paddle_tpu.serving): dynamic batching "
                    "with admission control, per-request deadlines, load "
                    "shedding, per-model circuit breaking, and graceful "
                    "SIGTERM drain.  Speaks one JSON object per line on "
                    "stdin/stdout (see paddle_tpu/serving/cli.py).")
    ap.add_argument("--model", action="append", required=True,
                    metavar="[NAME=]DIR",
                    help="export_compiled_model directory to serve "
                         "(repeat for multiple tenants; NAME defaults to "
                         "the directory basename)")
    ap.add_argument("--max-batch", type=int, default=None,
                    help="max requests coalesced per dispatch (default "
                         "32, or the persisted serving/batcher winner "
                         "under --autotune)")
    ap.add_argument("--max-wait-ms", type=float, default=None,
                    help="max batching wait after the first request "
                         "(default 5, or the persisted winner under "
                         "--autotune)")
    ap.add_argument("--autotune", action="store_true",
                    help="resolve omitted batcher knobs from the "
                         "persisted autotuner winners (paddle_tpu.tuning; "
                         "search with `python -m paddle_tpu tune "
                         "serving/batcher`)")
    ap.add_argument("--deadline-ms", type=float, default=100.0,
                    help="default per-request deadline; <= 0 disables "
                         "(default 100)")
    ap.add_argument("--queue", type=int, default=256,
                    help="admission queue capacity per model; 0 = "
                         "unbounded (default 256)")
    ap.add_argument("--no-shed", action="store_true",
                    help="disable oldest-deadline-first load shedding "
                         "(full queue then rejects newcomers; with "
                         "--queue 0 this is the no-robustness control "
                         "arm)")
    ap.add_argument("--breaker-threshold", type=int, default=3,
                    help="consecutive failed batches that open a model's "
                         "circuit breaker (default 3)")
    ap.add_argument("--breaker-cooldown-s", type=float, default=30.0,
                    help="seconds an open breaker waits before a probe "
                         "(default 30)")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip warmup dispatches (first requests pay "
                         "compile)")
    ap.add_argument("--warmup-all", action="store_true",
                    help="warm EVERY batch bucket before ready (not "
                         "just smallest+largest): steady-state "
                         "benchmarks/fleets never pay a mid-window "
                         "compile)")
    ap.add_argument("--http", type=int, default=None, metavar="PORT",
                    help="serve HTTP on PORT instead of the stdio "
                         "protocol (serving/http.py; 0 = ephemeral, "
                         "printed on the ready line)")
    ap.add_argument("--http-host", default="127.0.0.1")
    ap.add_argument("--token", action="append", metavar="TOKEN[=MODEL]",
                    help="HTTP auth token, optionally bound to one "
                         "model (repeatable; only with --http)")
    ap.add_argument("--replica-index", type=int, default=None,
                    help="this replica's index in a fleet (stamps the "
                         "JSONL identity line so multi-file merges "
                         "label events serve:<index>)")
    args = ap.parse_args(argv)

    set_process_identity("serve", args.replica_index)

    srv = Server(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        deadline_ms=(None if args.deadline_ms is not None
                     and args.deadline_ms <= 0 else args.deadline_ms),
        queue_capacity=(None if args.queue == 0 else args.queue),
        shed=not args.no_shed, breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown_s,
        warmup=not args.no_warmup,
        autotune=True if args.autotune else None)
    if args.warmup_all:
        srv.warmup_buckets = list(srv.buckets)

    emitter = _Emitter(sys.stdout)

    # Handlers FIRST: a supervisor's SIGTERM during model load or the
    # warmup-compile window (tens of seconds for big artifacts) must
    # still end in the documented drain-and-exit-0, not a default-
    # disposition kill.  Warmup itself is not interruptible (an XLA
    # compile runs to completion) — the flag is checked right after.
    drain = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: drain.set())

    for name, path in _parse_models(args.model):
        emitter.emit({"event": "loading", "model": name
                      or os.path.basename(os.path.normpath(path)),
                      "path": path})
        srv.add_model(Model.from_artifact(path, name=name))

    emitter.emit({"event": "state", "state": "warming"})
    srv.start()

    if args.http is not None:
        # HTTP front instead of the stdio loop (lazy: only --http pays
        # for serving/http.py — the zero-cost-when-unused lint gate)
        from .http import HttpFront

        tokens = None
        if args.token:
            tokens = {}
            for t in args.token:
                tok, sep, model = t.partition("=")
                tokens[tok] = model if sep else None
        front = HttpFront(srv, host=args.http_host, port=args.http,
                          tokens=tokens).start()
        host, port = front.address
        emitter.emit({"event": "state", "state": "ready",
                      "host": host, "port": port,
                      "models": sorted(srv.health()["models"])})
        while not drain.is_set():
            drain.wait(0.1)
        # admission closes first: late HTTP requests get typed 503 +
        # Connection: close while admitted work completes
        srv.begin_drain()
        emitter.emit({"event": "state", "state": "draining"})
        srv.shutdown(drain=True)
        front.stop()
        h = srv.health()
        emitter.emit({"event": "state", "state": "stopped"})
        emitter.emit({"event": "stopped", "models": h["models"]})
        return 0

    emitter.emit({"event": "state", "state": "ready",
                  "models": sorted(srv.health()["models"])})

    # A dedicated blocking reader thread feeds a line queue: selecting on
    # a BUFFERED stdin is a classic stall (readline slurps every pending
    # line into Python's buffer, then select sees an empty pipe while
    # lines sit unread).  The daemon thread dies with the process; on
    # drain, lines it already queued still get typed rejections.
    lines: _queue_mod.Queue = _queue_mod.Queue()
    _EOF = object()

    def _read_stdin():
        for raw in sys.stdin:
            lines.put(raw)
        lines.put(_EOF)

    threading.Thread(target=_read_stdin, name="pt-serving-stdin",
                     daemon=True).start()

    cb = _response_cb(emitter)
    served = 0
    eof = False
    while not drain.is_set() and not eof:
        try:
            item = lines.get(timeout=0.05)
        except _queue_mod.Empty:
            continue
        if item is _EOF:        # EOF: client closed; drain what we have
            eof = True
            break
        line = item.strip()
        if not line:
            continue
        served += _handle_line(srv, emitter, cb, line)

    # graceful drain: stop admission FIRST (late writers get typed
    # ServerClosed rejections while in-flight batches complete), then
    # wait out every admitted request
    srv.begin_drain()
    emitter.emit({"event": "state", "state": "draining"})
    if not eof:
        # answer lines already on the pipe with the typed rejection
        # instead of silently dropping them (admission is closed, so
        # submit fails fast)
        deadline = time.monotonic() + 0.2
        while time.monotonic() < deadline:
            try:
                item = lines.get(timeout=0.05)
            except _queue_mod.Empty:
                continue
            if item is _EOF:
                break
            line = item.strip()
            if line:
                _handle_line(srv, emitter, cb, line)
    srv.shutdown(drain=True)
    h = srv.health()
    emitter.emit({"event": "state", "state": "stopped"})
    emitter.emit({"event": "stopped", "admitted": served,
                  "models": h["models"]})
    return 0


def _handle_line(srv: Server, emitter: _Emitter, cb, line: str) -> int:
    """Parse + submit one request line; returns 1 if admitted."""
    try:
        msg = json.loads(line)
        if isinstance(msg, dict) and msg.get("cmd") == "health":
            # control-plane poll (the fleet router's routing signal):
            # answered inline on the reader loop — queue depth must stay
            # fresh even when every dispatcher is saturated
            reply = {"id": msg.get("id"), "health": srv.health()}
            if msg.get("metrics"):
                # opt-in fleet-collector piggyback: the default health
                # reply stays byte-stable
                reply["metrics"] = metrics_snapshot()
                reply["identity"] = process_identity()
            emitter.emit(reply)
            return 0
        if not isinstance(msg, dict) or "feeds" not in msg:
            raise ValueError("want {'id', 'feeds': {...}} or "
                             "{'cmd': 'health'}")
    except (json.JSONDecodeError, ValueError) as e:
        emitter.emit({"id": None, "error": "BadRequest", "message": str(e)})
        return 0
    req_id = msg.get("id")
    deadline_ms: Optional[float] = msg.get("deadline_ms", -1.0)
    feeds: Dict[str, object] = msg["feeds"]
    try:
        pending = srv.submit(feeds, model=msg.get("model"),
                             deadline_ms=deadline_ms, req_id=req_id,
                             trace_parent=(_tracing.extract(msg["ctx"])
                                           if "ctx" in msg else None))
    except (_faults.Overloaded, _faults.ServerClosed,
            _faults.ModelUnavailable, ConnectionError, ValueError) as e:
        emitter.emit({"id": req_id, "error": type(e).__name__,
                      "message": str(e)})
        return 0
    except Exception as e:      # malformed feeds etc.
        emitter.emit({"id": req_id, "error": "BadRequest",
                      "message": f"{type(e).__name__}: {e}"})
        return 0
    pending.add_done_callback(cb)
    return 1
