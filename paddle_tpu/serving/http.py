"""HTTP/1.1 serving front: the network rim over the in-process serving
runtime (or a fleet router — anything with the ``submit``/``health``
surface).

PR 8 deliberately stopped at a stdio JSON protocol; this module is the
"web service in front of the C inference API" half of the reference's
deployment story (PAPER.md §capi), built on stdlib ``http.server`` /
``socketserver`` only — no new dependencies.  One POST body carries one
or more newline-delimited JSON requests (the exact stdio line schema),
and replies stream back as newline-delimited JSON in completion order:

  POST /v1/infer                  body: {"id", "model"?, "feeds",
                                         "deadline_ms"?}  (1+ lines)
  GET  /healthz                   backend health() JSON (200 ready / 503)
  GET  /metrics                   Prometheus text exposition

**Deadline propagation** — the ``X-Paddle-Deadline-Ms`` request header
becomes the per-request deadline for every body line that does not carry
its own ``deadline_ms``; it flows into the existing deadline machinery
and expires at the same two rims PR 8 pins (batch formation and
dispatch).  A request that expires maps to 504.

**Typed rejections → status codes** (single-request bodies; multi-line
bodies stream per-line error objects under a 200):

  ============================  ======  =====================
  Overloaded                     429    Retry-After: 1
  DeadlineExceeded               504
  ModelUnavailable               503    Retry-After: cooldown
  ServerClosed                   503    Connection: close
  ModelError                     500
  BadRequest (parse/feeds)       400
  auth (missing/unknown token)   401/403
  ============================  ======  =====================

**Per-tenant auth → model routing** — an optional ``tokens`` map
(``{token: model_name-or-None}``) gates admission: requests authenticate
with ``Authorization: Bearer <token>`` (or ``X-Paddle-Token``); a token
bound to a model routes every line to that model and 403s an explicit
mismatch, a ``None`` binding admits any tenant.

ZERO COST WHEN UNUSED: nothing in ``paddle_tpu`` — including
``paddle_tpu.serving`` itself — imports this module at top level
(repo-lint enforced); only the CLI's ``--http`` / ``fleet`` branches and
an explicit ``from paddle_tpu.serving.http import HttpFront`` pay for it.
"""
from __future__ import annotations

import json
import logging
import queue as _queue_mod
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from .. import faults as _faults
from .. import observability as obs

logger = logging.getLogger("paddle_tpu")

__all__ = ["HttpFront", "DEADLINE_HEADER", "TOKEN_HEADER", "status_for"]

DEADLINE_HEADER = "X-Paddle-Deadline-Ms"
TOKEN_HEADER = "X-Paddle-Token"


def status_for(exc: BaseException) -> int:
    """Map a typed serving rejection to its HTTP status."""
    if isinstance(exc, _faults.Overloaded):
        return 429
    if isinstance(exc, _faults.DeadlineExceeded):
        return 504
    if isinstance(exc, (_faults.ModelUnavailable, _faults.ServerClosed)):
        return 503
    if isinstance(exc, (ValueError, TypeError, KeyError)):
        return 400                  # unknown model / malformed feeds
    return 500                      # ModelError and anything else


def _error_obj(req_id, exc: BaseException) -> dict:
    return {"id": req_id, "error": type(exc).__name__, "message": str(exc)}


def _response_obj(pending) -> dict:
    """Wire form of one completed request (same schema as the stdio
    protocol's response lines)."""
    if pending.error is not None:
        return _error_obj(pending.id, pending.error)
    # outputs are numpy rows from an in-process Server, but already
    # nested lists when the backend is a fleet router over process
    # replicas (they arrived as wire JSON)
    return {"id": pending.id, "model": pending.model,
            "outputs": [o.tolist() if hasattr(o, "tolist") else o
                        for o in pending.outputs],
            "ms": round((time.monotonic() - pending.t_admit) * 1e3, 3),
            "dispatch_ms": None if pending.dispatch_ms is None
            else round(pending.dispatch_ms, 3)}


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1: keep-alive + chunked transfer encoding for streamed
    # multi-request replies
    protocol_version = "HTTP/1.1"

    # -- plumbing ------------------------------------------------------------
    def log_message(self, fmt, *args):          # quiet by default
        logger.debug("http: %s", fmt % args)

    @property
    def front(self) -> "HttpFront":
        return self.server.front                # type: ignore[attr-defined]

    def _send_json(self, status: int, obj: dict, *,
                   headers: Optional[Dict[str, str]] = None,
                   close: bool = False):
        body = (json.dumps(obj, default=repr) + "\n").encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        if close:
            self.send_header("Connection", "close")
            self.close_connection = True
        self.end_headers()
        self.wfile.write(body)

    def _reject(self, status: int, exc_or_msg, req_id=None, *,
                headers=None, close=False, auth=False):
        obs.inc_counter("http/rejected")
        if auth:
            obs.inc_counter("http/auth_failures")
        if isinstance(exc_or_msg, BaseException):
            obj = _error_obj(req_id, exc_or_msg)
        else:
            obj = {"id": req_id, "error": "BadRequest",
                   "message": str(exc_or_msg)}
        self._send_json(status, obj, headers=headers, close=close)
        return status

    # -- GET -----------------------------------------------------------------
    def do_GET(self):
        # W3C traceparent: a valid header parents this request's span
        # onto the edge caller's trace; absent/garbage degrades to a
        # fresh per-request trace (garbage is counted, never fatal)
        parent = obs.tracing.extract_traceparent(
            self.headers.get("traceparent")) or obs.tracing.ROOT
        sp = obs.tracing.start_span("http/request", parent=parent,
                                    method="GET", path=self.path)
        t0 = time.monotonic()
        try:
            status = self._get()
        except BrokenPipeError:                  # client went away
            sp.cancel()
            return
        except Exception as e:                   # noqa: BLE001 — contained
            logger.exception("http: GET %s failed", self.path)
            try:
                status = self._reject(500, e)
            except BrokenPipeError:
                sp.cancel()
                return
        obs.observe_hist("http/request_ms", (time.monotonic() - t0) * 1e3)
        sp.end(status=status)

    def _get(self) -> int:
        if self.path in ("/healthz", "/health"):
            h = self.front.backend.health()
            status = 200 if h.get("ready") else 503
            self._send_json(status, h)
            return status
        if self.path == "/metrics":
            text = obs.to_prometheus(obs.metrics_snapshot())
            body = text.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return 200
        return self._reject(404, f"no such path {self.path!r} "
                            f"(have /v1/infer, /healthz, /metrics)")

    # -- POST /v1/infer ------------------------------------------------------
    def do_POST(self):
        obs.inc_counter("http/requests")
        parent = obs.tracing.extract_traceparent(
            self.headers.get("traceparent")) or obs.tracing.ROOT
        sp = obs.tracing.start_span("http/request", parent=parent,
                                    method="POST", path=self.path)
        t0 = time.monotonic()
        try:
            status = self._post()
        except BrokenPipeError:
            sp.cancel()
            return
        except Exception as e:                   # noqa: BLE001 — contained
            logger.exception("http: POST %s failed", self.path)
            try:
                status = self._reject(500, e)
            except BrokenPipeError:
                sp.cancel()
                return
        obs.observe_hist("http/request_ms", (time.monotonic() - t0) * 1e3)
        sp.end(status=status)

    def _auth(self):
        """(model_bound_by_token, error_status_or_None).  With no token
        table the front is open (None binding)."""
        tokens = self.front.tokens
        if tokens is None:
            return None, None
        tok = self.headers.get(TOKEN_HEADER)
        if tok is None:
            bearer = self.headers.get("Authorization", "")
            if bearer.startswith("Bearer "):
                tok = bearer[len("Bearer "):].strip()
        if tok is None:
            return None, self._reject(
                401, "missing auth token (Authorization: Bearer <token> "
                     f"or {TOKEN_HEADER})", auth=True,
                headers={"WWW-Authenticate": "Bearer"})
        if tok not in tokens:
            return None, self._reject(401, "unknown auth token", auth=True,
                                      headers={"WWW-Authenticate": "Bearer"})
        return tokens[tok], None

    def _post(self) -> int:
        if self.path not in ("/v1/infer", "/infer"):
            return self._reject(404, f"no such path {self.path!r} "
                                f"(POST /v1/infer)")
        token_model, err = self._auth()
        if err is not None:
            return err
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            return self._reject(411, "bad Content-Length")
        if length <= 0:
            return self._reject(411, "Content-Length required")
        raw = self.rfile.read(length).decode("utf-8", errors="replace")
        lines = [ln for ln in raw.splitlines() if ln.strip()]
        if not lines:
            return self._reject(400, "empty body: want newline-delimited "
                                "JSON request objects")
        # the client timeout header is the default deadline for every
        # line that doesn't set its own deadline_ms
        hdr_deadline: Optional[float] = -1.0
        hdr_raw = self.headers.get(DEADLINE_HEADER)
        if hdr_raw is not None:
            try:
                hdr_deadline = float(hdr_raw)
                if hdr_deadline <= 0:
                    hdr_deadline = None        # explicit "no deadline"
            except ValueError:
                return self._reject(
                    400, f"bad {DEADLINE_HEADER}: {hdr_raw!r}")
        if len(lines) == 1:
            return self._post_single(lines[0], token_model, hdr_deadline)
        return self._post_stream(lines, token_model, hdr_deadline)

    def _submit_line(self, line: str, token_model, hdr_deadline):
        """Parse + submit one body line.  Returns (pending, None) on
        admission, (None, (exc, req_id)) on any typed rejection."""
        try:
            msg = json.loads(line)
            if not isinstance(msg, dict) or "feeds" not in msg:
                raise ValueError("want {'id', 'feeds': {...}}")
        except (json.JSONDecodeError, ValueError) as e:
            return None, (ValueError(str(e)), None)
        req_id = msg.get("id")
        model = msg.get("model")
        if token_model is not None:
            if model is not None and model != token_model:
                exc = PermissionError(
                    f"token is bound to model {token_model!r}, "
                    f"not {model!r}")
                return None, (exc, req_id)
            model = token_model
        deadline_ms = msg.get("deadline_ms", hdr_deadline)
        try:
            pending = self.front.backend.submit(
                msg["feeds"], model=model, deadline_ms=deadline_ms,
                req_id=req_id)
        except BaseException as e:     # typed rejection / bad feeds
            return None, (e, req_id)
        return pending, None

    def _post_single(self, line: str, token_model, hdr_deadline) -> int:
        pending, rejected = self._submit_line(line, token_model,
                                              hdr_deadline)
        if rejected is not None:
            exc, req_id = rejected
            if isinstance(exc, PermissionError):
                return self._reject(403, exc, req_id, auth=True)
            return self._finish_error(exc, req_id)
        try:
            pending.result(timeout=self.front.result_timeout_s)
        except TimeoutError as e:
            return self._finish_error(_faults.DeadlineExceeded(str(e)),
                                      pending.id)
        except BaseException as e:     # the request's typed terminal error
            return self._finish_error(e, pending.id)
        self._send_json(200, _response_obj(pending))
        return 200

    def _finish_error(self, exc: BaseException, req_id) -> int:
        status = status_for(exc)
        headers = {}
        close = False
        if isinstance(exc, _faults.Overloaded):
            headers["Retry-After"] = "1"
        elif isinstance(exc, _faults.ModelUnavailable):
            headers["Retry-After"] = "5"
        elif isinstance(exc, _faults.ServerClosed):
            # this replica is going away: the client must reconnect
            # (through its balancer) instead of reusing the connection
            close = True
        return self._reject(status, exc, req_id, headers=headers,
                            close=close)

    def _post_stream(self, lines, token_model, hdr_deadline) -> int:
        """N>1 request lines: stream newline-JSON responses back in
        completion order under a 200 with chunked transfer encoding —
        per-line failures ride as error objects, they don't fail the
        stream."""
        done: _queue_mod.Queue = _queue_mod.Queue()
        expected = 0
        for line in lines:
            pending, rejected = self._submit_line(line, token_model,
                                                  hdr_deadline)
            expected += 1
            if rejected is not None:
                exc, req_id = rejected
                obs.inc_counter("http/rejected")
                if isinstance(exc, PermissionError):
                    # same accounting as the single-line 403 path
                    obs.inc_counter("http/auth_failures")
                done.put(_error_obj(req_id, exc))
            else:
                pending.add_done_callback(
                    lambda p: done.put(_response_obj(p)))
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        deadline = time.monotonic() + self.front.result_timeout_s
        for _ in range(expected):
            remaining = deadline - time.monotonic()
            try:
                obj = done.get(timeout=max(0.0, remaining))
            except _queue_mod.Empty:
                obj = {"id": None, "error": "DeadlineExceeded",
                       "message": "response stream timed out"}
            self._write_chunk(json.dumps(obj, default=repr) + "\n")
        self.wfile.write(b"0\r\n\r\n")
        return 200

    def _write_chunk(self, text: str):
        data = text.encode("utf-8")
        self.wfile.write(f"{len(data):x}\r\n".encode("ascii"))
        self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()


class _FrontServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class HttpFront:
    """HTTP front over any backend with the server surface
    (``submit(feeds, model=, deadline_ms=, req_id=)`` returning a
    :class:`~paddle_tpu.serving.server.PendingResponse`-shaped handle,
    plus ``health()``) — an in-process
    :class:`~paddle_tpu.serving.server.Server` or a
    :class:`~paddle_tpu.serving.fleet.FleetRouter`.

    ::

        front = HttpFront(server, port=8000).start()
        ...                       # serve
        front.stop()              # close the socket (backend untouched)

    ``tokens``: optional ``{token: model-or-None}`` auth table;
    ``result_timeout_s`` bounds how long one HTTP exchange may wait on
    a response (deadline-less requests against a wedged backend).
    """

    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0,
                 tokens: Optional[Dict[str, Optional[str]]] = None,
                 result_timeout_s: float = 120.0):
        self.backend = backend
        self.tokens = dict(tokens) if tokens is not None else None
        self.result_timeout_s = float(result_timeout_s)
        self._httpd = _FrontServer((host, int(port)), _Handler)
        self._httpd.front = self            # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        """(host, port) actually bound — port 0 resolves at bind."""
        return self._httpd.server_address[:2]

    def start(self) -> "HttpFront":
        if self._thread is not None:
            raise RuntimeError("HttpFront.start: already started")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="pt-http-front", daemon=True)
        self._thread.start()
        host, port = self.address
        obs.emit_event("serving", event="http_front", host=host, port=port)
        logger.info("serving: HTTP front listening on %s:%d", host, port)
        return self

    def stop(self):
        """Stop accepting connections and close the socket.  The backend
        (server/router) is the caller's to drain."""
        if self._thread is None:
            return
        self._httpd.shutdown()
        self._thread.join(timeout=10)
        self._httpd.server_close()
        self._thread = None

    def serve_until(self, stop_event: threading.Event,
                    poll_s: float = 0.1):
        """Convenience for CLI mains: start (if needed), then block until
        ``stop_event`` is set."""
        if self._thread is None:
            self.start()
        while not stop_event.wait(poll_s):
            pass
