"""VGG (reference: benchmark/paddle/image/vgg.py, book
test_image_classification_train.py vgg16_bn_drop)."""
from __future__ import annotations

from .. import layers, nets

_VGG_CFG = {
    11: (1, 1, 2, 2, 2),
    13: (2, 2, 2, 2, 2),
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}


def _vgg_network(img, num_classes, depth, with_bn=False, fc_size=4096,
                 drop_rate=0.5):
    counts = _VGG_CFG[depth]
    filters = (64, 128, 256, 512, 512)
    tmp = img
    for n, nf in zip(counts, filters):
        tmp = nets.img_conv_group(
            tmp, conv_num_filter=[nf] * n, pool_size=2, pool_stride=2,
            conv_filter_size=3, conv_padding=1, conv_act="relu",
            conv_with_batchnorm=with_bn)
    fc1 = layers.fc(tmp, size=fc_size, act="relu")
    fc1 = layers.dropout(fc1, drop_rate)
    fc2 = layers.fc(fc1, size=fc_size, act="relu")
    fc2 = layers.dropout(fc2, drop_rate)
    return layers.fc(fc2, size=num_classes, act="softmax")


def vgg16(img, num_classes=1000, with_bn=False):
    return _vgg_network(img, num_classes, 16, with_bn)


def vgg19(img, num_classes=1000, with_bn=False):
    return _vgg_network(img, num_classes, 19, with_bn)


def vgg_cifar(img, num_classes=10):
    """vgg16 with BN + small fc head (book vgg16_bn_drop)."""
    return _vgg_network(img, num_classes, 16, with_bn=True, fc_size=512)
