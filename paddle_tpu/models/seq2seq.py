"""Seq2seq with attention (reference: fluid/tests/book/test_machine_translation.py,
v1 demo seqToseq; generation analog of RecurrentGradientMachine.generateSequence,
gserver/gradientmachines/RecurrentGradientMachine.h:307-309).

Training builds an encoder (GRU over padded+length batches) and a StaticRNN
decoder computing dot-product attention per step — the whole thing traces to
one lax.scan that XLA pipelines on the MXU.

Inference (``seq2seq_infer``) reuses the SAME parameter names inside a
BeamSearchDecoder (layers/generation.py) so a trained scope decodes directly
— the reference's --job=test generation path (api/SequenceGenerator.cpp).
"""
from __future__ import annotations

from .. import layers
from ..layers import control_flow
from ..param_attr import ParamAttr


def _p(prefix, name):
    return ParamAttr(name=f"{prefix}.{name}")


def encoder(src, vocab_size, emb_dim=64, hidden_dim=64, prefix="s2s"):
    emb = layers.embedding(src, size=[vocab_size, emb_dim],
                           param_attr=_p(prefix, "src_emb"))
    proj = layers.fc(emb, size=hidden_dim * 3, num_flatten_dims=2,
                     param_attr=_p(prefix, "enc_proj_w"),
                     bias_attr=_p(prefix, "enc_proj_b"))
    enc = layers.dynamic_gru(proj, size=hidden_dim,
                             param_attr=_p(prefix, "enc_gru_w"),
                             bias_attr=_p(prefix, "enc_gru_b"))
    return enc


def _attention(state, enc_out, enc_proj):
    """Dot-product attention: state [B,H] vs enc_proj [B,T,H] -> ctx [B,H].

    Padding positions are already zeroed in enc_out by the masked recurrence,
    so a plain softmax over T suffices for the reference's parity tests; the
    padded tail contributes near-zero context.
    """
    q = layers.unsqueeze(state, [2])                     # [B,H,1]
    scores = layers.matmul(enc_proj, q)                  # [B,T,1]
    scores = layers.squeeze(scores, [2])                 # [B,T]
    weights = layers.softmax(scores)                     # [B,T]
    w = layers.unsqueeze(weights, [1])                   # [B,1,T]
    ctx = layers.matmul(w, enc_out)                      # [B,1,H]
    return layers.squeeze(ctx, [1])


def _encoder_head(src, src_vocab_size, emb_dim, hidden_dim, prefix):
    enc_out = encoder(src, src_vocab_size, emb_dim, hidden_dim, prefix)
    enc_proj = layers.fc(enc_out, size=hidden_dim, num_flatten_dims=2,
                         param_attr=_p(prefix, "att_proj_w"),
                         bias_attr=False)
    dec_init = layers.fc(layers.sequence_last_step(enc_out),
                         size=hidden_dim, act="tanh",
                         param_attr=_p(prefix, "dec_init_w"),
                         bias_attr=_p(prefix, "dec_init_b"))
    return enc_out, enc_proj, dec_init


def _decoder_step(tok_emb, state, enc_out, enc_proj, hidden_dim,
                  tgt_vocab_size, prefix):
    ctx = _attention(state, enc_out, enc_proj)
    gates = layers.fc([tok_emb, ctx], size=hidden_dim * 3,
                      param_attr=[_p(prefix, "dec_gates_w_emb"),
                                  _p(prefix, "dec_gates_w_ctx")],
                      bias_attr=_p(prefix, "dec_gates_b"))
    new_state, _, _ = layers.gru_unit(
        gates, state, size=hidden_dim * 3,
        param_attr=_p(prefix, "dec_gru_w"),
        bias_attr=_p(prefix, "dec_gru_b"))
    probs = layers.fc(new_state, size=tgt_vocab_size, act="softmax",
                      param_attr=_p(prefix, "dec_out_w"),
                      bias_attr=_p(prefix, "dec_out_b"))
    return new_state, probs


def seq2seq_attention(src, tgt, src_vocab_size, tgt_vocab_size,
                      emb_dim=64, hidden_dim=64, prefix="s2s"):
    """Teacher-forced training network; returns per-step [B,T,V] softmax.

    ``src``/``tgt`` are int token tensors [B,T] with lod_level=1.
    """
    enc_out, enc_proj, dec_init = _encoder_head(
        src, src_vocab_size, emb_dim, hidden_dim, prefix)
    tgt_emb = layers.embedding(tgt, size=[tgt_vocab_size, emb_dim],
                               param_attr=_p(prefix, "tgt_emb"))

    rnn = control_flow.StaticRNN()
    with rnn.step():
        step_emb = rnn.step_input(tgt_emb)
        state = rnn.memory(init=dec_init)
        new_state, probs = _decoder_step(step_emb, state, enc_out, enc_proj,
                                         hidden_dim, tgt_vocab_size, prefix)
        rnn.update_memory(state, new_state)
        rnn.step_output(probs)
    return rnn()


def seq2seq_infer(src, src_vocab_size, tgt_vocab_size, emb_dim=64,
                  hidden_dim=64, beam_size=4, bos_id=0, eos_id=1,
                  max_len=16, length_penalty=0.0, prefix="s2s"):
    """Beam-search decoding network sharing parameter names with
    ``seq2seq_attention``; build it in a separate program run against the
    trained scope.  Returns (ids [B,K,max_len], scores [B,K], lens [B,K])."""
    from ..layers.generation import BeamSearchDecoder

    enc_out, enc_proj, dec_init = _encoder_head(
        src, src_vocab_size, emb_dim, hidden_dim, prefix)

    bs = BeamSearchDecoder(beam_size=beam_size, bos_id=bos_id, eos_id=eos_id,
                           max_len=max_len, vocab_size=tgt_vocab_size,
                           length_penalty=length_penalty)
    with bs.step():
        tok = bs.token()
        state = bs.memory(init=dec_init)
        enc_out_t = bs.context(enc_out)
        enc_proj_t = bs.context(enc_proj)
        tok_emb = layers.embedding(tok, size=[tgt_vocab_size, emb_dim],
                                   param_attr=_p(prefix, "tgt_emb"))
        new_state, probs = _decoder_step(tok_emb, state, enc_out_t,
                                         enc_proj_t, hidden_dim,
                                         tgt_vocab_size, prefix)
        bs.update_memory(state, new_state)
        bs.set_probs(probs)
    return bs()
