"""Seq2seq with attention (reference: fluid/tests/book/test_machine_translation.py,
v1 demo seqToseq; generation analog of RecurrentGradientMachine.generateSequence,
gserver/gradientmachines/RecurrentGradientMachine.h:307-309).

Training builds an encoder (bi-directional-capable GRU over padded+length
batches) and a StaticRNN decoder computing dot-product attention per step —
the whole thing traces to one lax.scan that XLA pipelines on the MXU.

Inference/beam-search lives in ``paddle_tpu.generation`` (static-shape beam
search under jit; the reference needed a dedicated C++ beam machine).
"""
from __future__ import annotations

from .. import layers
from ..layers import control_flow


def encoder(src, vocab_size, emb_dim=64, hidden_dim=64):
    emb = layers.embedding(src, size=[vocab_size, emb_dim])
    proj = layers.fc(emb, size=hidden_dim * 3, num_flatten_dims=2)
    enc = layers.dynamic_gru(proj, size=hidden_dim)
    return enc


def _attention(state, enc_out, enc_proj):
    """Dot-product attention: state [B,H] vs enc_proj [B,T,H] -> ctx [B,H].

    Padding positions are already zeroed in enc_out by the masked recurrence,
    so a plain softmax over T suffices for the reference's parity tests; the
    padded tail contributes near-zero context.
    """
    q = layers.unsqueeze(state, [2])                     # [B,H,1]
    scores = layers.matmul(enc_proj, q)                  # [B,T,1]
    scores = layers.squeeze(scores, [2])                 # [B,T]
    weights = layers.softmax(scores)                     # [B,T]
    w = layers.unsqueeze(weights, [1])                   # [B,1,T]
    ctx = layers.matmul(w, enc_out)                      # [B,1,H]
    return layers.squeeze(ctx, [1])


def seq2seq_attention(src, tgt, src_vocab_size, tgt_vocab_size,
                      emb_dim=64, hidden_dim=64):
    """Teacher-forced training network; returns per-step [B,T,V] softmax.

    ``src``/``tgt`` are int token tensors [B,T] with lod_level=1.
    """
    enc_out = encoder(src, src_vocab_size, emb_dim, hidden_dim)
    enc_proj = layers.fc(enc_out, size=hidden_dim, num_flatten_dims=2,
                         bias_attr=False)
    dec_init = layers.fc(layers.sequence_last_step(enc_out),
                         size=hidden_dim, act="tanh")

    tgt_emb = layers.embedding(tgt, size=[tgt_vocab_size, emb_dim])

    rnn = control_flow.StaticRNN()
    with rnn.step():
        step_emb = rnn.step_input(tgt_emb)
        state = rnn.memory(init=dec_init)
        ctx = _attention(state, enc_out, enc_proj)
        gates = layers.fc([step_emb, ctx], size=hidden_dim * 3)
        new_state, _, _ = layers.gru_unit(gates, state, size=hidden_dim * 3)
        rnn.update_memory(state, new_state)
        scores = layers.fc(new_state, size=tgt_vocab_size, act="softmax")
        rnn.step_output(scores)
    return rnn()
