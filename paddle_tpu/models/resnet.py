"""ResNet for ImageNet and CIFAR (reference: benchmark/paddle/image/resnet.py,
fluid/tests/book/test_image_classification_train.py resnet_cifar10).

TPU notes: NCHW layout is kept at the API surface for reference parity; the
conv lowering transposes to NHWC internally where XLA prefers it.  All matmul/
conv compute is eligible for bf16 via the executor's amp mode.
"""
from __future__ import annotations

from .. import layers


def conv_bn_layer(input, num_filters, filter_size, stride=1, padding=None,
                  act="relu"):
    if padding is None:
        padding = (filter_size - 1) // 2
    conv = layers.conv2d(input, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=padding, act=None, bias_attr=False)
    return layers.batch_norm(conv, act=act)


def shortcut(input, ch_in, ch_out, stride):
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None)
    return input


def bottleneck_block(input, ch_in, num_filters, stride):
    """1x1 -> 3x3 -> 1x1(x4) bottleneck (resnet.py:89-100 structure)."""
    conv0 = conv_bn_layer(input, num_filters, 1, 1, 0)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride, 1)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, 1, 0, act=None)
    short = shortcut(input, ch_in, num_filters * 4, stride)
    return layers.elementwise_add(short, conv2, act="relu")


def basic_block(input, ch_in, num_filters, stride):
    conv0 = conv_bn_layer(input, num_filters, 3, stride, 1)
    conv1 = conv_bn_layer(conv0, num_filters, 3, 1, 1, act=None)
    short = shortcut(input, ch_in, num_filters, stride)
    return layers.elementwise_add(short, conv1, act="relu")


_DEPTH_CFG = {
    # depth: (block fn, counts, expansion)
    18: (basic_block, (2, 2, 2, 2), 1),
    34: (basic_block, (3, 4, 6, 3), 1),
    50: (bottleneck_block, (3, 4, 6, 3), 4),
    101: (bottleneck_block, (3, 4, 23, 3), 4),
    152: (bottleneck_block, (3, 8, 36, 3), 4),
}


def resnet_imagenet(img, num_classes=1000, depth=50):
    """ResNet-{18,34,50,101,152} on 224x224 (resnet.py:118-146)."""
    block_fn, counts, expansion = _DEPTH_CFG[depth]
    conv = conv_bn_layer(img, 64, 7, stride=2, padding=3)
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                         pool_type="max")
    ch_in = 64
    filters = (64, 128, 256, 512)
    out = pool
    for stage, (nf, n) in enumerate(zip(filters, counts)):
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            out = block_fn(out, ch_in, nf, stride)
            ch_in = nf * expansion
    pool = layers.pool2d(out, pool_type="avg", global_pooling=True)
    return layers.fc(pool, size=num_classes, act="softmax")


def resnet50(img, num_classes=1000):
    return resnet_imagenet(img, num_classes, depth=50)


def resnet_cifar(img, num_classes=10, depth=32):
    """3-stage CIFAR resnet (book test_image_classification resnet_cifar10)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv = conv_bn_layer(img, 16, 3, 1, 1)
    out = conv
    ch_in = 16
    for stage, nf in enumerate((16, 32, 64)):
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            out = basic_block(out, ch_in, nf, stride)
            ch_in = nf
    pool = layers.pool2d(out, pool_type="avg", global_pooling=True)
    return layers.fc(pool, size=num_classes, act="softmax")
