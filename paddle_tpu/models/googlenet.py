"""GoogLeNet / Inception-v1 (reference: benchmark/paddle/image/googlenet.py).

The two auxiliary-classifier heads of the paper are omitted exactly as in the
reference benchmark config (googlenet.py trains the main head only).
"""
from __future__ import annotations

from .. import layers


def inception(input, c1, c3r, c3, c5r, c5, proj):
    conv1 = layers.conv2d(input, num_filters=c1, filter_size=1, act="relu")
    conv3r = layers.conv2d(input, num_filters=c3r, filter_size=1, act="relu")
    conv3 = layers.conv2d(conv3r, num_filters=c3, filter_size=3, padding=1,
                          act="relu")
    conv5r = layers.conv2d(input, num_filters=c5r, filter_size=1, act="relu")
    conv5 = layers.conv2d(conv5r, num_filters=c5, filter_size=5, padding=2,
                          act="relu")
    pool = layers.pool2d(input, pool_size=3, pool_stride=1, pool_padding=1)
    convprj = layers.conv2d(pool, num_filters=proj, filter_size=1, act="relu")
    return layers.concat([conv1, conv3, conv5, convprj], axis=1)


def googlenet(img, num_classes=1000):
    conv = layers.conv2d(img, num_filters=64, filter_size=7, stride=2,
                         padding=3, act="relu")
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2)
    conv = layers.conv2d(pool, num_filters=64, filter_size=1, act="relu")
    conv = layers.conv2d(conv, num_filters=192, filter_size=3, padding=1,
                         act="relu")
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2)

    ince3a = inception(pool, 64, 96, 128, 16, 32, 32)
    ince3b = inception(ince3a, 128, 128, 192, 32, 96, 64)
    pool3 = layers.pool2d(ince3b, pool_size=3, pool_stride=2)

    ince4a = inception(pool3, 192, 96, 208, 16, 48, 64)
    ince4b = inception(ince4a, 160, 112, 224, 24, 64, 64)
    ince4c = inception(ince4b, 128, 128, 256, 24, 64, 64)
    ince4d = inception(ince4c, 112, 144, 288, 32, 64, 64)
    ince4e = inception(ince4d, 256, 160, 320, 32, 128, 128)
    pool4 = layers.pool2d(ince4e, pool_size=3, pool_stride=2)

    ince5a = inception(pool4, 256, 160, 320, 32, 128, 128)
    ince5b = inception(ince5a, 384, 192, 384, 48, 128, 128)
    pool5 = layers.pool2d(ince5b, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool5, 0.4)
    return layers.fc(drop, size=num_classes, act="softmax")
