"""AlexNet (reference: benchmark/paddle/image/alexnet.py)."""
from __future__ import annotations

from .. import layers


def alexnet(img, num_classes=1000, use_lrn=True):
    conv1 = layers.conv2d(img, num_filters=64, filter_size=11, stride=4,
                          padding=2, act="relu")
    if use_lrn:
        conv1 = layers.lrn(conv1, n=5, alpha=1e-4, beta=0.75)
    pool1 = layers.pool2d(conv1, pool_size=3, pool_stride=2)

    conv2 = layers.conv2d(pool1, num_filters=192, filter_size=5, padding=2,
                          act="relu")
    if use_lrn:
        conv2 = layers.lrn(conv2, n=5, alpha=1e-4, beta=0.75)
    pool2 = layers.pool2d(conv2, pool_size=3, pool_stride=2)

    conv3 = layers.conv2d(pool2, num_filters=384, filter_size=3, padding=1,
                          act="relu")
    conv4 = layers.conv2d(conv3, num_filters=256, filter_size=3, padding=1,
                          act="relu")
    conv5 = layers.conv2d(conv4, num_filters=256, filter_size=3, padding=1,
                          act="relu")
    pool3 = layers.pool2d(conv5, pool_size=3, pool_stride=2)

    fc1 = layers.fc(pool3, size=4096, act="relu")
    fc1 = layers.dropout(fc1, 0.5)
    fc2 = layers.fc(fc1, size=4096, act="relu")
    fc2 = layers.dropout(fc2, 0.5)
    return layers.fc(fc2, size=num_classes, act="softmax")
