"""Model zoo: the reference's benchmark + book-test model families, built on
the paddle_tpu layers API (reference configs: benchmark/paddle/image/{alexnet,
googlenet,resnet,vgg,smallnet_mnist_cifar}.py, benchmark/paddle/rnn/rnn.py,
python/paddle/v2/fluid/tests/book/*).

Each builder appends ops to the current default program and returns the
output variable(s); pair with ``paddle_tpu.optimizer`` and ``Executor`` for
training, or use the packaged ``build_*_trainer`` convenience wrappers.
"""
from .mnist import mlp as mnist_mlp, lenet as mnist_lenet
from .alexnet import alexnet
from .vgg import vgg16, vgg19, vgg_cifar
from .resnet import resnet_imagenet, resnet50, resnet_cifar
from .googlenet import googlenet
from .lstm_textcls import lstm_text_classification
from .seq2seq import seq2seq_attention, seq2seq_infer
from .wide_deep import wide_deep

__all__ = [
    "mnist_mlp", "mnist_lenet", "alexnet", "vgg16", "vgg19", "vgg_cifar",
    "resnet_imagenet", "resnet50", "resnet_cifar", "googlenet",
    "lstm_text_classification", "seq2seq_attention", "seq2seq_infer", "wide_deep",
]
