"""Wide & Deep CTR model (reference capability: sparse/CTR machinery —
SparseRowCpuMatrix embeddings SparseRowMatrix.h:31-260, SelectedRows grads,
lookup_table_op; BASELINE.json config "DeepFM / wide-deep CTR").

TPU design: every sparse feature is an embedding lookup (gather) whose
gradient XLA turns into a scatter-add — the SelectedRows path without a
parameter server.  For multi-chip, shard the embedding tables over the 'mp'
axis via Parameter.sharding (paddle_tpu.parallel.embedding).
"""
from __future__ import annotations

from .. import layers


def wide_deep(sparse_ids, dense_feat, vocab_sizes, emb_dim=16,
              deep_hidden=(64, 32)):
    """``sparse_ids``: list of int id tensors [B, 1]; ``dense_feat``:
    [B, D] float tensor; returns sigmoid CTR prediction [B, 1]."""
    # deep: concat embeddings + dense -> MLP
    embs = [layers.embedding(ids, size=[vs, emb_dim], is_sparse=True)
            for ids, vs in zip(sparse_ids, vocab_sizes)]
    deep = layers.concat(embs + [dense_feat], axis=1)
    for h in deep_hidden:
        deep = layers.fc(deep, size=h, act="relu")
    # wide: one scalar weight per sparse id (linear part) + dense linear
    wides = [layers.embedding(ids, size=[vs, 1], is_sparse=True)
             for ids, vs in zip(sparse_ids, vocab_sizes)]
    wide = layers.concat(wides + [dense_feat], axis=1)
    wide = layers.fc(wide, size=1)
    logit = layers.elementwise_add(layers.fc(deep, size=1), wide)
    return layers.sigmoid(logit)
