"""LSTM text classification (reference: benchmark/paddle/rnn/rnn.py —
embedding -> N stacked LSTMs -> last step -> fc softmax)."""
from __future__ import annotations

from .. import layers


def lstm_text_classification(data, vocab_size=30000, num_classes=2,
                             emb_dim=128, hidden_size=128, lstm_num=1):
    """``data`` is an int token tensor [B, T] (lod_level=1: pair with a
    ``<name>@LEN`` length feed for padded batches)."""
    net = layers.embedding(data, size=[vocab_size, emb_dim])
    for _ in range(lstm_num):
        proj = layers.fc(net, size=hidden_size * 4, num_flatten_dims=2)
        net, _ = layers.dynamic_lstm(proj, size=hidden_size * 4)
    last = layers.sequence_last_step(net)
    return layers.fc(last, size=num_classes, act="softmax")


def stacked_lstm_net(data, vocab_size, num_classes=2, emb_dim=128,
                     hidden_dim=512, stacked_num=3):
    """book test_understand_sentiment stacked_lstm_net: alternating-direction
    stacked LSTMs with max pooling."""
    emb = layers.embedding(data, size=[vocab_size, emb_dim])
    fc1 = layers.fc(emb, size=hidden_dim, num_flatten_dims=2)
    lstm1, _ = layers.dynamic_lstm(fc1, size=hidden_dim)
    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = layers.fc(layers.concat(inputs, axis=2), size=hidden_dim,
                       num_flatten_dims=2)
        lstm, _ = layers.dynamic_lstm(fc, size=hidden_dim,
                                      is_reverse=(i % 2) == 0)
        inputs = [fc, lstm]
    fc_last = layers.sequence_pool(inputs[0], "max")
    lstm_last = layers.sequence_pool(inputs[1], "max")
    return layers.fc([fc_last, lstm_last], size=num_classes, act="softmax")
