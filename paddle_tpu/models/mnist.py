"""MNIST models (reference: v1_api_demo/mnist/mnist_provider.py + api_train.py,
fluid/tests/book/test_recognize_digits_{mlp,conv}.py)."""
from __future__ import annotations

from .. import layers, nets


def mlp(img, hidden_sizes=(128, 64), num_classes=10):
    """3-layer MLP (book test_recognize_digits_mlp.py network)."""
    h = img
    for size in hidden_sizes:
        h = layers.fc(h, size=size, act="relu")
    return layers.fc(h, size=num_classes, act="softmax")


def lenet(img, num_classes=10):
    """conv-pool x2 + fc (book test_recognize_digits_conv.py network)."""
    conv1 = nets.simple_img_conv_pool(img, num_filters=20, filter_size=5,
                                      pool_size=2, pool_stride=2, act="relu")
    conv2 = nets.simple_img_conv_pool(conv1, num_filters=50, filter_size=5,
                                      pool_size=2, pool_stride=2, act="relu")
    return layers.fc(conv2, size=num_classes, act="softmax")
