"""Model persistence (reference: fluid/io.py:32-165 — save/load_vars/params/
persistables via save_op/load_op files-per-var; save_inference_model
serializing the pruned ProgramDesc).

Format: one ``<name>.npy`` per var in ``dirname`` (mirroring the reference's
file-per-parameter layout), program serialized as JSON (``__model__``).
Sharded/async checkpointing for training state lives in
paddle_tpu.distributed.checkpoint; this module is the simple synchronous
path.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from .core.program import Parameter, Program, Variable, default_main_program
from .core.scope import Scope, global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "get_inference_program",
]


def _san(name: str) -> str:
    return name.replace("/", "__")


def save_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, scope: Optional[Scope] = None):
    main_program = main_program or default_main_program()
    scope = global_scope() if scope is None else scope
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    os.makedirs(dirname, exist_ok=True)
    for v in vars:
        name = v.name if isinstance(v, Variable) else str(v)
        if not scope.has(name):
            continue
        np.save(os.path.join(dirname, _san(name) + ".npy"),
                np.asarray(scope.get(name)))


def _is_param(v):
    return isinstance(v, Parameter)


def _is_persistable(v):
    return v.persistable


def save_params(executor=None, dirname=None, main_program=None, scope=None):
    save_vars(executor, dirname, main_program, predicate=_is_param,
              scope=scope)


def save_persistables(executor=None, dirname=None, main_program=None,
                      scope=None):
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              scope=scope)


def load_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, scope: Optional[Scope] = None):
    import jax.numpy as jnp
    main_program = main_program or default_main_program()
    scope = global_scope() if scope is None else scope
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    for v in vars:
        name = v.name if isinstance(v, Variable) else str(v)
        path = os.path.join(dirname, _san(name) + ".npy")
        if os.path.exists(path):
            scope.set(name, jnp.asarray(np.load(path)))


def load_params(executor=None, dirname=None, main_program=None, scope=None):
    load_vars(executor, dirname, main_program, predicate=_is_param,
              scope=scope)


def load_persistables(executor=None, dirname=None, main_program=None,
                      scope=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              scope=scope)


def get_inference_program(target_vars, main_program=None) -> Program:
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    return main_program.prune(target_vars)


def save_inference_model(dirname, feeded_var_names: List[str], target_vars,
                         executor=None, main_program=None, scope=None):
    """Prune to the inference slice and persist program+params
    (reference: fluid/io.py:165)."""
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    pruned = main_program.prune(target_vars)
    pruned = pruned.clone(for_test=True)
    os.makedirs(dirname, exist_ok=True)
    meta = {
        "program": pruned.to_dict(),
        "feed_var_names": list(feeded_var_names),
        "fetch_var_names": [t.name if isinstance(t, Variable) else str(t)
                            for t in target_vars],
    }
    with open(os.path.join(dirname, "__model__"), "w") as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, main_program, scope=scope)


def load_inference_model(dirname, executor=None, scope=None):
    with open(os.path.join(dirname, "__model__")) as f:
        meta = json.load(f)
    program = Program.from_dict(meta["program"])
    load_persistables(executor, dirname, program, scope=scope)
    fetch_vars = [program.global_block().var(n)
                  for n in meta["fetch_var_names"]
                  if program.global_block().has_var(n)]
    return program, meta["feed_var_names"], fetch_vars
