"""Trainer CLI: the ``paddle train`` analog (reference:
trainer/TrainerMain.cpp — FLAGS_job one of train/test/checkgrad/time,
trainer.init(config) + ParamUtil save/load).

``python -m paddle_tpu --config=conf.py --job=train`` evaluates a v1 config
file verbatim (trainer_config_helpers DSL), builds the optimizer from its
settings(), and runs the requested job on the TPU runtime:

  train      steps over feeds, prints per-pass loss, saves params
  test       loads params, evaluates the config outputs on feeds
  time       TrainerMain's timing job: one untimed compiled window
             (compile+warmup), one timed window, ms/batch
  checkgrad  numeric-vs-autodiff gradient check on the config's cost

``python -m paddle_tpu check prog.json`` is the subcommand form of the
static program verifier (paddle_tpu.analysis): it loads a serialized
program — ``Program.to_json`` output, a ``save_inference_model``
``__model__`` meta, or a directory containing one — runs all passes, and
prints the ``PT0xx`` report (exit 1 on errors, and on warnings too with
``--strict``).  ``--mesh dp=8,mp=2`` enables the sharding lints; with a
v1 config (``check --config conf.py``) it verifies the built main and
startup programs instead.

``python -m paddle_tpu plan prog.json --mesh dp=8`` runs the static
auto-sharding planner (paddle_tpu.analysis.planner): it prints proposed
``param_specs``/``feed_specs`` for the mesh, the static cost breakdown
and the per-device peak-HBM estimate, and ``--out plan.json`` writes a
plan file that ``check --specs plan.json`` can later re-validate against
the program — a CI gate needing no Python config import.

``python -m paddle_tpu serve --model dir`` runs the production serving
runtime (paddle_tpu.serving) over exported StableHLO artifacts: dynamic
batching with admission control, per-request deadlines, load shedding,
per-model circuit breaking, and graceful SIGTERM drain — one JSON object
per line on stdin/stdout (see serving/cli.py for the protocol), or over
HTTP with ``--http PORT``.

``python -m paddle_tpu fleet --model dir --replicas N --http PORT``
scales that horizontally: N supervised serve replicas behind a
queue-depth-aware router and the HTTP front, with bounded-restart
relaunch of dead replicas and optional metric-driven autoscaling
(serving/fleet.py).

``python -m paddle_tpu elastic --config conf.py --data 'parts/*' --workers
K --root dir`` runs the elastic multi-worker training service
(distributed/elastic.py): K supervised trainer processes over the
master's slot-sharded exactly-once streams, die/rejoin with
bit-identical resume, and checkpointed mesh RESIZE on membership change
(drain -> merge replicas -> planner re-plan -> re-shard -> relaunch).

Feeds come from ``--feed-npz`` (named arrays matching the config's data
layers, with ``name@LEN`` companions for sequences); ``time`` and
``checkgrad`` synthesize random feeds from the declared shapes when none
are given (the reference's fake-data provider role).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, Optional

import numpy as np


def _parse_config_args(s: Optional[str]) -> Dict[str, str]:
    if not s:
        return {}
    out = {}
    for kv in s.split(","):
        k, _, v = kv.partition("=")
        out[k.strip()] = v.strip()
    return out


def _load_feeds(path: Optional[str]):
    if not path:
        return None
    data = np.load(path, allow_pickle=False)
    return {k: data[k] for k in data.files}


def _synth_feeds(cfg, batch: int, seed: int = 0, seq_len: int = 12):
    """Random feeds shaped from the config's data layers (the fake-data
    provider TrainerMain's time job leaned on)."""
    rng = np.random.RandomState(seed)
    feeds = {}
    for name, v in cfg.data_layers.items():
        if v.dtype == np.dtype("int64"):
            vocab = getattr(v, "v1_size", None) or 2
            if v.lod_level:
                T = seq_len
                feeds[name] = rng.randint(0, vocab, (batch, T))
                feeds[name + "@LEN"] = np.full(batch, T)
            else:
                # label-style: v1 size is the number of classes
                feeds[name] = rng.randint(0, max(vocab, 2), (batch, 1))
        else:
            dims = [int(d) for d in (v.shape or (1,))[1:] if d and d > 0]
            feeds[name] = rng.rand(batch, *dims).astype("float32")
    return feeds


def _used_feed_names(cfg):
    """Data layers actually consumed by ops (a config may declare inputs
    the network never reads, e.g. rnn_crf's 'features')."""
    used = set()
    for op in cfg.main_program.global_block().ops:
        for names in op.inputs.values():
            used.update(names)
    out = set()
    for n in cfg.data_layers:
        if n in used:
            out.add(n)
            out.add(n + "@LEN")
    return out


def job_train(cfg, exe, feeds, args):
    import paddle_tpu as pt

    loss = cfg.minimize_outputs()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    if args.init_model_path:
        pt.load_persistables(exe, args.init_model_path, cfg.main_program)
    steps = args.steps_per_pass
    # --start_pass resume semantics (Flags.cpp:81, TrainerMain.cpp:25):
    # saved pass dirs keep their true index; num_passes is the TOTAL pass
    # index bound, so resuming past it is a usage error, not a no-op
    if not 0 <= args.start_pass < args.num_passes:
        raise SystemExit(
            f"--start_pass={args.start_pass} must be in [0, "
            f"--num_passes={args.num_passes}) — num_passes is the total "
            f"pass count, not additional passes")
    for p in range(args.start_pass, args.num_passes):
        # one compiled dispatch per pass (device-side scan over the steps)
        (vals,) = exe.run_steps(steps, cfg.main_program, feed=feeds,
                                fetch_list=[loss])
        vals = np.asarray(vals).reshape(-1)
        print(json.dumps({"pass": p, "loss": float(vals[-1]),
                          "mean_loss": float(np.mean(vals))}), flush=True)
        if args.save_dir:
            d = os.path.join(args.save_dir, f"pass-{p:05d}")
            os.makedirs(d, exist_ok=True)
            pt.save_persistables(exe, d, cfg.main_program)
    return 0


def job_test(cfg, exe, feeds, args):
    import paddle_tpu as pt

    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    if args.init_model_path:
        pt.load_persistables(exe, args.init_model_path, cfg.main_program)
    outs = exe.run(cfg.main_program, feed=feeds, fetch_list=cfg.outputs,
                   is_test=True)
    for var, val in zip(cfg.outputs, outs):
        name = getattr(var, "name", str(var))
        print(json.dumps({"output": name,
                          "mean": float(np.mean(val)),
                          "shape": list(np.shape(val))}), flush=True)
    return 0


def job_time(cfg, exe, feeds, args):
    """TrainerMain's timing job with the compiled-window methodology
    (benchmark/RESULTS.md): the timed window is ONE run_steps dispatch, so
    host dispatch latency is out of the measurement."""
    cfg.minimize_outputs()
    loss = cfg.outputs[0]
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    # the untimed first call MUST use the same num_steps as the timed one:
    # run_steps compiles per scan length, so it is the compile + warmup
    (lv,) = exe.run_steps(args.iters, cfg.main_program, feed=feeds,
                          fetch_list=[loss], return_numpy=False)
    # unconditional materialization = the sync barrier (an assert would
    # vanish under python -O and the window would time async dispatch)
    if not np.isfinite(np.asarray(lv)[-1]):
        raise FloatingPointError("non-finite loss during warmup window")
    t0 = time.perf_counter()
    (lv,) = exe.run_steps(args.iters, cfg.main_program, feed=feeds,
                          fetch_list=[loss], return_numpy=False)
    last = float(np.asarray(lv)[-1])
    dt = (time.perf_counter() - t0) / args.iters
    if not np.isfinite(last):
        raise FloatingPointError("non-finite loss during timed window")
    print(json.dumps({"ms_per_batch": round(dt * 1e3, 3),
                      "batches_per_sec": round(1.0 / dt, 2)}), flush=True)
    return 0


def job_checkgrad(cfg, exe, feeds, args, eps=1e-4, rtol=1e-3):
    """Central-difference vs autodiff on the config's cost (Trainer::
    checkGradient): perturb a few elements of the first parameters.
    Backward ONLY — no optimizer ops, so probe runs don't move the
    weights they are probing.

    Precision instrument (round 5): the whole comparison runs in FLOAT64
    on the CPU backend (main() pins the platform before the backend
    initializes; ``Executor(compute_dtype="float64")`` upcasts the step) —
    at eps=1e-4 the f64 central difference is accurate to ~1e-8, so the
    1e-3 tolerance actually tests the lowerings, matching the double-
    precision rigor of the reference's checkgrad job."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu.backward import append_backward
    from paddle_tpu.core.program import grad_var_name, program_guard

    if jax.config.jax_enable_x64 and jax.default_backend() == "cpu":
        exe = pt.Executor(compute_dtype="float64")
    else:                                  # pragma: no cover - fallback
        eps, rtol = 1e-3, 5e-2
        print(json.dumps({"warning": "x64 unavailable; f32 checkgrad at "
                          f"rtol={rtol}"}), flush=True)

    loss = cfg.outputs[0]
    with program_guard(cfg.main_program, cfg.startup_program):
        append_backward(loss)
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    scope = pt.global_scope()
    params = [v.name for v in
              cfg.main_program.global_block().vars.values()
              if v.persistable and scope.has(v.name) and
              np.asarray(scope.get(v.name)).dtype.kind == "f"][:3]
    if not params:
        print(json.dumps({"checkgrad": "FAIL",
                          "error": "no floating parameters found"}),
              flush=True)
        return 1
    failures = 0
    rng = np.random.RandomState(0)
    for pname in params:
        g, = exe.run(cfg.main_program, feed=feeds,
                     fetch_list=[grad_var_name(pname)])
        w0 = np.array(scope.get(pname))
        flat = w0.ravel()
        for idx in rng.choice(flat.size, size=min(3, flat.size),
                              replace=False):
            for sign, store in ((+1, "hi"), (-1, "lo")):
                w = flat.copy()
                w[idx] += sign * eps
                scope.set(pname, w.reshape(w0.shape))
                val = float(exe.run(cfg.main_program, feed=feeds,
                                    fetch_list=[loss], is_test=False)[0])
                if store == "hi":
                    hi = val
                else:
                    lo = val
            scope.set(pname, w0)
            num = (hi - lo) / (2 * eps)
            ana = float(np.asarray(g).ravel()[idx])
            ok = abs(num - ana) <= rtol * max(1.0, abs(num), abs(ana))
            if not ok:
                failures += 1
            print(json.dumps({"param": pname, "index": int(idx),
                              "numeric": num, "autodiff": ana,
                              "ok": bool(ok)}), flush=True)
    print(json.dumps({"checkgrad": "PASS" if failures == 0 else "FAIL",
                      "failures": failures}), flush=True)
    return 0 if failures == 0 else 1


def _parse_mesh(s: Optional[str]) -> Optional[Dict[str, int]]:
    """'dp=8,mp=2' -> {'dp': 8, 'mp': 2} for the sharding lints."""
    if not s:
        return None
    out: Dict[str, int] = {}
    for kv in s.split(","):
        k, _, v = kv.partition("=")
        try:
            size = int(v)
        except ValueError:
            raise SystemExit(f"--mesh: bad axis entry {kv!r} "
                             f"(want name=size,...)")
        if size < 1:
            # size <= 1 axes are skipped by the divisibility lints, so a
            # typo'd dp=0 would silently validate nothing and PASS
            raise SystemExit(f"--mesh: axis size must be >= 1, got {kv!r}")
        k = k.strip()
        if k in out:
            # dp=8,dp=2 (typo for dp=8,mp=2) would silently lint against
            # the last size only
            raise SystemExit(f"--mesh: duplicate axis {k!r}")
        out[k] = size
    return out


def _load_check_target(path: str):
    """(program, fetch_names) from a program JSON / __model__ meta / dir."""
    from paddle_tpu.core.program import Program

    if os.path.isdir(path):
        path = os.path.join(path, "__model__")
    try:
        with open(path) as f:
            d = json.load(f)
    except OSError as e:
        raise SystemExit(f"check: cannot read program {path!r}: {e}")
    except json.JSONDecodeError as e:
        raise SystemExit(f"check: {path!r} is not a program JSON "
                         f"(Program.to_json or save_inference_model "
                         f"__model__): {e}")
    try:
        if "program" in d:     # save_inference_model meta
            return Program.from_dict(d["program"]), d.get("fetch_var_names")
        return Program.from_dict(d), None
    except (KeyError, TypeError, ValueError) as e:
        raise SystemExit(f"check: {path!r} does not deserialize as a "
                         f"Program: {type(e).__name__}: {e}")


def _load_plan_file(path: str):
    """plan.json (analysis.planner.Plan.to_dict output) -> Plan."""
    from paddle_tpu.analysis.planner import Plan

    try:
        with open(path) as f:
            d = json.load(f)
    except OSError as e:
        raise SystemExit(f"check: cannot read plan {path!r}: {e}")
    except json.JSONDecodeError as e:
        raise SystemExit(f"check: {path!r} is not a plan JSON "
                         f"(paddle_tpu plan --out output): {e}")
    try:
        return Plan.from_dict(d)
    except (KeyError, TypeError, ValueError) as e:
        raise SystemExit(f"check: {path!r} does not deserialize as a "
                         f"sharding plan: {type(e).__name__}: {e}")


def job_check(argv):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu check",
        description="static program verifier: shape/dtype inference, "
                    "well-formedness and graph lints with stable PT0xx "
                    "codes (the desc-layer InferShape analog; see "
                    "paddle_tpu.analysis)")
    ap.add_argument("program", nargs="?", default=None,
                    help="Program.to_json file, save_inference_model "
                         "__model__ meta, or a directory containing one")
    ap.add_argument("--config", default=None,
                    help="verify a v1 config's built programs instead")
    ap.add_argument("--config_args", default=None,
                    help="k=v,... forwarded to get_config_arg")
    ap.add_argument("--mesh", default=None,
                    help="axis=size,... — enables the sharding lints "
                         "(PT030/PT031/PT040) against this mesh")
    ap.add_argument("--specs", default=None,
                    help="plan.json (from `paddle_tpu plan --out`): "
                         "validate its param/feed specs against the "
                         "program — a CI gate for a committed plan; the "
                         "plan's own mesh applies when --mesh is omitted")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings too")
    ap.add_argument("--concurrency", action="store_true",
                    help="run the PT05x lock-discipline pass over the "
                         "paddle_tpu host source tree instead of a "
                         "program (analysis.concurrency): findings "
                         "beyond the frozen baseline fail the check")
    args = ap.parse_args(argv)
    if args.concurrency:
        if args.program is not None or args.config is not None:
            ap.error("--concurrency analyzes the host source tree; "
                     "it takes no program/--config")
        from paddle_tpu.analysis import concurrency as _cc
        findings = _cc.analyze_package()
        new, suppressed, stale = _cc.apply_baseline(findings)
        print(_cc.render_report(findings), flush=True)
        warn_new = [f for f in new
                    if _cc.CODES[f.code][0] != "error"]
        err_new = [f for f in new if _cc.CODES[f.code][0] == "error"]
        failed = bool(err_new or stale
                      or (args.strict and warn_new))
        print(json.dumps({"check": "FAIL" if failed else "PASS",
                          "findings": len(findings),
                          "new": len(new), "stale": len(stale),
                          "baselined": sum(suppressed.values())}),
              flush=True)
        return 1 if failed else 0
    if (args.program is None) == (args.config is None):
        ap.error("give exactly one of a program file or --config")

    mesh = _parse_mesh(args.mesh)
    param_specs = feed_specs = None
    if args.specs is not None:
        plan_obj = _load_plan_file(args.specs)
        param_specs = plan_obj.param_specs
        feed_specs = plan_obj.feed_specs
        if mesh is None:
            mesh = plan_obj.mesh_axes
    targets = []                 # (label, program, fetch_list)
    if args.config is not None:
        from paddle_tpu.trainer_config_helpers import load_v1_config
        cfg = load_v1_config(args.config,
                             **_parse_config_args(args.config_args))
        targets.append(("main", cfg.main_program, cfg.outputs))
        targets.append(("startup", cfg.startup_program, None))
    else:
        program, fetch_names = _load_check_target(args.program)
        targets.append((args.program, program, fetch_names))

    errors = warnings_ = 0
    for label, program, fetch_list in targets:
        report = program.validate(fetch_list=fetch_list, mesh=mesh,
                                  param_specs=param_specs,
                                  feed_specs=feed_specs)
        errors += len(report.errors)
        warnings_ += len(report.warnings)
        print(f"== {label}: {report.render()}", flush=True)
    print(json.dumps({"check": "FAIL" if errors or
                      (args.strict and warnings_) else "PASS",
                      "errors": errors, "warnings": warnings_}),
          flush=True)
    return 1 if errors or (args.strict and warnings_) else 0


def job_plan(argv):
    """Auto-sharding planner CLI: propose specs for a program + mesh."""
    ap = argparse.ArgumentParser(
        prog="paddle_tpu plan",
        description="static auto-sharding planner "
                    "(paddle_tpu.analysis.planner): propose "
                    "param_specs/feed_specs for a serialized program and "
                    "a mesh, print the cost breakdown and the per-device "
                    "peak-HBM estimate — pure static analysis, no chip "
                    "required.  The emitted plan passes the PT030/PT031 "
                    "sharding lints by construction; validate a committed "
                    "plan later with `paddle_tpu check prog.json --specs "
                    "plan.json`.")
    ap.add_argument("program",
                    help="Program.to_json file, save_inference_model "
                         "__model__ meta, or a directory containing one")
    ap.add_argument("--mesh", required=True,
                    help="axis=size,... (e.g. dp=8 or dp=4,tp=2)")
    ap.add_argument("--batch", type=int, default=64,
                    help="batch assumed for symbolic -1 dims in the cost "
                         "model (default 64)")
    ap.add_argument("--batch-axis", default="dp",
                    help="mesh axis feeds shard their batch dim on "
                         "(default dp)")
    ap.add_argument("--json", action="store_true",
                    help="print the plan as ONE JSON object only")
    ap.add_argument("--out", default=None,
                    help="also write the plan JSON to this file")
    ap.add_argument("--calibration", default=None,
                    help="opprof calibration table (doctor/profile "
                         "--calibration-out output): rank candidates "
                         "with its per-op-class measured/predicted "
                         "ratios instead of the nominal constants alone")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import planner

    mesh = _parse_mesh(args.mesh)
    program, _fetch_names = _load_check_target(args.program)
    ratios = None
    if args.calibration:
        from paddle_tpu.observability import attribution
        try:
            ratios = attribution.load_op_class_ratios(args.calibration)
        except (OSError, ValueError) as e:
            raise SystemExit(f"plan: cannot load calibration "
                             f"{args.calibration!r}: {e}")
        if not ratios:
            # stderr: --json promises ONE JSON object on stdout
            print("plan: calibration table has no op-class rows; "
                  "ranking on nominal constants", file=sys.stderr,
                  flush=True)
    try:
        plan_obj = planner.plan(program, mesh, batch_axis=args.batch_axis,
                                assume_batch=args.batch,
                                op_class_ratios=ratios)
    except ValueError as e:
        raise SystemExit(f"plan: {e}")
    if args.out:
        try:
            with open(args.out, "w") as f:
                f.write(plan_obj.to_json())
        except OSError as e:
            raise SystemExit(f"plan: cannot write {args.out!r}: {e}")
    if args.json:
        print(json.dumps(plan_obj.to_dict(), sort_keys=True), flush=True)
    else:
        print(plan_obj.render(), flush=True)
        print(json.dumps({"plan": "OK", "candidate": plan_obj.candidate,
                          "params_sharded": len(plan_obj.param_specs),
                          "feeds_sharded": len(plan_obj.feed_specs)}),
              flush=True)
    return 0


def job_tune(argv):
    """Persistent-autotuner CLI: search one tunable's declared space and
    commit the winner for trace-time replay."""
    ap = argparse.ArgumentParser(
        prog="paddle_tpu tune",
        description="persistent autotuner (paddle_tpu.tuning): search a "
                    "registered tunable's declared space on its built-in "
                    "measurement target (grid or successive halving, "
                    "paired-A/B noise gate), and persist the winner under "
                    "<cache_dir>/tuning/ for trace-time replay via the "
                    "autotune opt-ins (Executor(autotune=True), "
                    "Trainer.train(autotune=True), PADDLE_TPU_AUTOTUNE=1)."
                    "  Device-side targets on a host without the "
                    "accelerator report their pending-hardware stub and "
                    "pre-registered decision rule instead of searching.")
    ap.add_argument("target", nargs="?", default=None,
                    help="tunable name (e.g. executor/run_pipelined); "
                         "omit with --list to enumerate")
    ap.add_argument("--list", action="store_true",
                    help="list registered tunables (spaces, defaults, "
                         "decision rules) and exit")
    ap.add_argument("--algo", default="grid", choices=["grid", "halving"],
                    help="search algorithm (default grid; halving for "
                         "large spaces under a tight budget)")
    ap.add_argument("--budget", type=int, default=None,
                    help="max configs evaluated (default: the full grid; "
                         "the shipped default config is always included)")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed windows per trial (median scores; "
                         "default 3)")
    ap.add_argument("--pairs", type=int, default=5,
                    help="alternating default/candidate pairs in the "
                         "final A/B (median of per-pair ratios; default 5)")
    ap.add_argument("--min-speedup", type=float, default=1.10,
                    help="noise-gate threshold on the median pair ratio "
                         "(default 1.10)")
    ap.add_argument("--trial-timeout-s", type=float, default=120.0,
                    help="soft per-trial budget; overruns record "
                         "'timeout' and the search continues (default "
                         "120)")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-fast target sizes (path check; winners "
                         "from smoke runs are still persisted — use "
                         "--no-save)")
    ap.add_argument("--cache-dir", default=None,
                    help="winner store root (default: the cache_dir flag "
                         "/ PADDLE_TPU_CACHE_DIR; records land under "
                         "<dir>/tuning/)")
    ap.add_argument("--no-save", action="store_true",
                    help="search and report only; do not persist a "
                         "winner")
    ap.add_argument("--out", default=None,
                    help="also write the full result document (trial "
                         "table, A/B windows, verdict) to this JSON file")
    args = ap.parse_args(argv)

    from paddle_tpu.core.registry import get_tunable, registered_tunables
    from paddle_tpu.tuning import search, targets, tunables

    if args.list or args.target is None:
        if not args.list and args.target is None:
            ap.error("give a tunable name, or --list")
        # surface lazily-imported subsystems' declarations too
        for t in targets.target_names():
            targets.ensure_registered(t)
        for n in registered_tunables():
            has_target = n in targets.TARGETS
            print(tunables.describe(n)
                  + ("" if has_target else "\n  (no built-in target — "
                     "library use via paddle_tpu.tuning.tune)"),
                  flush=True)
            print(flush=True)
        return 0

    name = args.target
    targets.ensure_registered(name)
    try:
        entry = get_tunable(name)
    except KeyError as e:
        raise SystemExit(f"tune: {e}")
    import jax
    if entry["side"] == "device" and jax.default_backend() == "cpu":
        doc = search.pending_stub(name)
    else:
        if not args.no_save:
            # fail BEFORE the multi-minute search, not after: an
            # accepted winner with nowhere to persist would silently
            # make the documented search-then-replay workflow a no-op
            from paddle_tpu.tuning import store as _store
            if not _store.store_dir(args.cache_dir):
                raise SystemExit(
                    "tune: no winner store configured — set "
                    "PADDLE_TPU_CACHE_DIR (or the cache_dir flag), pass "
                    "--cache-dir DIR, or run with --no-save to search "
                    "without persisting")
        try:
            measure = targets.build_target(name, smoke=args.smoke)
        except KeyError as e:
            raise SystemExit(f"tune: {e}")

        def on_trial(t):
            print(json.dumps({"trial": t.config, "status": t.status,
                              "seconds": t.seconds,
                              "spread_pct": t.spread_pct,
                              "error": t.error}), flush=True)

        doc = search.tune(
            name, measure, algo=args.algo, budget=args.budget,
            reps=args.reps, pairs=args.pairs,
            min_speedup=args.min_speedup,
            trial_timeout_s=args.trial_timeout_s,
            save=not args.no_save, base=args.cache_dir,
            on_trial=on_trial)
    if args.out:
        try:
            with open(args.out, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
        except OSError as e:
            raise SystemExit(f"tune: cannot write {args.out!r}: {e}")
    # one summary object on the last line (the trial table is in --out)
    summary = {k: doc.get(k) for k in
               ("tunable", "status", "winner", "record_path",
                "decision_rule")
               if doc.get(k) is not None}
    if "ab" in doc:
        summary["speedup"] = doc["ab"]["speedup"]
        summary["pair_ratios"] = doc["ab"]["pair_ratios"]
        if doc["ab"]["refusal_reason"]:
            summary["refusal_reason"] = doc["ab"]["refusal_reason"]
    print(json.dumps(summary, sort_keys=True), flush=True)
    return 0


def job_stats(argv):
    """Summarize JSONL observability logs (PADDLE_TPU_METRICS_LOG)."""
    ap = argparse.ArgumentParser(
        prog="paddle_tpu stats",
        description="summarize one or more structured observability "
                    "logs (paddle_tpu.observability, flag metrics_log / "
                    "env PADDLE_TPU_METRICS_LOG): step-time statistics, "
                    "pipeline stall/busy numbers, last metrics snapshot, "
                    "NaN events.  Multiple files (a supervised run's "
                    "per-relaunch logs) merge in time order with restart "
                    "boundaries marked.")
    ap.add_argument("log", nargs="+", help="JSONL metrics log file(s)")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as ONE JSON object only")
    ap.add_argument("--prom", action="store_true",
                    help="print the logs' LAST metrics snapshot in "
                         "Prometheus text exposition format (scrape a "
                         "serving deployment without a new dependency) "
                         "and exit")
    args = ap.parse_args(argv)
    from paddle_tpu.observability import export
    if args.prom:
        try:
            events, _files = export.iter_log_events(args.log)
        except OSError as e:
            raise SystemExit(f"stats: cannot read log: {e}")
        snap = next((e for e in reversed(events)
                     if e.get("kind") == "snapshot"), None)
        if snap is None:
            raise SystemExit(
                "stats --prom: no snapshot events in the log — run with "
                "observe on and periodic reports (log_period), or call "
                "observability.periodic_report()")
        print(export.to_prometheus(snap), end="", flush=True)
        return 0
    try:
        summary = export.summarize_logs(args.log)
    except OSError as e:
        raise SystemExit(f"stats: cannot read log: {e}")
    if not args.json:
        print(export.render_summary(summary), flush=True)
    print(json.dumps(summary, default=repr), flush=True)
    return 0


def job_trace(argv):
    """Reconstruct per-trace timelines from a span-carrying JSONL log."""
    ap = argparse.ArgumentParser(
        prog="paddle_tpu trace",
        description="replay the tracing spans of one or more "
                    "observability logs (paddle_tpu.observability."
                    "tracing): per-trace timelines, the critical path of "
                    "the longest trace, and p50/p99 latency by span "
                    "name.  Multiple files merge in time order (a "
                    "resumed job's logs read as one).")
    ap.add_argument("log", nargs="+", help="JSONL metrics log file(s)")
    ap.add_argument("--json", action="store_true",
                    help="print ONE JSON object only")
    ap.add_argument("--limit", type=int, default=5,
                    help="timelines rendered (largest traces first; "
                         "default 5)")
    args = ap.parse_args(argv)
    from paddle_tpu.observability import export, tracing
    try:
        events, files = export.iter_log_events(args.log)
    except OSError as e:
        raise SystemExit(f"trace: cannot read log: {e}")
    traces = tracing.build_traces(events)
    stats = tracing.span_stats(events)
    if args.json:
        print(json.dumps({
            "files": files, "traces": len(traces), "span_stats": stats,
            "critical_path": [
                {"name": s["name"], "dur_ms": s.get("dur_ms")}
                for s in tracing.critical_path(
                    max(traces, key=lambda t: t["dur_ms"]))]
            if traces else [],
        }, default=repr), flush=True)
        return 0
    if not traces:
        print("no spans in this log — run with observe on and a "
              "metrics_log set", flush=True)
        return 0
    print(f"{len(traces)} trace(s), {sum(len(t['spans']) for t in traces)}"
          f" span(s)", flush=True)
    if len(files) > 1:
        for f in files:
            # [role:index] when the log stamped identity — a merged
            # fleet trace names which process each file came from
            print(f"  restart boundary: [{export.source_label(f)}] "
                  f"{f['file']} ({f['events']} event(s), from "
                  f"ts={f['t_first']})", flush=True)
    print("\nby span name:", flush=True)
    for name, s in stats.items():
        print(f"  {name}: count={s['count']} p50={s['p50_ms']}ms "
              f"p99={s['p99_ms']}ms max={s['max_ms']}ms "
              f"total={s['total_ms']}ms", flush=True)
    big = sorted(traces, key=lambda t: -t["dur_ms"])[:args.limit]
    for t in big:
        print("\n" + tracing.render_trace(t), flush=True)
    longest = max(traces, key=lambda t: t["dur_ms"])
    cp = tracing.critical_path(longest)
    print("\ncritical path of the longest trace "
          f"({longest['trace']}):", flush=True)
    for s in cp:
        print(f"  {s['name']} ({s.get('dur_ms', 0.0)} ms)", flush=True)
    return 0


def job_doctor(argv):
    """Measured-vs-modeled step/request budget: where did the time go."""
    ap = argparse.ArgumentParser(
        prog="paddle_tpu doctor",
        description="explain where the step (or request) time went: a "
                    "budget decomposing the measured wall into compute / "
                    "fetch / compile / staging / host-stall from the "
                    "log's step events and spans, the top bottleneck "
                    "with actionable hints, and — with --program — a "
                    "cost-model calibration row (predicted vs measured, "
                    "stored ratio for the planner; ROADMAP item 2).  "
                    "Budget components reconcile with the measured wall "
                    "within the pinned tolerance or the report says so.")
    ap.add_argument("log", nargs="+", help="JSONL metrics log file(s)")
    ap.add_argument("--program", default=None,
                    help="Program.to_json file / __model__ meta / dir: "
                         "confront the static cost model with this run")
    ap.add_argument("--batch", type=int, default=64,
                    help="batch assumed for symbolic -1 dims in the "
                         "static model (default 64)")
    ap.add_argument("--mesh", default=None,
                    help="axis=size,... the measured run was sharded "
                         "over (folds into the prediction)")
    ap.add_argument("--calibration-out", default=None,
                    help="merge the calibration row into this JSON "
                         "table (keyed by program digest; the planner-"
                         "consumable store).  With --per-op the per-"
                         "op-class rows merge into the same table")
    ap.add_argument("--per-op", action="store_true",
                    help="also run the eager per-op profiler "
                         "(observability.opprof) on --program and join "
                         "its measured/modeled table under the step "
                         "budget — op-level 'where does XLA lose'")
    ap.add_argument("--json", action="store_true",
                    help="print ONE JSON object only")
    args = ap.parse_args(argv)
    from paddle_tpu.observability import attribution
    program = None
    if args.program is not None:
        program, _fetch = _load_check_target(args.program)
    if args.per_op and program is None:
        ap.error("--per-op needs --program (the eager profiler replays "
                 "the program op by op)")
    try:
        report = attribution.doctor_report(
            args.log, program=program, assume_batch=args.batch,
            mesh_axes=_parse_mesh(args.mesh))
    except OSError as e:
        raise SystemExit(f"doctor: cannot read log: {e}")
    per_op = None
    if args.per_op:
        from paddle_tpu.observability import opprof
        per_op = opprof.profile_program(
            program, batch=args.batch, mesh_axes=_parse_mesh(args.mesh))
        report["per_op"] = per_op
    if args.calibration_out:
        try:
            if report.get("calibration"):
                attribution.save_calibration([report["calibration"]],
                                             args.calibration_out)
            if per_op is not None and per_op.get("op_classes"):
                attribution.save_op_class_calibration(
                    per_op["op_classes"], args.calibration_out)
        except OSError as e:
            raise SystemExit(
                f"doctor: cannot write {args.calibration_out!r}: {e}")
    if not args.json:
        print(attribution.render_doctor(report), flush=True)
        if per_op is not None:
            from paddle_tpu.observability import opprof
            print(opprof.render_profile(per_op), flush=True)
    print(json.dumps(report, default=repr), flush=True)
    return 0


def job_profile(argv):
    """Per-op runtime profiler: measured vs modeled, op by op."""
    ap = argparse.ArgumentParser(
        prog="paddle_tpu profile",
        description="eager per-op profiler + HBM timeline "
                    "(paddle_tpu.observability.opprof): replay one step "
                    "of a program op by op with host timers at the "
                    "compiled step's exact precision, join each op "
                    "against the static cost model's FLOPs/HBM "
                    "estimates (roofline verdict, measured/predicted "
                    "ratio), rank the 'XLA loses here' op classes "
                    "naming the pre-registered Pallas candidates, and "
                    "walk the liveness order for the measured live-"
                    "bytes curve vs the modeled per-device peak.  The "
                    "per-op table must sum to the eager-replay total "
                    "within the pinned tolerance or the report says "
                    "so.  --calibration-out commits the per-op-class "
                    "calibration table `paddle_tpu plan --calibration` "
                    "consumes.")
    ap.add_argument("program", nargs="?", default=None,
                    help="Program.to_json file, save_inference_model "
                         "__model__ meta, or a directory containing one")
    ap.add_argument("--config", default=None,
                    help="profile a v1 config's TRAINING step instead "
                         "(minimize_outputs + startup-initialized "
                         "parameters)")
    ap.add_argument("--config_args", default=None,
                    help="k=v,... forwarded to get_config_arg")
    ap.add_argument("--batch", type=int, default=64,
                    help="batch for synthesized feeds and the static "
                         "model's symbolic -1 dims (default 64)")
    ap.add_argument("--seq-len", type=int, default=8,
                    help="synthesized sequence length for lod feeds "
                         "(default 8)")
    ap.add_argument("--reps", type=int, default=2,
                    help="timed windows per op (median; default 2)")
    ap.add_argument("--warmup", type=int, default=1,
                    help="discarded warmup windows per op (default 1)")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the rendered top-ops table "
                         "(default 10)")
    ap.add_argument("--mesh", default=None,
                    help="axis=size,... folded into the static model's "
                         "per-device estimates")
    ap.add_argument("--is-test", action="store_true",
                    help="profile the inference form of the step")
    ap.add_argument("--compiled-check", action="store_true",
                    help="also AOT-compile the step and cross-check "
                         "the memory view against the executable's "
                         "memory_analysis (where this jax exposes it)")
    ap.add_argument("--json", action="store_true",
                    help="print ONE JSON object only")
    ap.add_argument("--calibration-out", default=None,
                    help="merge the per-op-class calibration rows into "
                         "this JSON table (the planner-consumable "
                         "store; `paddle_tpu plan --calibration`)")
    args = ap.parse_args(argv)
    if (args.program is None) == (args.config is None):
        ap.error("give exactly one of a program file or --config")

    from paddle_tpu.observability import opprof

    kw = dict(batch=args.batch, seq_len=args.seq_len, reps=args.reps,
              warmup=args.warmup, top=args.top, is_test=args.is_test,
              mesh_axes=_parse_mesh(args.mesh),
              compiled_check=args.compiled_check)
    if args.config is not None:
        import paddle_tpu as pt
        from paddle_tpu.trainer_config_helpers import load_v1_config
        cfg = load_v1_config(args.config,
                             **_parse_config_args(args.config_args))
        cfg.minimize_outputs()
        exe = pt.Executor()
        exe.run(cfg.startup_program, feed={}, fetch_list=[])
        feeds = _synth_feeds(cfg, args.batch, seq_len=args.seq_len)
        used = _used_feed_names(cfg)
        feeds = {k: v for k, v in feeds.items() if k in used}
        report = opprof.profile_program(cfg.main_program, executor=exe,
                                        feed=feeds, **kw)
    else:
        program, _fetch = _load_check_target(args.program)
        report = opprof.profile_program(program, **kw)
    if args.calibration_out and report.get("op_classes"):
        from paddle_tpu.observability import attribution
        try:
            attribution.save_op_class_calibration(
                report["op_classes"], args.calibration_out)
        except OSError as e:
            raise SystemExit(
                f"profile: cannot write {args.calibration_out!r}: {e}")
    if not args.json:
        print(opprof.render_profile(report, top=args.top), flush=True)
    print(json.dumps(report, default=repr), flush=True)
    return 0


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "check":
        return job_check(argv[1:])
    if argv and argv[0] == "plan":
        return job_plan(argv[1:])
    if argv and argv[0] == "stats":
        return job_stats(argv[1:])
    if argv and argv[0] == "trace":
        return job_trace(argv[1:])
    if argv and argv[0] == "fleet-stats":
        # lazy: the fleet collector can dial sockets and pull the sparse
        # wire stack — only this subcommand pays for it (repo-lint
        # enforced, like the doctor's attribution engine)
        from paddle_tpu.observability import collector
        return collector.fleet_stats_main(argv[1:])
    if argv and argv[0] == "doctor":
        # lazy: the attribution engine pulls analysis.cost_model — only
        # the doctor pays for it
        return job_doctor(argv[1:])
    if argv and argv[0] == "profile":
        # lazy: the per-op profiler pulls analysis.cost_model AND
        # tuning.search — only the profiler pays for them
        return job_profile(argv[1:])
    if argv and argv[0] == "tune":
        # lazy: `import paddle_tpu` must never pull the tuning package
        # (zero-cost-when-unused guard, tier-1 enforced)
        return job_tune(argv[1:])
    if argv and argv[0] == "serve":
        # lazy: `import paddle_tpu` must never pull the serving package
        # (zero-cost-when-unused guard, tier-1 enforced)
        from paddle_tpu.serving.cli import serve_main
        return serve_main(argv[1:])
    if argv and argv[0] == "fleet":
        # lazy: the fleet router/autoscaler rides the same
        # zero-cost-when-unused contract as the serving package
        from paddle_tpu.serving.fleet import fleet_main
        return fleet_main(argv[1:])
    if argv and argv[0] == "elastic":
        # lazy: the elastic training service (distributed/elastic.py)
        # rides the same zero-cost-when-unused contract — importing
        # paddle_tpu (or running a plain trainer) never loads it
        from paddle_tpu.distributed.elastic import elastic_main
        return elastic_main(argv[1:])
    if argv and argv[0] == "pserver":
        # lazy: the sparse wire tier (sparse/{wire,pserver,client})
        # rides the same zero-cost-when-unused contract — importing
        # paddle_tpu or paddle_tpu.sparse never loads a socket stack
        from paddle_tpu.sparse.pserver import pserver_main
        return pserver_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="paddle_tpu",
        description="TrainerMain analog: run a v1 config on the TPU "
                    "runtime.  Subcommands also exist: `paddle_tpu check "
                    "prog.json|__model__|dir` runs the static program "
                    "verifier, `paddle_tpu plan prog.json --mesh dp=8` "
                    "proposes auto-sharding specs with a static cost "
                    "breakdown, `paddle_tpu stats run.jsonl...` "
                    "summarizes observability metrics logs (--prom for "
                    "Prometheus exposition), `paddle_tpu fleet-stats "
                    "<logs|dir|host:port...>` merges per-process metrics "
                    "snapshots into one labeled fleet view, `paddle_tpu "
                    "trace "
                    "run.jsonl...` renders span timelines and critical "
                    "paths, `paddle_tpu doctor run.jsonl... [--program "
                    "prog.json] [--per-op]` explains where the "
                    "step/request time went and calibrates the cost "
                    "model, `paddle_tpu profile prog.json` measures "
                    "every op eagerly against the static model (per-op "
                    "'where does XLA lose' + HBM timeline), `paddle_tpu "
                    "tune <target>` searches and persists autotuner "
                    "winners, `paddle_tpu serve --model dir` runs "
                    "the batching inference server over exported "
                    "artifacts (stdio JSON, or HTTP with --http), and "
                    "`paddle_tpu fleet --model dir --replicas N` scales "
                    "it behind a queue-depth-aware router, and "
                    "`paddle_tpu elastic --config conf.py --data "
                    "'parts/*' --workers K --root dir` runs the elastic "
                    "multi-worker training service with checkpointed "
                    "mesh resize, and `paddle_tpu pserver --shard k/N "
                    "--dir dir` runs one sparse parameter-server shard "
                    "behind the batched binary wire protocol (see "
                    "`paddle_tpu check|plan|stats|fleet-stats|trace|"
                    "doctor|profile|tune|serve|fleet|elastic|pserver "
                    "--help`).")
    ap.add_argument("--config", required=True, help="v1 config file")
    ap.add_argument("--job", default="train",
                    choices=["train", "test", "time", "checkgrad"])
    ap.add_argument("--config_args", default=None,
                    help="k=v,... forwarded to get_config_arg")
    ap.add_argument("--feed-npz", default=None,
                    help="npz of named feed arrays (+ name@LEN)")
    ap.add_argument("--batch", type=int, default=None,
                    help="synthetic-feed batch (default: settings batch)")
    ap.add_argument("--num_passes", type=int, default=1)
    ap.add_argument("--start_pass", type=int, default=0,
                    help="resume pass numbering (use with "
                         "--init_model_path)")
    ap.add_argument("--steps_per_pass", type=int, default=10)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--seq_len", type=int, default=12,
                    help="synthetic-feed sequence length")
    ap.add_argument("--save_dir", default=None)
    ap.add_argument("--init_model_path", default=None)
    ap.add_argument("--use_amp", action="store_true")
    args = ap.parse_args(argv)

    if args.job == "checkgrad":
        # the precision instrument wants float64, which the TPU does not
        # implement: pin the CPU backend + x64 BEFORE first device touch
        # (same live-config trick as dryrun_multichip's child process).
        # If the backend already initialized (library use, not CLI),
        # job_checkgrad falls back to the f32 tolerance with a warning.
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
            jax.config.update("jax_enable_x64", True)
        except Exception:
            pass

    import paddle_tpu as pt
    from paddle_tpu.trainer_config_helpers import load_v1_config

    cfg = load_v1_config(args.config, **_parse_config_args(args.config_args))
    batch = args.batch or cfg.settings.get("batch_size") or 16
    feeds = _load_feeds(args.feed_npz) or _synth_feeds(cfg, batch, seq_len=args.seq_len)
    used = _used_feed_names(cfg)
    feeds = {k: v for k, v in feeds.items() if k in used}
    # stage feeds on device ONCE: re-uploading a big batch per dispatch
    # (79 MB for alexnet bs128) costs seconds over a tunneled link and
    # would dominate job=time's measurement
    import jax
    feeds = {k: jax.device_put(v) for k, v in feeds.items()}
    exe = pt.Executor(amp=args.use_amp)
    job = {"train": job_train, "test": job_test, "time": job_time,
           "checkgrad": job_checkgrad}[args.job]
    return job(cfg, exe, feeds, args)


if __name__ == "__main__":
    sys.exit(main())
