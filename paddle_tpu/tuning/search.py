"""Search engine: measurement harness, grid + successive-halving
searches, and the paired-A/B noise gate that decides whether a winner is
real.

Measurement discipline (the PR 2 / benchmark/RESULTS.md rules, now
infrastructure instead of per-benchmark copies):

* every score is the MEDIAN of ``reps`` timed windows with ``warmup``
  untimed windows discarded first (compiles, cache warming);
* the candidate-vs-default verdict comes from :func:`paired_ab` —
  alternating default/candidate window pairs with the headline speedup
  the MEDIAN OF PER-PAIR RATIOS, because this container's throughput
  drifts 2-3x on multi-minute timescales and a paired design cancels
  drift that independent medians do not;
* the **noise gate**: a winner is only declared when the median pair
  ratio clears ``min_speedup`` AND at least ``min_winning_fraction`` of
  the pairs individually favor the candidate.  Anything less is an
  explicit REFUSAL recorded with the raw windows — no config change
  ships on a number that could be jitter.

Fault containment: each trial runs inside :func:`run_trial` — a config
whose measurement raises is recorded ``failed``, one that exceeds
``trial_timeout_s`` is recorded ``timeout``, and neither crashes the
search (the ``tuning.trial`` fault-injection site makes both paths
deterministic facts for the test suite).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import observability as obs
from ..testing import faultinject as _fi
from . import store as _store
from . import tunables as _tn

__all__ = [
    "Trial", "SearchResult", "time_windows", "run_trial", "grid_search",
    "successive_halving", "paired_ab", "tune", "pending_stub",
]


# ---------------------------------------------------------------------------
# Measurement harness (shared with the benchmark drivers)
# ---------------------------------------------------------------------------
def time_windows(call: Callable[[], object], *, reps: int = 3,
                 warmup: int = 1, unit: int = 1) -> dict:
    """Time ``call`` (which must block until its work is DONE — include
    the completion barrier) over ``reps`` windows after ``warmup``
    discarded ones.  Returns median seconds per ``unit`` plus the raw
    windows and the (max-min)/median spread in percent."""
    for _ in range(max(0, warmup)):
        call()
    times = []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        call()
        times.append(time.perf_counter() - t0)
    med = float(np.median(times))
    return {
        "seconds": med / max(1, unit),
        "windows": [round(t, 6) for t in times],
        "spread_pct": round(100.0 * (max(times) - min(times)) / med, 2)
        if med > 0 else 0.0,
    }


# ---------------------------------------------------------------------------
# Trials
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class Trial:
    config: Dict[str, object]
    status: str                      # ok | failed | timeout
    seconds: Optional[float] = None  # median s/window (ok trials only)
    windows: List[float] = dataclasses.field(default_factory=list)
    spread_pct: float = 0.0
    error: Optional[str] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class _InjectedTimeout(Exception):
    """tuning.trial 'timeout' action: deterministically exercise the
    timeout-recording path without actually hanging the suite."""


def run_trial(measure: Callable[[dict], float], config: Dict[str, object],
              *, reps: int = 3, warmup: int = 1,
              trial_timeout_s: float = 120.0) -> Trial:
    """One contained trial of ``config``.

    ``measure(config)`` runs ONE window (including its own completion
    barrier) and returns elapsed seconds.  A raising config records
    ``failed``; one whose total wall time exceeds ``trial_timeout_s``
    records ``timeout`` (soft: the in-flight window finishes — the
    engine cannot preempt arbitrary host/device work — but its score is
    discarded and the search moves on).  Neither propagates."""
    t_start = time.perf_counter()
    windows: List[float] = []
    status, err = "ok", None
    try:
        if _fi.ENABLED:
            action = _fi.check("tuning.trial")
            if action == "fail":
                raise _fi.InjectedFault("injected trial failure at "
                                        "tuning.trial")
            if action == "timeout":
                raise _InjectedTimeout("injected trial timeout at "
                                       "tuning.trial")
            if action is not None:
                _fi.raise_for(action, "tuning.trial")
        for _ in range(max(0, warmup)):
            measure(dict(config))
            if time.perf_counter() - t_start > trial_timeout_s:
                raise _InjectedTimeout(
                    f"trial exceeded {trial_timeout_s}s during warmup")
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            measure(dict(config))
            windows.append(time.perf_counter() - t0)
            if time.perf_counter() - t_start > trial_timeout_s:
                raise _InjectedTimeout(
                    f"trial exceeded {trial_timeout_s}s")
    except _InjectedTimeout as e:
        status, err = "timeout", str(e)
    except Exception as e:          # noqa: BLE001 — containment is the point
        status, err = "failed", f"{type(e).__name__}: {e}"
    wall_ms = (time.perf_counter() - t_start) * 1e3
    obs.inc_counter("tuning/trials")
    obs.observe_hist("tuning/trial_ms", wall_ms)
    if status != "ok":
        obs.inc_counter("tuning/failures")
    obs.emit_event("tuning", event="trial", config=dict(config),
                   status=status, wall_ms=round(wall_ms, 3),
                   error=err)
    if status != "ok":
        return Trial(dict(config), status, error=err, windows=windows)
    med = float(np.median(windows))
    return Trial(dict(config), "ok", seconds=med,
                 windows=[round(w, 6) for w in windows],
                 spread_pct=round(100.0 * (max(windows) - min(windows))
                                  / med, 2) if med > 0 else 0.0)


# ---------------------------------------------------------------------------
# Search algorithms
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class SearchResult:
    tunable: str
    algo: str
    trials: List[Trial]
    best: Optional[Dict[str, object]]      # lowest-median ok config
    default: Dict[str, object]
    truncated: int = 0                     # grid configs dropped by budget

    def to_dict(self) -> dict:
        return {"tunable": self.tunable, "algo": self.algo,
                "trials": [t.to_dict() for t in self.trials],
                "best": self.best, "default": dict(self.default),
                "truncated": self.truncated}


def _candidates(entry: dict, budget: Optional[int]):
    configs = list(_tn.grid_configs(entry))
    if budget is not None and budget < len(configs):
        # grid_configs yields the default first, so a capped search still
        # re-measures the shipped config
        return configs[:max(1, budget)], len(configs) - max(1, budget)
    return configs, 0


def grid_search(name: str, measure, *, budget: Optional[int] = None,
                reps: int = 3, warmup: int = 1,
                trial_timeout_s: float = 120.0,
                on_trial=None) -> SearchResult:
    """Exhaustive (budget-capped) grid: every config measured at full
    ``reps``.  Right for small declared spaces; the driver-level sweep
    engine (benchmark/longctx.py --sweep) is exactly this with the full
    trial list as the product."""
    entry = _tn.get_tunable(name)
    configs, truncated = _candidates(entry, budget)
    trials = []
    for cfg in configs:
        t = run_trial(measure, cfg, reps=reps, warmup=warmup,
                      trial_timeout_s=trial_timeout_s)
        trials.append(t)
        if on_trial is not None:
            on_trial(t)
    ok = [t for t in trials if t.status == "ok"]
    best = min(ok, key=lambda t: t.seconds).config if ok else None
    return SearchResult(name, "grid", trials, best, entry["default"],
                        truncated)


def successive_halving(name: str, measure, *, budget: Optional[int] = None,
                       eta: int = 3, reps: int = 3, warmup: int = 1,
                       trial_timeout_s: float = 120.0,
                       on_trial=None) -> SearchResult:
    """Successive halving: every candidate gets ONE cheap window first;
    the best ``1/eta`` fraction advance to the next rung with the rep
    count multiplied by ``eta``, until at most ``eta`` survivors run at
    full ``reps``.  Failed/timeout configs are eliminated at their rung.
    Right when the declared space is large relative to the budget."""
    entry = _tn.get_tunable(name)
    configs, truncated = _candidates(entry, budget)
    trials: List[Trial] = []
    alive = list(configs)
    rung_reps = 1
    while alive:
        rung: List[Trial] = []
        for cfg in alive:
            # warmup at EVERY rung: a rung-1 window that includes a
            # config's one-time compile would systematically cull
            # slow-to-compile configs on compile time, not runtime
            t = run_trial(measure, cfg, reps=rung_reps, warmup=warmup,
                          trial_timeout_s=trial_timeout_s)
            rung.append(t)
            trials.append(t)
            if on_trial is not None:
                on_trial(t)
        ok = sorted([t for t in rung if t.status == "ok"],
                    key=lambda t: t.seconds)
        if not ok:
            break
        if len(ok) <= max(2, eta) and rung_reps >= reps:
            break
        keep = max(1, len(ok) // eta)
        alive = [t.config for t in ok[:keep]]
        if rung_reps >= reps:
            break
        rung_reps = min(reps, rung_reps * eta)
    # the winner comes from the HIGHEST-evidence trials only (the final
    # rung's full-rep measurements) — a 1-window rung-1 score of an
    # eliminated config must not out-jitter the survivors
    finals: Dict[str, Trial] = {}
    for t in trials:
        if t.status == "ok":
            finals[repr(sorted(t.config.items()))] = t
    ok = list(finals.values())
    best = None
    if ok:
        evidence = max(len(t.windows) for t in ok)
        finalists = [t for t in ok if len(t.windows) == evidence]
        best = min(finalists, key=lambda t: t.seconds).config
    return SearchResult(name, "halving", trials, best, entry["default"],
                        truncated)


# ---------------------------------------------------------------------------
# Paired A/B + noise gate
# ---------------------------------------------------------------------------
def paired_ab(measure, default_config: Dict[str, object],
              candidate_config: Dict[str, object], *, pairs: int = 5,
              warmup: int = 1, min_speedup: float = 1.10,
              min_winning_fraction: float = 0.75) -> dict:
    """Alternating default/candidate windows; verdict by median of
    per-pair ratios with the noise gate (module docstring).  Returns a
    dict with the verdict AND the raw windows — a refusal commits its
    evidence, not just a boolean."""
    for _ in range(max(0, warmup)):
        measure(dict(default_config))
        measure(dict(candidate_config))
    d_windows, c_windows = [], []
    for _ in range(max(2, pairs)):
        t0 = time.perf_counter()
        measure(dict(default_config))
        d_windows.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        measure(dict(candidate_config))
        c_windows.append(time.perf_counter() - t0)
    ratios = [d / c for d, c in zip(d_windows, c_windows)]
    med = float(np.median(ratios))
    winning = sum(1 for r in ratios if r > 1.0) / len(ratios)
    accepted = med >= min_speedup and winning >= min_winning_fraction
    if accepted:
        reason = None
    elif med < min_speedup:
        reason = (f"median pair ratio {med:.3f} < min_speedup "
                  f"{min_speedup} — inside the noise band")
    else:
        reason = (f"only {winning:.0%} of pairs favor the candidate "
                  f"(< {min_winning_fraction:.0%}) — not robust to "
                  f"window-scale jitter")
    return {
        "speedup": round(med, 4),
        "pair_ratios": [round(r, 4) for r in ratios],
        "default_windows": [round(w, 6) for w in d_windows],
        "candidate_windows": [round(w, 6) for w in c_windows],
        "min_speedup": min_speedup,
        "min_winning_fraction": min_winning_fraction,
        "accepted": bool(accepted),
        "refusal_reason": reason,
    }


# ---------------------------------------------------------------------------
# Orchestration
# ---------------------------------------------------------------------------
def pending_stub(name: str) -> dict:
    """The pending-hardware result document for a device-side tunable on
    a host without the accelerator (the PR 1 stub convention: the harness
    and the pre-registered decision rule ship; the rows wait for a
    chip)."""
    entry = _tn.get_tunable(name)
    import jax
    return {
        "tunable": name, "status": "pending_hardware",
        "backend": jax.default_backend(),
        "side": entry["side"],
        "decision_rule": entry["decision_rule"],
        "note": "device-side search target; run `python -m paddle_tpu "
                "tune " + name + "` on a host with the accelerator — the "
                "pre-registered decision rule above governs enabling the "
                "winner",
    }


def tune(name: str, measure, *, algo: str = "grid",
         budget: Optional[int] = None, reps: int = 3, warmup: int = 1,
         pairs: int = 5, min_speedup: float = 1.10,
         trial_timeout_s: float = 120.0, context: str = "",
         save: bool = True, base: Optional[str] = None,
         on_trial=None) -> dict:
    """Full tuning run for one tunable: search the declared space, verify
    the best candidate against the default through the paired-A/B noise
    gate, and (gate willing) persist the winner for trace-time replay.

    Returns a result document (JSON-serializable) carrying the trial
    table, the A/B verdict with raw windows, and the stored-record path
    when a winner shipped.  Device-side tunables on a chipless host
    return the pending-hardware stub instead of searching."""
    entry = _tn.get_tunable(name)
    import jax
    if entry["side"] == "device" and jax.default_backend() == "cpu":
        doc = pending_stub(name)
        obs.emit_event("tuning", event="pending", tunable=name,
                       backend=doc["backend"])
        return doc
    search_fn = {"grid": grid_search,
                 "halving": successive_halving}.get(algo)
    if search_fn is None:
        raise ValueError(f"tune: unknown algo {algo!r} (grid|halving)")
    result = search_fn(name, measure, budget=budget, reps=reps,
                       warmup=warmup, trial_timeout_s=trial_timeout_s,
                       on_trial=on_trial)
    doc = {
        "tunable": name, "status": "searched", "context": str(context),
        "search": result.to_dict(),
    }
    if result.best is None:
        doc["status"] = "no_viable_config"
        obs.inc_counter("tuning/refusals")
        obs.emit_event("tuning", event="refusal", tunable=name,
                       reason="no config measured ok")
        return doc
    if result.best == dict(entry["default"]):
        doc["status"] = "default_is_best"
        obs.emit_event("tuning", event="default_best", tunable=name)
        return doc
    verdict = paired_ab(measure, entry["default"], result.best,
                        pairs=pairs, warmup=warmup,
                        min_speedup=min_speedup)
    doc["ab"] = verdict
    doc["winner"] = result.best if verdict["accepted"] else None
    if verdict["accepted"]:
        doc["status"] = "winner"
        obs.inc_counter("tuning/winners")
        obs.emit_event("tuning", event="winner", tunable=name,
                       config=result.best,
                       speedup=verdict["speedup"])
        if save:
            doc["record_path"] = _store.save_record(
                name, result.best, context=context, base=base,
                score=min(t.seconds for t in result.trials
                          if t.status == "ok"),
                speedup=verdict["speedup"], algo=result.algo,
                pair_ratios=verdict["pair_ratios"],
                default_windows=verdict["default_windows"],
                candidate_windows=verdict["candidate_windows"])
    else:
        doc["status"] = "noise_gate_refusal"
        obs.inc_counter("tuning/refusals")
        obs.emit_event("tuning", event="refusal", tunable=name,
                       reason=verdict["refusal_reason"],
                       speedup=verdict["speedup"])
    return doc
