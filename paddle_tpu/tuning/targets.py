"""Built-in measurement targets for ``python -m paddle_tpu tune`` and
``benchmark/autotune.py``.

A *target* binds a registered tunable to a concrete, self-contained
workload whose one-window runtime ``measure(config)`` the search engine
can time — the subsystem's representative hot loop, sized so a full grid
finishes in minutes on a CPU container (``smoke=True`` shrinks it to
seconds for path checks).

Host-side targets run anywhere; device-side targets (Pallas blocks, XLA
flags) build real kernel workloads and are only reached on a host with
the accelerator — ``search.tune`` short-circuits them into the
pending-hardware stub on CPU, so ``tune pallas/flash_attention`` in this
container documents the pre-registered decision rule instead of
fabricating numbers.

Every builder constructs its fixture ONCE (model, synthetic data) and
returns a closure measuring one window: per-config compile costs land in
the engine's warmup-discard windows, exactly like the committed
benchmarks.
"""
from __future__ import annotations

import time
from typing import Callable, Dict

import numpy as np

__all__ = ["TARGETS", "build_target", "target_names"]


# ---------------------------------------------------------------------------
# Host-side targets
# ---------------------------------------------------------------------------
def _target_run_pipelined(smoke: bool) -> Callable[[dict], None]:
    """Pipelined-dispatch chunking on a dispatch-overhead-bound workload:
    a small MLP whose per-step device time is tiny, so steps_per_dispatch
    (host dispatches amortized per compiled scan) and prefetch_depth
    (staging overlap) are the binding knobs — the regime PR 2 measured
    CPU headroom in."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    x = layers.data("x", shape=[64], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    h = layers.fc(x, size=64, act="relu")
    pred = layers.fc(h, size=8, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    program = pt.default_main_program()

    rng = np.random.RandomState(0)
    n = 8 if smoke else 48
    feeds = [{"x": rng.rand(32, 64).astype(np.float32),
              "y": rng.randint(0, 8, (32, 1))} for _ in range(n)]

    def measure(cfg: dict):
        outs = list(exe.run_pipelined(
            iter(feeds), program, fetch_list=[loss], is_test=True,
            steps_per_dispatch=cfg["steps_per_dispatch"],
            prefetch_depth=cfg["prefetch_depth"]))
        # materialized numpy fetches ARE the completion barrier
        assert len(outs) == n
    return measure


def _target_reader_prefetch(smoke: bool) -> Callable[[dict], None]:
    """Reader-engine worker/buffer sizing on genuine decode work (string
    parsing, the PR 2 CTR recipe shape) with a consumer that also costs
    host time — the overlap the workers exist to buy."""
    rng = np.random.RandomState(0)
    n = 128 if smoke else 2048
    lines = ["%d," % rng.randint(0, 2)
             + " ".join("%d" % v for v in rng.randint(0, 65536, 13))
             for _ in range(n)]

    def decode(line):
        lab, _, dense_s = line.partition(",")
        return np.array([np.log1p(float(t)) for t in dense_s.split()],
                        np.float32), np.float32(int(lab))

    def reader():
        return iter(lines)

    sink = np.zeros(13, np.float32)

    def measure(cfg: dict):
        from ..reader.pipeline import prefetch
        src = prefetch(reader, buffer_size=cfg["buffer_size"],
                       num_workers=cfg["num_workers"], mapper=decode)
        acc = sink.copy()
        for dense, _lab in src():
            acc += dense            # consumer-side host work (overlap target)
        assert acc.shape == (13,)
    return measure


def _target_serving_batcher(smoke: bool) -> Callable[[dict], None]:
    """Batcher coalescing policy under closed-loop concurrent load on a
    live-program model: max_batch/max_wait_ms trade per-dispatch
    amortization against batch-fill waiting — the knob pair PR 8's
    capacity probe showed CPU headroom on."""
    import threading

    import paddle_tpu as pt
    from paddle_tpu import layers
    from ..serving.model import Model

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    x = layers.data("x", shape=[32], dtype="float32")
    h = layers.fc(x, size=32, act="relu")
    pred = layers.fc(h, size=4, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    model = Model.from_program(
        exe, pt.default_main_program(), fetch_list=[pred], name="tune-mlp",
        example={"x": np.zeros(32, np.float32)})

    rng = np.random.RandomState(0)
    n_requests = 24 if smoke else 240
    clients = 4 if smoke else 8
    examples = [{"x": rng.rand(32).astype(np.float32)} for _ in range(16)]

    def measure(cfg: dict):
        from ..serving.server import Server
        srv = Server(max_batch=cfg["max_batch"],
                     max_wait_ms=cfg["max_wait_ms"],
                     deadline_ms=None, queue_capacity=None,
                     warmup=True)
        srv.add_model(model)
        srv.start()
        try:
            errors = []
            per_client = n_requests // clients

            def client(ci):
                try:
                    for i in range(per_client):
                        srv.infer(examples[(ci + i) % len(examples)],
                                  timeout=60.0)
                except Exception as e:      # noqa: BLE001 — reported below
                    errors.append(e)

            threads = [threading.Thread(target=client, args=(c,),
                                        name=f"pt-tune-client-{c}")
                       for c in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise errors[0]
        finally:
            srv.shutdown(drain=True, timeout=30.0)
    return measure


def _sparse_fixture(smoke: bool, **session_kw):
    """Shared sparse-target fixture: a one-table sparse program, a zipf
    feed list, and a session factory (fresh session per config so knob
    changes take effect; the TABLE persists so only the first config
    pays cold-row init)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from ..sparse import SparseSession, SparseTable

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    ids = layers.data("ids", shape=[1], dtype="int64")
    label = layers.data("label", shape=[1], dtype="float32")
    emb = layers.embedding(ids, size=[20_000, 16], sparse=True,
                           name="tune_tbl")
    fc = layers.fc(emb, size=16, act="relu")
    loss = layers.mean(layers.square(layers.fc(fc, size=1) - label))
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    program = pt.default_main_program()
    table = SparseTable("tune_tbl", 20_000, 16, num_shards=4, seed=1,
                        learning_rate=0.05)

    rng = np.random.RandomState(0)
    B = 64 if smoke else 256
    n = 6 if smoke else 32
    draws = rng.zipf(1.3, size=(n, B, 1)).astype(np.int64)
    feeds = [{"ids": (draws[i] - 1) % 20_000,
              "label": rng.rand(B, 1).astype(np.float32)}
             for i in range(n)]

    def make_session(**kw):
        merged = dict(session_kw)
        merged.update(kw)
        s = SparseSession(table, bucket_floor=B, **merged)
        s.bind(program)
        return s
    return program, table, feeds, make_session


def _target_sparse_hot_rows(smoke: bool) -> Callable[[dict], None]:
    """Hot-rows LRU capacity on serving-style read-only zipf traffic —
    the cache-first pull loop the capacity knob bounds."""
    _, _, feeds, make_session = _sparse_fixture(smoke)

    def measure(cfg: dict):
        sess = make_session(cache_rows=cfg["cache_rows"])
        for f in feeds:
            sess.prepare_feed(f, is_test=True)
    return measure


def _target_sparse_prefetch(smoke: bool) -> Callable[[dict], None]:
    """Pull-ahead depth on a REAL training loop (pull -> dispatch ->
    push): the overlap only pays when the host has parallelism to
    spare, which is exactly what the paired gate decides."""
    import paddle_tpu as pt

    program, _, feeds, make_session = _sparse_fixture(smoke)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    loss_name = [op.output("Out")[0] for b in program.blocks
                 for op in b.ops if op.type == "mean"][-1]

    def measure(cfg: dict):
        sess = make_session(prefetch_depth=cfg["depth"])
        fetch = [loss_name] + sess.grad_fetch_list
        feed_it = sess.prefetch_feeds(iter(feeds))
        try:
            for feed in feed_it:
                out = exe.run(program, feed=feed, fetch_list=fetch)
                sess.complete(out[1:])
        finally:
            feed_it.close()
        sess.flush()
    return measure


def _target_sparse_push_flush(smoke: bool) -> Callable[[dict], None]:
    """Async-push drain size on a push-only loop (prepare + complete,
    no dispatch): isolates the worker wakeup/lock amortization the
    knob exists for."""
    _, table, feeds, make_session = _sparse_fixture(smoke)
    rng = np.random.RandomState(1)
    grads = {}

    def measure(cfg: dict):
        sess = make_session(async_push=8,
                            push_flush_batch=cfg["batch"])
        for f in feeds:
            prepared = sess.prepare_feed(f)
            shape = prepared["tune_tbl@ROWS"].shape
            if shape not in grads:
                grads[shape] = rng.randn(*shape).astype(np.float32)
            sess.complete([grads[shape]])
        sess.flush()
    return measure


def _target_decode_slots(smoke: bool) -> Callable[[dict], None]:
    """Decode slot-pool sizing under closed-loop generate load: slots is
    the compiled decode batch (per-step amortization vs padded compute
    at partial occupancy), step_wait_ms the idle-pool poll — the
    continuous-batching knob pair benchmark/decode.py measured."""
    import threading

    from ..serving.decode import DecodeEngine, DecodeRuntime

    V = 64
    rng = np.random.RandomState(0)
    n_requests = 8 if smoke else 64
    clients = 2 if smoke else 4
    prompts = [[int(t) for t in rng.randint(1, V, rng.randint(3, 9))]
               for _ in range(16)]
    max_news = [int(rng.randint(4, 17)) for _ in range(16)]
    # one engine per slot count, built on first use — rebuilding per
    # config would make the A/B pay compile inside timed windows
    engines: Dict[int, DecodeEngine] = {}

    def measure(cfg: dict):
        s = int(cfg["slots"])
        if s not in engines:
            engines[s] = DecodeEngine(
                vocab_size=V, hidden_dim=32, n_layers=1, slots=s,
                max_len=32, seed=0, name=f"tune-dec{s}")
            rt0 = DecodeRuntime(engines[s], step_wait_ms=1.0)
            rt0.start(warmup=True)
            rt0.shutdown()
        rt = DecodeRuntime(engines[s],
                           step_wait_ms=cfg["step_wait_ms"])
        rt.start(warmup=False)
        try:
            errors = []
            per_client = n_requests // clients

            def client(ci):
                try:
                    for i in range(per_client):
                        j = (ci * per_client + i) % len(prompts)
                        rt.submit(prompts[j], max_news[j]).result(60.0)
                except Exception as e:  # noqa: BLE001 — reported below
                    errors.append(e)

            threads = [threading.Thread(target=client, args=(c,),
                                        name=f"pt-tune-dec-{c}")
                       for c in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise errors[0]
        finally:
            rt.shutdown(drain=True, timeout=30.0)
    return measure


# ---------------------------------------------------------------------------
# Device-side targets (reached only with the accelerator present;
# search.tune returns the pending-hardware stub on CPU)
# ---------------------------------------------------------------------------
def _target_flash_blocks(smoke: bool) -> Callable[[dict], None]:
    """Flash-attention tile shape at 32k tokens — the longctx sweep's
    grid point, one config per trial."""
    import jax
    import jax.numpy as jnp

    from ..ops.pallas_kernels import flash_attention

    T = 2048 if smoke else 32768
    rng = np.random.RandomState(0)
    qkv = tuple(jnp.asarray(rng.randn(8, T, 64), jnp.bfloat16)
                for _ in range(3))
    steps = 2 if smoke else 10
    # one jitted window PER CONFIG, reused across that config's windows:
    # the compile lands in the engine's warmup-discarded window instead
    # of polluting every timed one (same memoization longctx's
    # _sweep_measure uses)
    compiled = {}

    def measure(cfg: dict):
        key = (cfg["block_q"], cfg["block_k"])
        if key not in compiled:
            def loss_fn(qkv, bq=cfg["block_q"], bk=cfg["block_k"]):
                q, k, v = qkv
                o = flash_attention(q, k, v, causal=True, block_q=bq,
                                    block_k=bk)
                return jnp.sum(o.astype(jnp.float32) ** 2) * 1e-6

            grad = jax.value_and_grad(loss_fn)

            @jax.jit
            def window(qkv):
                def body(carry, _):
                    l, g = grad(carry)
                    new = tuple(t - 1e-6 * gt.astype(t.dtype)
                                for t, gt in zip(carry, g))
                    return new, l
                _, losses = jax.lax.scan(body, qkv, None, length=steps)
                return losses
            compiled[key] = window
        float(compiled[key](qkv)[-1])       # completion barrier
    return measure


def _target_conv1x1_blocks(smoke: bool) -> Callable[[dict], None]:
    """Conv1x1 Pallas tile shape on the worst measured pass (deep-K
    wgrad) of a representative ResNet-50 shape."""
    import jax
    import jax.numpy as jnp

    from ..ops.pallas_conv import _to_pixel_major, pallas_matmul

    N, C, H, W, M = (2, 128, 16, 16, 256) if smoke \
        else (128, 512, 28, 28, 128)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(N, C, H, W), jnp.bfloat16)
    g = jnp.asarray(rng.randn(N, M, H, W), jnp.bfloat16)
    xm, _ = _to_pixel_major(x)
    gm, _ = _to_pixel_major(g)
    steps = 2 if smoke else 50
    compiled = {}        # per-config jitted window (compile -> warmup)

    def measure(cfg: dict):
        key = (cfg["block_m"], cfg["block_n"], cfg["block_k"])
        if key not in compiled:
            @jax.jit
            def window(xm, gm, bm=cfg["block_m"], bn=cfg["block_n"],
                       bk=cfg["block_k"]):
                def body(carry, _):
                    xc, gc = carry
                    dw = pallas_matmul(gc, xc, True, False, bm, bn, bk)
                    s = jnp.sum(dw * dw[:1])
                    f = (1.0 - 1e-12 * s)
                    return (xc * f.astype(xc.dtype),
                            gc * f.astype(gc.dtype)), s
                _, ss = jax.lax.scan(body, (xm, gm), None, length=steps)
                return ss[-1]
            compiled[key] = window
        float(compiled[key](xm, gm))
    return measure


def _target_scoped_vmem(smoke: bool) -> Callable[[dict], None]:
    """Scoped-VMEM limit at the sweep point it gates: 2048-row flash
    blocks, which the 16 MiB default rejects.  A config whose compile is
    rejected records a failed trial — that IS the sweep result for it."""
    import jax
    import jax.numpy as jnp

    from ..ops.pallas_kernels import flash_attention

    T = 2048 if smoke else 32768
    rng = np.random.RandomState(0)
    qkv = tuple(jnp.asarray(rng.randn(8, T, 64), jnp.bfloat16)
                for _ in range(3))
    steps = 2 if smoke else 10
    compiled = {}        # per-config AOT executable (compile -> warmup)

    def measure(cfg: dict):
        key = int(cfg["scoped_vmem_limit_kib"])
        if key not in compiled:
            def window(qkv):
                def body(carry, _):
                    q, k, v = carry
                    o = flash_attention(q, k, v, causal=True,
                                        block_q=2048, block_k=1024)
                    s = jnp.sum(o.astype(jnp.float32) ** 2) * 1e-6
                    return tuple(t * (1.0 - 1e-12 * s).astype(t.dtype)
                                 for t in carry), s
                _, losses = jax.lax.scan(body, qkv, None, length=steps)
                return losses
            compiled[key] = jax.jit(window).lower(qkv).compile(
                compiler_options={"xla_tpu_scoped_vmem_limit_kib":
                                  str(key)})
        float(compiled[key](qkv)[-1])
    return measure


TARGETS: Dict[str, Callable[[bool], Callable[[dict], None]]] = {
    "executor/run_pipelined": _target_run_pipelined,
    "reader/prefetch": _target_reader_prefetch,
    "serving/batcher": _target_serving_batcher,
    "serving/decode_slots": _target_decode_slots,
    "sparse/hot_rows": _target_sparse_hot_rows,
    "sparse/prefetch": _target_sparse_prefetch,
    "sparse/push_flush": _target_sparse_push_flush,
    "pallas/flash_attention": _target_flash_blocks,
    "pallas/conv1x1_blocks": _target_conv1x1_blocks,
    "xla/scoped_vmem_limit_kib": _target_scoped_vmem,
}


#: target name -> module whose import registers the tunable (lazily
#: imported subsystems: serving, the sparse parameter server, the
#: flag-gated Pallas conv kernels)
_REGISTERING_MODULE = {
    "serving/batcher": "paddle_tpu.serving.server",
    "serving/decode_slots": "paddle_tpu.serving.decode",
    "sparse/hot_rows": "paddle_tpu.sparse.session",
    "sparse/prefetch": "paddle_tpu.sparse.session",
    "sparse/push_flush": "paddle_tpu.sparse.session",
    "pallas/conv1x1_blocks": "paddle_tpu.ops.pallas_conv",
}


def ensure_registered(name: str):
    """Import the subsystem that declares ``name`` (no-op for tunables
    registered by the core import)."""
    mod = _REGISTERING_MODULE.get(name)
    if mod is not None:
        import importlib
        importlib.import_module(mod)


def target_names():
    return sorted(TARGETS)


def build_target(name: str, smoke: bool = False) -> Callable[[dict], None]:
    """Build the measurement closure for a registered target (importing
    whatever subsystem registers the tunable, e.g. serving)."""
    try:
        builder = TARGETS[name]
    except KeyError:
        raise KeyError(f"no built-in tune target for {name!r}; "
                       f"available: {target_names()}") from None
    t0 = time.perf_counter()
    measure = builder(smoke)
    build_s = time.perf_counter() - t0
    measure.build_seconds = round(build_s, 3)
    return measure
