"""Persistence + replay for autotuner winners.

A winner is one JSON file under ``<PADDLE_TPU_CACHE_DIR>/tuning/`` named
``ptat-<fingerprint>.json`` — the PR 3 compile-cache discipline applied
to configs instead of executables:

* **Keying** — :func:`record_fingerprint` hashes (format version,
  tunable name, the tunable's declared-space digest, topology, context)
  through :func:`~paddle_tpu.core.compile_cache.fingerprint_hex`, which
  folds in the jax + paddle_tpu versions, backend and device count.  A
  jax upgrade, a framework release, a different chip count/kind, or an
  edit to the tunable's declaration each produce a different fingerprint
  — the stale record is simply never found, and the call site keeps its
  default.  ``context`` is a free-form site key (e.g. a kernel shape)
  for tunables whose winner is shape-dependent.
* **Writes** — atomic tmp + ``os.replace`` (a concurrent reader never
  sees a truncated record); schema-versioned by :data:`TUNING_FORMAT`.
* **Replay** — :func:`tuned` is the ONLY surface the runtime call sites
  touch: stored winner merged over the caller's default, or the default
  object untouched.  Lookups memoize per (name, context) — including
  misses — so a training process pays at most one disk probe per call
  site, and a corrupt/foreign/schema-drifted record degrades to the
  default with a warning, never an error.
"""
from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from typing import Dict, Optional

from ..core import compile_cache
from ..core.registry import get_tunable
from ..testing import lockwatch as _lw
from . import tunables as _tn

logger = logging.getLogger("paddle_tpu")

__all__ = [
    "TUNING_FORMAT", "store_dir", "record_fingerprint", "record_path",
    "save_record", "load_record", "tuned", "clear_memo", "list_records",
]

TUNING_FORMAT = 1               # bump to invalidate every stored winner
_PREFIX = "ptat-"

_lock = _lw.make_lock("tuning.store")
#: (name, context) -> record dict or None (negative lookups memoized too:
#: the zero-search-cost contract means at most ONE probe per call site)
_memo: Dict[tuple, Optional[dict]] = {}


def store_dir(base: Optional[str] = None) -> str:
    """Active tuning-record directory ('' = persistence off).  ``base``
    overrides the ``cache_dir`` flag (CLI --out, tests)."""
    d = base if base is not None else compile_cache.cache_dir()
    return os.path.join(d, "tuning") if d else ""


def topology_key():
    """Device-topology fingerprint component beyond what
    ``environment_key`` already carries (backend + device count): the
    device KIND — a winner tuned on v4 must not replay on v5."""
    import jax
    devices = jax.devices()
    kind = getattr(devices[0], "device_kind", "unknown") if devices \
        else "none"
    return (str(kind), len(devices))


def record_fingerprint(name: str, context: str = "") -> str:
    entry = get_tunable(name)
    return compile_cache.fingerprint_hex(
        ("tunable", TUNING_FORMAT, name, _tn.space_digest(entry),
         topology_key(), str(context)))


def record_path(name: str, context: str = "",
                base: Optional[str] = None) -> str:
    d = store_dir(base)
    if not d:
        return ""
    return os.path.join(d, f"{_PREFIX}{record_fingerprint(name, context)}"
                           f".json")


def save_record(name: str, config: Dict[str, object], *,
                context: str = "", base: Optional[str] = None,
                **extra) -> str:
    """Persist a winner config atomically; returns the path ('' when
    persistence is off).  ``extra`` (score/speedup/windows/algo/...) is
    stored verbatim for auditability — replay reads only ``config``."""
    entry = get_tunable(name)
    problems = _tn.validate_config(entry, config)
    if problems:
        raise ValueError(f"save_record({name!r}): config does not match "
                         f"the declared space: {problems}")
    d = store_dir(base)
    if not d:
        return ""
    fp = record_fingerprint(name, context)
    payload = {
        "format": TUNING_FORMAT, "fingerprint": fp, "tunable": name,
        "context": str(context), "config": dict(config),
        "space_digest": _tn.space_digest(entry),
        "topology": list(topology_key()),
        "environment": list(compile_cache.environment_key()),
        "created": round(time.time(), 3),
        **extra,
    }
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=_PREFIX, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        path = os.path.join(d, f"{_PREFIX}{fp}.json")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    with _lock:
        # refresh every memoized view of this (name, context) — the
        # writing process should replay its own new winner
        for k in [k for k in _memo if k[0] == name and k[1] == str(context)]:
            del _memo[k]
    return path


def load_record(name: str, context: str = "",
                base: Optional[str] = None) -> Optional[dict]:
    """Read + validate the persisted record for (name, context), or None.

    Every failure mode is a MISS, never an error: missing file, unreadable
    or truncated JSON, format/fingerprint mismatch (foreign schema
    version or a hash collision), wrong tunable name, or a config the
    declared space no longer admits (schema drift).  Misses other than
    plain not-found log a warning naming the file."""
    path = record_path(name, context, base)
    if not path:
        return None
    try:
        with open(path) as f:
            payload = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        logger.warning("tuning store: unreadable record %s (%s: %s); "
                       "using defaults", path, type(e).__name__, e)
        return None
    fp = record_fingerprint(name, context)
    if not isinstance(payload, dict) \
            or payload.get("format") != TUNING_FORMAT \
            or payload.get("fingerprint") != fp \
            or payload.get("tunable") != name \
            or not isinstance(payload.get("config"), dict):
        logger.warning("tuning store: stale/foreign record %s "
                       "(format/fingerprint mismatch); using defaults",
                       path)
        return None
    problems = _tn.validate_config(get_tunable(name), payload["config"])
    if problems:
        logger.warning("tuning store: record %s no longer matches the "
                       "declared space (%s); using defaults", path,
                       "; ".join(problems))
        return None
    return payload


def tuned(name: str, default: Dict[str, object], *, context: str = "",
          base: Optional[str] = None) -> Dict[str, object]:
    """THE replay lookup: the persisted winner for (name, context) merged
    over ``default``, or ``default`` itself (the same object, untouched)
    when no valid record exists.

    Only keys present in ``default`` are overridden — a call site that
    consumes a subset of the tunable's params never receives foreign
    keys.  Memoized per (name, context): one disk probe per process,
    zero search cost always.  Call sites reach this lazily and only
    under an autotune opt-in (``Executor(autotune=...)`` / the
    ``autotune`` flag), so the off path never imports this package.
    """
    # base is part of the memo key (tests probe several stores in one
    # process); a changed cache_dir flag needs clear_memo(), documented
    key = (name, str(context), base)
    with _lock:
        hit = key in _memo
        payload = _memo.get(key)
    if not hit:
        payload = load_record(name, context, base)
        with _lock:
            _memo[key] = payload
        if payload is not None:
            # cold path, once per (site, process): the replay event makes
            # a tuned run's provenance visible to `paddle_tpu stats`
            from ..observability import emit_event, inc_counter
            inc_counter("tuning/replays")
            emit_event("tuning", event="replay", tunable=name,
                       context=str(context), config=payload["config"])
    if payload is None:
        return default
    cfg = payload["config"]
    return {k: cfg.get(k, v) for k, v in default.items()}


def clear_memo():
    """Forget memoized lookups (tests; also after writing new records
    from a search so the same process replays them)."""
    with _lock:
        _memo.clear()


def list_records(base: Optional[str] = None):
    """(path, payload) for every readable record in the store."""
    d = store_dir(base)
    if not d or not os.path.isdir(d):
        return []
    out = []
    for fn in sorted(os.listdir(d)):
        if not (fn.startswith(_PREFIX) and fn.endswith(".json")):
            continue
        path = os.path.join(d, fn)
        try:
            with open(path) as f:
                out.append((path, json.load(f)))
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            out.append((path, None))
    return out
