"""Persistent autotuner: searched-and-cached configs for kernels, XLA
flags, and host-side pipeline/serving knobs.

Three layers (ROADMAP item 3 generalized from the PR 1 one-off VMEM
sweep into infrastructure):

* :mod:`.tunables` — the registry view.  Subsystems DECLARE knobs next
  to their implementation via ``core.registry.register_tunable`` (the
  ``register_shape_fn`` pattern; same repo-lint AST + live-registry
  gates) — dispatch chunking in ``core/executor.py``, reader prefetch in
  ``reader/pipeline.py``, the serving batcher in ``serving/server.py``,
  Pallas block configs and the scoped-VMEM XLA flag beside their
  kernels.  Declaring never imports this package.
* :mod:`.search` — grid + successive-halving searches under the PR 2
  measurement discipline (warmup discard, median of windows, paired
  alternating A/B with median-of-pair-ratios) and a NOISE GATE that
  refuses to declare a winner inside the container's demonstrated jitter
  band; per-trial fault containment (a raising or overrunning config is
  a recorded ``failed``/``timeout`` trial, never a crashed search).
* :mod:`.store` — winners persisted as JSON under
  ``<PADDLE_TPU_CACHE_DIR>/tuning/`` keyed by the PR 3 content-
  fingerprint scheme extended with the tunable's schema digest and the
  device topology; ``tuned(name, default)`` replays them at trace time
  with zero search cost — and returns the default untouched when no
  record exists, so an autotune-free run is byte-identical to today.

Entry points: ``python -m paddle_tpu tune <target> [--budget N]``,
``Executor(autotune=True)`` / ``Trainer.train(autotune=True)`` / the
``autotune`` flag (replay opt-ins), :mod:`.targets` (built-in
measurement workloads), ``benchmark/autotune.py`` (the committed
tuned-vs-default A/B).

This package is imported LAZILY everywhere outside itself (tier-1 lint):
training paths that never opt in never load it.
"""
from .search import (SearchResult, Trial, grid_search,  # noqa: F401
                     paired_ab, pending_stub, successive_halving,
                     time_windows, tune)
from .store import (TUNING_FORMAT, clear_memo, list_records,  # noqa: F401
                    load_record, record_fingerprint, save_record, tuned)
from .tunables import (get_tunable, grid_configs,  # noqa: F401
                       has_tunable, register_tunable,
                       registered_tunables, space_digest, validate_config)

__all__ = [
    "register_tunable", "get_tunable", "has_tunable",
    "registered_tunables", "grid_configs", "space_digest",
    "validate_config",
    "Trial", "SearchResult", "time_windows", "grid_search",
    "successive_halving", "paired_ab", "tune", "pending_stub",
    "TUNING_FORMAT", "tuned", "save_record", "load_record",
    "record_fingerprint", "list_records", "clear_memo",
]
