"""Tunable-registry helpers: enumeration, config validation, schema digests.

The registry itself lives in :mod:`paddle_tpu.core.registry`
(``register_tunable``, beside ``register_shape_fn``/``register_shard_fn``)
so subsystems can DECLARE knobs next to their implementation without
importing this package — ``import paddle_tpu`` never loads the autotuner
(lazy-import lint, tests/test_repo_lint.py).  This module is the
autotuner's view of those declarations:

* :func:`grid_configs` — enumerate a tunable's full config grid in a
  deterministic order (the search engine's candidate source);
* :func:`validate_config` — check a (possibly deserialized) config
  against the declared space, so a persisted winner whose schema drifted
  falls back to defaults instead of injecting a foreign value;
* :func:`space_digest` — content hash of the declared space + default:
  the tunable-schema component of every persistence fingerprint.  Any
  edit to a tunable's axes or defaults invalidates its stored winners.
"""
from __future__ import annotations

import hashlib
import itertools
from typing import Dict, Iterator, List

from ..core.registry import (get_tunable, has_tunable,  # noqa: F401
                             register_tunable, registered_tunables)

__all__ = [
    "register_tunable", "get_tunable", "has_tunable",
    "registered_tunables", "grid_configs", "space_size",
    "validate_config", "space_digest", "describe",
]


def space_size(entry: dict) -> int:
    n = 1
    for values in entry["space"].values():
        n *= len(values)
    return n


def grid_configs(entry: dict) -> Iterator[Dict[str, object]]:
    """Every config in the declared space, deterministic order (sorted
    param names, axis order as declared), DEFAULT FIRST — a budget-capped
    search always re-evaluates the shipped config, so 'winner' is never
    an artifact of the default falling outside the cap."""
    params = sorted(entry["space"])
    default = entry["default"]
    yield dict(default)
    for combo in itertools.product(*(entry["space"][p] for p in params)):
        cfg = dict(zip(params, combo))
        if cfg != default:
            yield cfg


def validate_config(entry: dict, config: Dict[str, object]) -> List[str]:
    """Problems with ``config`` against the declared space ([] = valid).
    Used on persisted records at replay time: any problem means the
    record predates a schema change and must not be applied."""
    problems = []
    for param in entry["space"]:
        if param not in config:
            problems.append(f"missing param {param!r}")
    for param, value in config.items():
        axis = entry["space"].get(param)
        if axis is None:
            problems.append(f"unknown param {param!r}")
        elif value not in axis:
            problems.append(f"{param}={value!r} not in declared axis "
                            f"{axis}")
    return problems


def space_digest(entry: dict) -> str:
    """Schema-version digest: space axes + defaults + side.  Folded into
    the persistence fingerprint, so editing a tunable's declaration
    orphans its stored winners (they fall back to defaults silently)."""
    payload = repr((entry["name"], entry["side"],
                    tuple(sorted((p, tuple(v))
                          for p, v in entry["space"].items())),
                    tuple(sorted(entry["default"].items()))))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def describe(name: str) -> str:
    """One human-readable block for the CLI's registry table."""
    e = get_tunable(name)
    lines = [f"{e['name']}  [{e['side']}]"
             + ("  (pending hardware)" if e["pending_hardware"] else "")]
    if e["description"]:
        lines.append(f"  {e['description']}")
    for p in sorted(e["space"]):
        lines.append(f"  {p}: {list(e['space'][p])} (default "
                     f"{e['default'][p]!r})")
    if e["decision_rule"]:
        lines.append(f"  decision rule: {e['decision_rule']}")
    return "\n".join(lines)
