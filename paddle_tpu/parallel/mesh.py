"""Device-mesh management.

The reference selects devices with gflags (``--trainer_count``, ``--use_gpu``,
per-layer ``deviceId_``); TPU-native placement is a named
``jax.sharding.Mesh`` whose axes express the parallelism taxonomy:

    dp — data parallel (batch)          tp — tensor parallel (hidden)
    pp — pipeline stages                sp — sequence/context parallel
    ep — expert parallel

Mesh axis layout determines whether collectives ride ICI or DCN; keep tp/sp
on the innermost (fastest) axes, dp/pp outermost — the scaling-book recipe.
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class MeshConfig:
    dp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def size(self):
        return self.dp * self.tp * self.pp * self.sp * self.ep

    def axis_sizes(self) -> Tuple[Tuple[str, int], ...]:
        return (("dp", self.dp), ("pp", self.pp), ("sp", self.sp),
                ("ep", self.ep), ("tp", self.tp))


_current_mesh: Optional[Mesh] = None


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None,
              axis_names: Optional[Sequence[str]] = None,
              shape: Optional[Sequence[int]] = None) -> Mesh:
    """Build a Mesh.  Either from a MeshConfig (axes dp/pp/sp/ep/tp — inner
    axes map to adjacent devices => ICI) or raw (shape, axis_names)."""
    devices = list(devices if devices is not None else jax.devices())
    if config is not None:
        names = [n for n, s in config.axis_sizes()]
        sizes = [s for n, s in config.axis_sizes()]
        total = int(np.prod(sizes))
        if total != len(devices):
            raise ValueError(f"mesh size {total} != device count "
                             f"{len(devices)}")
        arr = np.asarray(devices).reshape(sizes)
        return Mesh(arr, axis_names=names)
    arr = np.asarray(devices).reshape(tuple(shape))
    return Mesh(arr, axis_names=tuple(axis_names))


def make_hybrid_mesh(ici_config: MeshConfig, dcn_dp: int = 1,
                     dcn_pp: int = 1) -> Mesh:
    """Multi-slice/multi-host mesh: outer axes span DCN (slow network),
    inner axes stay on ICI — the scaling-book layout where only dp/pp
    gradients ride DCN.  Axis names: dcn_dp, dcn_pp + the ICI axes."""
    from jax.experimental import mesh_utils
    names = [n for n, s in ici_config.axis_sizes()]
    sizes = [s for n, s in ici_config.axis_sizes()]
    dev = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=sizes, dcn_mesh_shape=[dcn_dp, dcn_pp] + [1] * (len(sizes) - 2),
        devices=jax.devices())
    return Mesh(dev, axis_names=tuple(names))


def mesh_for_axes(axes, devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh from an ``{axis: size}`` dict over the first
    ``prod(sizes)`` local devices, with a readable error when the host
    has too few — the shared entry for `train(auto_shard="dp=8")` and
    ``bench.py --mesh``."""
    axes = {str(k): int(v) for k, v in dict(axes).items()}
    n = int(np.prod(list(axes.values()))) if axes else 1
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n:
        raise RuntimeError(
            f"mesh {axes} needs {n} devices, have {len(devices)} "
            f"(simulate with XLA_FLAGS=--xla_force_host_platform_"
            f"device_count={n} on the cpu platform)")
    return make_mesh(shape=list(axes.values()),
                     axis_names=list(axes.keys()), devices=devices[:n])


def get_mesh() -> Mesh:
    """The ambient mesh (set with mesh_guard), defaulting to a 1-D 'dp' mesh
    over all local devices."""
    global _current_mesh
    if _current_mesh is not None:
        return _current_mesh
    devs = jax.devices()
    return Mesh(np.asarray(devs), axis_names=("dp",))


@contextlib.contextmanager
def mesh_guard(mesh: Mesh):
    global _current_mesh
    old = _current_mesh
    _current_mesh = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _current_mesh = old


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
