"""ShardedExecutor: the multi-chip training path.

One jit per (program, feed-signature) with explicit ``in_shardings`` /
``out_shardings`` over a named Mesh — GSPMD propagates the annotations and
inserts ICI collectives.  This single mechanism replaces the reference's
MultiGradientMachine ring reduce (MultiGradientMachine.h:60-110), both
parameter servers (paddle/pserver, go/pserver), and the NCCL op family
(operators/nccl/nccl_op.cu.cc) — there is no gradient-exchange code to write
because sharded-batch + replicated-params makes XLA emit the all-reduce.

Parallelism taxonomy (mesh axes, see parallel.mesh):
  dp — feeds sharded on batch dim 0 (data parallel)
  tp — Parameter.sharding PartitionSpecs (Megatron column/row, vocab-sharded
       embeddings — the SelectedRows/CTR analog)
  sp — sequence dim sharding on feeds declared lod_level>0 (NEW vs reference)
  pp/ep — via parallel.pipeline / expert specs on parameters.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.executor import Executor
from ..core.program import Program
from .mesh import get_mesh


class ShardedExecutor(Executor):
    """Executor whose compiled step carries mesh shardings.

    feed_specs: optional {feed_name: PartitionSpec} overrides.  Default:
    batch dim sharded on ``batch_axis`` (and, when the program var has
    lod_level>0 and the mesh has an 'sp' axis of size>1, time dim on 'sp').
    Parameters use ``Parameter.sharding`` annotations; unannotated state
    replicates.
    """

    def __init__(self, mesh: Optional[Mesh] = None, batch_axis: str = "dp",
                 feed_specs: Optional[Dict[str, P]] = None,
                 param_specs: Optional[Dict[str, P]] = None,
                 num_microbatches: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.mesh = mesh or get_mesh()
        self.batch_axis = batch_axis
        self.feed_specs = dict(feed_specs or {})
        self.param_specs = dict(param_specs or {})
        # GPipe microbatch count for pipeline_stage-annotated programs
        # (parallel/pipeline_program.py); default = the 'pp' axis size
        self.num_microbatches = num_microbatches

    # -- sharding selection -------------------------------------------------
    def _find_var(self, program: Program, name: str):
        for b in program.blocks:
            if name in b.vars:
                return b.vars[name]
        return None

    def _feed_spec(self, program: Program, name: str, ndim: int,
                   shape=None) -> P:
        if name in self.feed_specs:
            return self.feed_specs[name]
        if ndim == 0:
            return P()
        base = name[:-4] if name.endswith("@LEN") else name
        v = self._find_var(program, base)
        axes = [self.batch_axis if self.batch_axis in self.mesh.axis_names
                else None]
        if (not name.endswith("@LEN") and v is not None and v.lod_level
                and "sp" in self.mesh.axis_names
                and self.mesh.shape["sp"] > 1 and ndim >= 2
                and (shape is None
                     or shape[1] % self.mesh.shape["sp"] == 0)):
            axes.append("sp")
        axes = axes[:ndim]
        return P(*axes)

    def _state_spec(self, program: Program, name: str) -> P:
        if name in self.param_specs:
            return self.param_specs[name]
        v = self._find_var(program, name)
        if v is not None and getattr(v, "sharding", None):
            return P(*v.sharding)
        return P()

    # -- overrides ----------------------------------------------------------
    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, **kw):
        with self.mesh:
            return super().run(program, feed=feed, fetch_list=fetch_list,
                               **kw)

    def run_steps(self, num_steps, program=None, feed=None, **kw):
        with self.mesh:
            return super().run_steps(num_steps, program, feed=feed, **kw)

    def _build_steps(self, program: Program, multi, feeds_stacked: bool):
        """K-step scan with the same mesh shardings as the per-step path;
        stacked feeds shard their PER-STEP dims (the leading steps axis
        stays unsharded — it is scanned over, not distributed)."""
        if not self.use_jit:
            return multi
        mesh = self.mesh
        jitted = {}

        def wrapper(feed_arrays, state, step0):
            key = (tuple(sorted(feed_arrays)), tuple(sorted(state)))
            if key not in jitted:
                lead = 1 if feeds_stacked else 0
                feed_sh = {}
                for n, a in feed_arrays.items():
                    spec = self._feed_spec(program, n, np.ndim(a) - lead,
                                           shape=np.shape(a)[lead:])
                    if feeds_stacked:
                        spec = P(None, *spec)
                    feed_sh[n] = NamedSharding(mesh, spec)
                state_sh = {}
                for k in state:
                    spec = self.param_specs.get(k)
                    if spec is None:
                        v = self._find_var(program, k)
                        if v is not None and getattr(v, "sharding", None):
                            spec = P(*v.sharding)
                    state_sh[k] = NamedSharding(mesh, spec) \
                        if spec is not None else None
                jitted[key] = jax.jit(
                    multi, in_shardings=(feed_sh, state_sh, None),
                    donate_argnums=(1,))
            return jitted[key](feed_arrays, state, step0)

        return wrapper

    def _build(self, program: Program, feed_names, fetch_names,
               state_keys, is_test):
        fn = self._make_fn(program, fetch_names, is_test)
        if not self.use_jit:
            return fn
        mesh = self.mesh

        def shardings_for_call(feed_arrays, state):
            feed_sh = {n: NamedSharding(mesh, self._feed_spec(
                program, n, np.ndim(a), shape=np.shape(a)))
                for n, a in feed_arrays.items()}
            # Pin only explicitly-annotated params; None leaves let jit keep
            # whatever sharding GSPMD propagated onto the arrays (replicated
            # params stay replicated, derived accumulators keep their layout).
            state_sh = {}
            for k in state:
                spec = self.param_specs.get(k)
                if spec is None:
                    v = self._find_var(program, k)
                    if v is not None and getattr(v, "sharding", None):
                        spec = P(*v.sharding)
                state_sh[k] = NamedSharding(mesh, spec) if spec is not None \
                    else None
            return feed_sh, state_sh

        jitted = {}

        def wrapper(feed_arrays, state, step):
            key = (tuple(sorted(feed_arrays)), tuple(sorted(state)))
            if key not in jitted:
                feed_sh, state_sh = shardings_for_call(feed_arrays, state)
                # out_shardings stay unspecified: the produced state set can
                # exceed the fed state (first step materializes accumulators)
                # and GSPMD propagation keeps params on their input shardings.
                jitted[key] = jax.jit(
                    fn,
                    in_shardings=(feed_sh, state_sh, None),
                    donate_argnums=(1,))
            return jitted[key](feed_arrays, state, step)

        return wrapper

    def place_state(self, program: Program, scope=None):
        """Pre-place persistable scope entries with their specs (params get
        Parameter.sharding; others replicate).  Call once after the startup
        program ran — the analog of MultiGradientMachine's value dispatch."""
        from ..core.scope import global_scope
        scope = global_scope() if scope is None else scope
        for name in list(scope.keys()):
            v = self._find_var(program, name)
            if v is None or not v.persistable:
                continue
            spec = self._state_spec(program, name)
            scope.set(name, jax.device_put(
                scope.get(name), NamedSharding(self.mesh, spec)))
