"""ShardedExecutor: the multi-chip training path.

One jit per (program, feed-signature) with explicit ``in_shardings`` /
``out_shardings`` over a named Mesh — GSPMD propagates the annotations and
inserts ICI collectives.  This single mechanism replaces the reference's
MultiGradientMachine ring reduce (MultiGradientMachine.h:60-110), both
parameter servers (paddle/pserver, go/pserver), and the NCCL op family
(operators/nccl/nccl_op.cu.cc) — there is no gradient-exchange code to write
because sharded-batch + replicated-params makes XLA emit the all-reduce.

Parallelism taxonomy (mesh axes, see parallel.mesh):
  dp — feeds sharded on batch dim 0 (data parallel)
  tp — Parameter.sharding PartitionSpecs (Megatron column/row, vocab-sharded
       embeddings — the SelectedRows/CTR analog)
  sp — sequence dim sharding on feeds declared lod_level>0 (NEW vs reference)
  pp/ep — via parallel.pipeline / expert specs on parameters.
"""
from __future__ import annotations

import weakref
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import compile_cache
from ..core.executor import Executor, _specs_sig
from ..core.program import Program
from .mesh import get_mesh


class ShardedExecutor(Executor):
    """Executor whose compiled step carries mesh shardings.

    feed_specs: optional {feed_name: PartitionSpec} overrides.  Default:
    batch dim sharded on ``batch_axis`` (and, when the program var has
    lod_level>0 and the mesh has an 'sp' axis of size>1, time dim on 'sp').
    Parameters use ``Parameter.sharding`` annotations; unannotated state
    replicates.
    """

    def __init__(self, mesh: Optional[Mesh] = None, batch_axis: str = "dp",
                 feed_specs: Optional[Dict[str, P]] = None,
                 param_specs: Optional[Dict[str, P]] = None,
                 num_microbatches: Optional[int] = None,
                 auto_shard: bool = False, **kw):
        super().__init__(**kw)
        self.mesh = mesh or get_mesh()
        self.batch_axis = batch_axis
        self.feed_specs = dict(feed_specs or {})
        self.param_specs = dict(param_specs or {})
        # GPipe microbatch count for pipeline_stage-annotated programs
        # (parallel/pipeline_program.py); default = the 'pp' axis size
        self.num_microbatches = num_microbatches
        # auto_shard=True: when BOTH spec dicts are omitted, the static
        # auto-sharding planner (analysis.planner) proposes them from the
        # first program that carries feeds — the plan is validated against
        # the PT030/PT031 lints before a single trace happens
        self.auto_shard = auto_shard
        self.auto_plan = None

    def _ensure_auto_plan(self, program: Optional[Program]):
        """Plan once, on the first fed program (the startup program has no
        feeds and carries no information the planner wants)."""
        if not self.auto_shard or self.auto_plan is not None:
            return
        if program is None:
            from ..core.program import default_main_program
            program = default_main_program()
        if self.param_specs or self.feed_specs:
            # explicit specs win — auto_shard only fills an omission
            self.auto_plan = False
            return
        if not any(v.is_data for b in program.blocks
                   for v in b.vars.values()):
            return
        from ..analysis import planner
        mesh_axes = {str(a): int(self.mesh.shape[a])
                     for a in self.mesh.axis_names}
        plan = planner.plan(program, mesh_axes,
                            batch_axis=self.batch_axis)
        param_specs, feed_specs = plan.as_partition_specs()
        self.param_specs.update(param_specs)
        self.feed_specs.update(feed_specs)
        self.auto_plan = plan

    def _validation_context(self):
        # the static verifier's sharding lints (PT030/PT031) check
        # Parameter.sharding and these overrides against the mesh
        return self.mesh, self.param_specs, self.feed_specs

    def _observe_label(self) -> str:
        # folded into XProf annotation names and step events so multi-chip
        # dispatches are attributable to their mesh in a device trace;
        # size-1 axes are noise (make_mesh declares all five) — drop them
        axes = [f"{a}{self.mesh.shape[a]}" for a in self.mesh.axis_names
                if self.mesh.shape[a] > 1]
        return "mesh=" + (",".join(axes) or "1")

    # -- sharding selection -------------------------------------------------
    def _find_var(self, program: Program, name: str):
        for b in program.blocks:
            if name in b.vars:
                return b.vars[name]
        return None

    def _feed_spec(self, program: Program, name: str, ndim: int,
                   shape=None) -> P:
        if name in self.feed_specs:
            return self.feed_specs[name]
        if ndim == 0:
            return P()
        base = name[:-4] if name.endswith("@LEN") else name
        v = self._find_var(program, base)
        axes = [self.batch_axis if self.batch_axis in self.mesh.axis_names
                else None]
        if (not name.endswith("@LEN") and v is not None and v.lod_level
                and "sp" in self.mesh.axis_names
                and self.mesh.shape["sp"] > 1 and ndim >= 2
                and (shape is None
                     or shape[1] % self.mesh.shape["sp"] == 0)):
            axes.append("sp")
        axes = axes[:ndim]
        return P(*axes)

    def _state_spec(self, program: Program, name: str) -> P:
        if name in self.param_specs:
            return self.param_specs[name]
        v = self._find_var(program, name)
        if v is not None and getattr(v, "sharding", None):
            return P(*v.sharding)
        return P()

    # -- overrides ----------------------------------------------------------
    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, **kw):
        self._ensure_auto_plan(program)
        with self.mesh:
            return super().run(program, feed=feed, fetch_list=fetch_list,
                               **kw)

    def run_steps(self, num_steps, program=None, feed=None, **kw):
        self._ensure_auto_plan(program)
        with self.mesh:
            return super().run_steps(num_steps, program, feed=feed, **kw)

    def compile(self, program=None, *args, **kw):
        self._ensure_auto_plan(program)
        with self.mesh:
            return super().compile(program, *args, **kw)

    def _fingerprint_extras(self, program: Program):
        """Mesh + sharding-spec fingerprint components: the same program/
        feed signature compiled under a different mesh shape, device set,
        batch axis or spec override is a different executable."""
        mesh = self.mesh
        return ("mesh", tuple(mesh.axis_names),
                tuple(int(mesh.shape[a]) for a in mesh.axis_names),
                tuple(str(d) for d in np.ravel(mesh.devices)),
                self.batch_axis, self.num_microbatches,
                _specs_sig(self.feed_specs),
                _specs_sig(self.param_specs))

    def _state_shardings(self, program: Program, state):
        """Pin only explicitly-annotated params; None leaves let jit keep
        whatever sharding GSPMD propagated onto the arrays (replicated
        params stay replicated, derived accumulators keep their layout)."""
        state_sh = {}
        for k in state:
            spec = self.param_specs.get(k)
            if spec is None:
                v = self._find_var(program, k)
                if v is not None and getattr(v, "sharding", None):
                    spec = P(*v.sharding)
            state_sh[k] = NamedSharding(self.mesh, spec) \
                if spec is not None else None
        return state_sh

    def _sharded_wrapper(self, program: Program, fn, fingerprint, label,
                         feeds_stacked=None):
        """Shared jit wrapper: one CachedStep per argument-name set, with
        mesh shardings pinned on the inputs.  The outer fingerprint already
        covers shapes/dtypes/specs, so in practice each wrapper holds
        exactly one step; the dict guards name-set drift.  ``feeds_stacked``
        None means the per-step path; True/False the K-step scan (stacked
        feeds shard their PER-STEP dims — the leading steps axis is scanned
        over, not distributed).

        The Program is resolved through the step fn's refreshable weakref
        cell (executor._make_fn) rather than captured strongly: a strong
        closure here would defeat ExecCache's dead-program sweeping for
        every sharded entry."""
        mesh = self.mesh
        jitted = {}
        prog_cell = getattr(fn, "prog_cell", None) or \
            [weakref.ref(program)]

        def get_step(feed_arrays, state):
            key = (tuple(sorted(feed_arrays)), tuple(sorted(state)))
            if key not in jitted:
                program = prog_cell[0]()
                if program is None:
                    raise RuntimeError(
                        "sharded step built after its Program was "
                        "garbage-collected (cache entry outlived every "
                        "client program)")
                lead = 1 if feeds_stacked else 0
                feed_sh = {}
                for n, a in feed_arrays.items():
                    spec = self._feed_spec(
                        program, n, len(np.shape(a)) - lead,
                        shape=tuple(np.shape(a))[lead:])
                    if feeds_stacked:
                        spec = P(None, *spec)
                    feed_sh[n] = NamedSharding(mesh, spec)
                # out_shardings stay unspecified: the produced state set can
                # exceed the fed state (first step materializes
                # accumulators) and GSPMD keeps params on input shardings.
                jitted[key] = compile_cache.CachedStep(
                    fn, fingerprint,
                    compiler_options=self._effective_compiler_options(),
                    in_shardings=(feed_sh,
                                  self._state_shardings(program, state),
                                  None),
                    label=label, donate=not self.check_nan_inf)
            return jitted[key]

        def wrapper(feed_arrays, state, step):
            return get_step(feed_arrays, state)(feed_arrays, state, step)

        wrapper.prog_cell = prog_cell
        # AOT hook for Executor.compile: prepare (and return) the inner
        # CachedStep from abstract avals
        wrapper.prepare = lambda feeds, state, step: \
            get_step(feeds, state).prepare(feeds, state, step)
        return wrapper

    def _build_steps(self, program: Program, multi, feeds_stacked: bool,
                     fingerprint=None):
        if not self.use_jit:
            return multi
        return self._sharded_wrapper(program, multi, fingerprint,
                                     "sharded_run_steps",
                                     feeds_stacked=feeds_stacked)

    def _build(self, program: Program, feed_names, fetch_names,
               state_keys, is_test, fingerprint=None):
        fn = self._make_fn(program, fetch_names, is_test)
        if not self.use_jit:
            return fn
        return self._sharded_wrapper(program, fn, fingerprint,
                                     "sharded_run")

    def place_state(self, program: Program, scope=None):
        """Pre-place persistable scope entries with their specs (params get
        Parameter.sharding; others replicate).  Call once after the startup
        program ran — the analog of MultiGradientMachine's value dispatch."""
        from ..core.scope import global_scope
        scope = global_scope() if scope is None else scope
        for name in list(scope.keys()):
            v = self._find_var(program, name)
            if v is None or not v.persistable:
                continue
            spec = self._state_spec(program, name)
            scope.set(name, jax.device_put(
                scope.get(name), NamedSharding(self.mesh, spec)))
