"""Data parallelism over the mesh.

Replaces three reference subsystems at once (SURVEY §2.6):
* MultiGradientMachine's thread-per-GPU ring reduce (MultiGradientMachine.h:60-110)
* the pserver sync-SGD round trip (ParameterServer2.h:341-482)
* fluid's NCCLAllReduce ops (nccl_op.cu.cc:41)

Design: the Executor's compiled step function is wrapped so feeds are sharded
over the 'dp' mesh axis and persistable state is replicated; gradients inside
the ``backward`` lowering are psum'd across 'dp' automatically because XLA
inserts the collective when the batch axis is sharded and params are
replicated.  No parameter server, no gradient queue, no ring thread — one
all-reduce on ICI per step, overlapped by the XLA scheduler.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.executor import Executor
from ..core.program import Program
from ..core.scope import Scope, global_scope
from .mesh import get_mesh


def shard_batch(arrays: Dict[str, np.ndarray], mesh: Mesh, axis="dp"):
    """Place host batches sharded along the dp axis (batch dim 0)."""
    out = {}
    for name, arr in arrays.items():
        spec = P(axis) if np.ndim(arr) >= 1 else P()
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return out


class DataParallel:
    """Wrap an Executor run in dp sharding.

    Usage::

        mesh = make_mesh(MeshConfig(dp=8))
        dp = DataParallel(Executor(), mesh)
        dp.run(program, feed=..., fetch_list=[...])

    The global batch must divide the dp axis size.  Parameters/optimizer
    state stay replicated (the 2017 reference has no ZeRO-style sharding;
    see distributed.checkpoint for sharded saves).
    """

    def __init__(self, executor: Optional[Executor] = None,
                 mesh: Optional[Mesh] = None, batch_axis: str = "dp"):
        self.executor = executor or Executor()
        self.mesh = mesh or get_mesh()
        self.batch_axis = batch_axis

    def run(self, program: Program, feed=None, fetch_list=None,
            scope: Optional[Scope] = None, **kw):
        feed = feed or {}
        scope = global_scope() if scope is None else scope
        n = self.mesh.shape[self.batch_axis]
        for name, arr in feed.items():
            if np.ndim(arr) >= 1 and np.shape(arr)[0] % n != 0:
                raise ValueError(
                    f"feed {name!r} batch {np.shape(arr)[0]} not divisible "
                    f"by dp={n}")
        with self.mesh:
            sharded = shard_batch(feed, self.mesh, self.batch_axis)
            # replicate state on first touch
            for k in list(scope.keys()):
                v = scope.get(k)
                if hasattr(v, "sharding") and not isinstance(
                        v.sharding, NamedSharding):
                    scope.set(k, jax.device_put(
                        v, NamedSharding(self.mesh, P())))
            return self.executor.run(program, feed=sharded,
                                     fetch_list=fetch_list, scope=scope, **kw)
