"""Async-SGD's TPU-native successor: local SGD (periodic parameter
averaging).

Reference capability: asyncSGD (pserver/ParameterServer2.h:468,
trainer/TrainerConfigHelper + async_lagged_grad_discard_ratio) let trainers
apply gradients WITHOUT a global barrier, tolerating staleness to keep slow
workers from stalling the fleet.  On a TPU mesh there is no parameter
server to be async *against* — the analogous capability is to decouple
replicas between syncs:

* each dp replica runs K local SGD steps on its own batch shard with NO
  collective (replica parameters drift, exactly like pserver-era staleness,
  but bounded by K);
* every K steps one pmean restores consensus (one collective per K steps
  instead of per step — the same comm-hiding asyncSGD bought, with a
  deterministic staleness bound instead of unbounded lag).

K=1 reduces to synchronous data parallelism (gradient pmean every step is
replaced by parameter pmean after the update — identical for SGD).  The
async_lagged discard knob maps to choosing K.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import compat

__all__ = ["make_local_sgd_step"]


def make_local_sgd_step(loss_fn, mesh, sync_every: int, learning_rate: float,
                        axis_name: str = "dp"):
    """Build a jitted (params, x, y) -> (params', mean_loss) step running
    ``sync_every`` LOCAL SGD steps per call followed by one parameter pmean.

    loss_fn(params, x, y) -> scalar on one replica's shard; x/y arrive
    [B, ...] and are split B/n per replica on dim 0.  Each call consumes
    ``sync_every`` microbatches sliced from the leading batch dim.
    """
    from ..compat import shard_map

    grad_fn = jax.value_and_grad(loss_fn)

    def per_replica(params, x, y):
        K = sync_every
        # params arrive replicated; mark them device-VARYING so jax.grad
        # inside the body yields each replica's LOCAL gradient (the new
        # shard_map autodiff would otherwise psum cotangents of replicated
        # values on every step — the exact collective local SGD elides)
        params = jax.tree.map(
            lambda p: compat.pvary(p, (axis_name,)), params)
        xs = x.reshape((K, x.shape[0] // K) + x.shape[1:])
        ys = y.reshape((K, y.shape[0] // K) + y.shape[1:])

        def local_step(params, xy):
            xb, yb = xy
            lval, g = grad_fn(params, xb, yb)
            params = jax.tree.map(lambda p, gr: p - learning_rate * gr,
                                  params, g)
            return params, lval

        params, losses = lax.scan(local_step, params, (xs, ys))
        # consensus: one collective per K local steps (the async-SGD
        # communication saving, with staleness bounded by K)
        params = jax.tree.map(lambda p: lax.pmean(p, axis_name), params)
        return params, lax.pmean(jnp.mean(losses), axis_name)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(params, x, y):
        specs = jax.tree.map(lambda _: P(), params)
        f = shard_map(per_replica, mesh=mesh,
                      in_specs=(specs, P(axis_name), P(axis_name)),
                      out_specs=(specs, P()))
        return f(params, x, y)

    return step
