"""Lower a ``pipeline_stage``-annotated Program region onto the 'pp' mesh
axis as a GPipe pipeline — the first-class framework path to pipeline
parallelism.

Users declare stages in the Paddle-style API::

    with pt.pipeline_stage(0):
        h = layers.fc(x, 256, act='relu')
    with pt.pipeline_stage(1):
        h = layers.fc(h, 256, act='relu')

Every op appended inside the context carries a ``pipeline_stage`` attr
(core/program.py).  A plain ``Executor`` ignores the attr and runs the ops
in program order — numerically identical for per-sample stages, which is
exactly what the equivalence test asserts.  A ``ShardedExecutor`` whose
mesh has pp>1 routes the contiguous staged region here
(core/executor.py ``interpret_ops``) and lowers it as:

* one ``jax.shard_map`` manual over ONLY the 'pp' axis (``axis_names=
  {'pp'}``) — dp/tp/sp/ep stay GSPMD-managed, so dp x pp composes without
  hand-sharding the batch;
* inside, a lax.scan over (microbatches + stages - 1) ticks; each device
  runs its own stage via ``lax.switch`` on ``axis_index('pp')`` and
  activations hop stages with ``ppermute`` — differentiable end to end, so
  ``jax.value_and_grad`` through the region yields correct per-stage
  parameter gradients (the psum from the shard_map transpose of the
  replicated-in params zeroes out the stages a device didn't run);
* stage bodies are the op lowerings themselves, interpreted per stage —
  the same code path as single-device execution.

Reference capability frame: ParallelNeuralNetwork.cpp pins whole layers to
devices and pipelines activations through queues (SURVEY §2.6 "Model
parallelism (v1)"; trainer/Flags.cpp:30 --parallel_nn); here the schedule
is a compiled scan and the backward falls out of autodiff instead of
hand-managed backward queues.

Constraints (validated with actionable errors): the staged region must be
contiguous, stage ids 0..S-1 in non-decreasing program order with S equal
to the mesh 'pp' size; exactly one non-persistable activation enters the
region; every inter-stage activation (and the region output) must share
one shape/dtype (the ppermute ring buffer is a single static-shape
tensor); the global batch must divide the microbatch count.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import compat

__all__ = ["lower_pipeline_region"]


def _persistable(ctx, name: str) -> bool:
    for b in ctx.program.blocks:
        if name in b.vars:
            return bool(b.vars[name].persistable)
    return False


def _group_stages(ops: Sequence) -> List[List]:
    """Split region ops into per-stage lists; stage ids must be
    non-decreasing 0..S-1 in program order."""
    stages: List[List] = []
    last = -1
    for op in ops:
        s = int(op.attrs["pipeline_stage"])
        if s < last:
            raise ValueError(
                f"pipeline_stage ids must be non-decreasing in program "
                f"order; op {op.type!r} has stage {s} after stage {last}")
        if s == last:
            stages[-1].append(op)
        else:
            if s != last + 1:
                raise ValueError(
                    f"pipeline_stage ids must be consecutive from 0; "
                    f"found stage {s} after {last}")
            stages.append([op])
            last = s
    return stages


def lower_pipeline_region(ops: Sequence, env, ctx) -> None:
    """Lower one contiguous staged region (see module docstring).  Binds
    the region's output var in ``env``; region-internal intermediates are
    not materialized outside the pipeline."""
    from ..core.executor import Env, run_op

    mesh = ctx.mesh
    S = ctx.pp_size
    stages = _group_stages(ops)
    if len(stages) != S:
        raise ValueError(
            f"program declares {len(stages)} pipeline stages but the mesh "
            f"'pp' axis has size {S}; they must match (declare stages with "
            f"pt.pipeline_stage(i) for i in range({S}))")

    produced = {n for op in ops for n in op.output_names}
    # region inputs in first-use order
    ext_inputs: List[str] = []
    for op in ops:
        for n in op.input_names:
            if n not in produced and n not in ext_inputs:
                ext_inputs.append(n)
    acts = [n for n in ext_inputs if not _persistable(ctx, n)]
    if len(acts) != 1:
        raise ValueError(
            f"a pipeline region must consume exactly one non-persistable "
            f"activation; found {acts or 'none'} (persistable parameters "
            f"are captured per stage automatically)")
    act_in = acts[0]

    # per-stage: captured external inputs + the inter-stage boundary vars
    stage_caps: List[List[str]] = []
    stage_in: List[str] = [act_in]
    for i, sops in enumerate(stages):
        sprod = {n for op in sops for n in op.output_names}
        sins = []
        for op in sops:
            for n in op.input_names:
                if n not in sprod and n != stage_in[i] and n not in sins:
                    sins.append(n)
        bad = [n for n in sins if not _persistable(ctx, n)
               and n not in ext_inputs]
        # vars produced by EARLIER stages (not the immediate boundary) would
        # skip a pipeline hop — unsupported by the single ring buffer
        if bad:
            raise ValueError(
                f"stage {i} consumes {bad}, produced by a non-adjacent "
                f"stage; pipeline stages must form a chain (each stage "
                f"reads only the previous stage's output)")
        stage_caps.append(sins)
        if i < len(stages) - 1:
            cons_next = {n for op in stages[i + 1]
                         for n in op.input_names}
            boundary = [n for n in sprod if n in cons_next]
            if len(boundary) != 1:
                raise ValueError(
                    f"exactly one activation must flow from stage {i} to "
                    f"stage {i + 1}; found {boundary or 'none'}")
            stage_in.append(boundary[0])
    # region output: last stage's product that isn't consumed inside it
    last_prod = [n for op in stages[-1] for n in op.output_names]
    last_cons = {n for op in stages[-1] for n in op.input_names}
    tail = [n for n in last_prod if n not in last_cons]
    out_name = tail[-1] if tail else last_prod[-1]
    stage_out = stage_in[1:] + [out_name]

    block = ops[0].block

    def make_stage_fn(i):
        sops = stages[i]
        in_name, o_name = stage_in[i], stage_out[i]

        def f(caps: Dict[str, object], x):
            senv = Env(block)
            senv.local.update(caps)
            senv.local[in_name] = x
            for op in sops:
                run_op(op, senv, ctx)
            return senv.get(o_name)

        return f

    stage_fns = [make_stage_fn(i) for i in range(S)]
    caps_tuple = tuple({n: env.get(n) for n in stage_caps[i]}
                       for i in range(S))
    x_val = env.get(act_in)

    M = int(ctx.pipeline_microbatches or S)
    B = x_val.shape[0]
    if B % M != 0:
        raise ValueError(
            f"num_microbatches={M} must divide the global batch {B} "
            f"(ShardedExecutor(num_microbatches=...))")
    mb = B // M

    # validate: every inter-stage activation and the output share one
    # shape/dtype — the ppermute ring buffer is one static tensor
    aval = jax.ShapeDtypeStruct((mb,) + tuple(x_val.shape[1:]), x_val.dtype)
    outs_avals = []
    for i in range(S):
        aval = jax.eval_shape(stage_fns[i], caps_tuple[i], aval)
        outs_avals.append(aval)
    uniform = {(a.shape, str(a.dtype)) for a in outs_avals}
    if len(uniform) != 1:
        raise ValueError(
            f"pipeline stages must produce one common activation "
            f"shape/dtype (the inter-stage ring buffer is static); got "
            f"{[(stage_out[i], outs_avals[i].shape, str(outs_avals[i].dtype)) for i in range(S)]}")
    y_aval = outs_avals[-1]

    perm = [(d, (d + 1) % S) for d in range(S)]

    def region_fn(caps, x):
        idx = lax.axis_index("pp")
        xs = x.reshape((M, mb) + tuple(x.shape[1:]))

        def tick(carry, t):
            buf, outs = carry
            x0 = xs[jnp.clip(t, 0, M - 1)]

            def branch(i):
                # stage 0 reads the injected microbatch, others the ring
                return lambda args: stage_fns[i](
                    caps[i], args[0] if i == 0 else args[1])

            y = lax.switch(idx, [branch(i) for i in range(S)], (x0, buf))
            slot = t - (S - 1)
            valid = (idx == S - 1) & (slot >= 0)
            slot_c = jnp.clip(slot, 0, M - 1)
            cur = lax.dynamic_index_in_dim(outs, slot_c, keepdims=False)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, y, cur), slot_c, 0)
            return (lax.ppermute(y, "pp", perm), outs), None

        buf0 = jnp.zeros(y_aval.shape, y_aval.dtype)
        outs0 = jnp.zeros((M,) + y_aval.shape, y_aval.dtype)
        (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(M + S - 1))
        # only the last stage holds real results; psum broadcasts them so
        # the region output is replicated over pp
        outs = lax.psum(
            jnp.where(idx == S - 1, outs, jnp.zeros_like(outs)), "pp")
        return outs.reshape((B,) + tuple(y_aval.shape[1:]))

    caps_specs = jax.tree.map(lambda _: P(), caps_tuple)
    y = compat.shard_map(
        region_fn, mesh=mesh, in_specs=(caps_specs, P()), out_specs=P(),
        axis_names=frozenset({"pp"}), check_vma=False)(caps_tuple, x_val)
    env.set(out_name, y)
