"""Mixture-of-Experts layer with expert-parallel all-to-all dispatch.

Reference capability frame: the closest ancestors are the v1 per-layer
device placement (ParallelNeuralNetwork.cpp) and sparse gradient machinery
(SelectedRows / row-sparse CTR); the reference never shipped MoE, so this is
capability-forward surface the ep mesh axis exists for.

TPU-native design (Switch/GShard style, static shapes throughout):
tokens pick their top-k experts by a learned gate; a [T, E, C] one-hot
dispatch tensor (capacity C per expert, overflow tokens dropped — residual
connections carry them) turns routing into einsums that ride the MXU; the
[E, C, D] expert batches hop devices with ONE all_to_all over the 'ep' axis
each way (ICI), each device runs only its local experts' FFNs, and the
combine einsum restores token order weighted by gate probabilities.  The
load-balancing auxiliary loss is the standard E * sum(fraction_e * prob_e).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import compat

__all__ = ["moe_dispatch", "moe_ffn", "load_balancing_loss"]


def _axis_size(axis_name):
    if axis_name is None:
        return 1
    try:
        return compat.axis_size(axis_name)
    except NameError:
        return 1


def moe_dispatch(gates, capacity: int, top_k: int = 2):
    """Routing tensors from gate probabilities.

    gates: [T, E] softmax probabilities.  Returns (dispatch [T, E, C] {0,1},
    combine [T, E, C] floats).  Token t goes to its k highest-probability
    experts, subject to each expert accepting at most ``capacity`` tokens
    (first-come order, GShard §3.2); overflow slots are dropped.
    """
    T, E = gates.shape
    dispatch = jnp.zeros((T, E, capacity), gates.dtype)
    combine = jnp.zeros((T, E, capacity), gates.dtype)
    masked = gates
    # occupancy carried across the k rounds so round-2 picks respect slots
    # taken in round 1
    occupancy = jnp.zeros((E,), jnp.int32)
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=1)                    # [T]
        mask = jax.nn.one_hot(idx, E, dtype=gates.dtype)    # [T, E]
        pos = occupancy[None, :] + (
            jnp.cumsum(mask, axis=0) - mask).astype(jnp.int32)  # [T, E]
        keep = mask * (pos < capacity)
        pos_t = jnp.sum(pos * mask, axis=1).astype(jnp.int32)   # [T]
        slot = jax.nn.one_hot(jnp.clip(pos_t, 0, capacity - 1),
                              capacity, dtype=gates.dtype)      # [T, C]
        d = keep[:, :, None] * slot[:, None, :]
        dispatch = dispatch + d
        combine = combine + d * jnp.sum(gates * mask, axis=1)[:, None, None]
        occupancy = occupancy + jnp.sum(keep, axis=0).astype(jnp.int32)
        masked = masked * (1.0 - mask)      # exclude picked expert next round
    return dispatch, combine


def load_balancing_loss(gates, dispatch):
    """E * sum_e(mean-fraction-of-tokens_e * mean-gate-prob_e) — the
    Switch-Transformer aux loss keeping experts evenly loaded."""
    E = gates.shape[1]
    frac = jnp.mean(jnp.sum(dispatch, axis=2), axis=0)   # [E] token fraction
    prob = jnp.mean(gates, axis=0)                       # [E]
    return E * jnp.sum(frac * prob)


def moe_ffn(x, gate_w, expert_w1, expert_w2, axis_name="ep", top_k=2,
            capacity_factor=1.25, activation=jax.nn.relu):
    """Expert-parallel MoE FFN for one device's tokens.

    x [T, D] this device's tokens; gate_w [D, E] (replicated);
    expert_w1 [E_local, D, H], expert_w2 [E_local, H, D] — THIS device's
    expert slice (shard the stacked weights P('ep', ...)).  E = E_local *
    axis_size.  Returns (out [T, D], aux_loss scalar).  Outside shard_map
    (axis absent) it degrades to a single-device MoE over all experts.
    """
    T, D = x.shape
    n = _axis_size(axis_name)
    e_local = expert_w1.shape[0]
    E = e_local * n
    logits = x @ gate_w                                  # [T, E]
    gates = jax.nn.softmax(logits, axis=-1)
    capacity = max(1, int(capacity_factor * top_k * T / E))
    dispatch, combine = moe_dispatch(gates, capacity, top_k)
    aux = load_balancing_loss(gates, dispatch)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)   # [E, C, D]
    if n > 1:
        # hop out (tiled all_to_all): the expert axis splits into n chunks
        # of e_local — chunk j travels to the device owning those experts —
        # and the n source batches concatenate on the token axis:
        #   [E, C, D] -> [e_local, n*C, D]
        arrived = lax.all_to_all(expert_in, axis_name, split_axis=0,
                                 concat_axis=1, tiled=True)
    else:
        arrived = expert_in

    h = activation(jnp.einsum("ecd,edh->ech", arrived, expert_w1))
    out_e = jnp.einsum("ech,ehd->ecd", h, expert_w2)

    if n > 1:
        # inverse hop: [e_local, n*C, D] -> [E, C, D], returning each
        # source's rows (the exact transpose of the hop out)
        returned = lax.all_to_all(out_e, axis_name, split_axis=1,
                                  concat_axis=0, tiled=True)
    else:
        returned = out_e

    out = jnp.einsum("tec,ecd->td", combine, returned)
    return out, aux
