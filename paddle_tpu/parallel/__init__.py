"""Parallelism over the device mesh.

Replaces ALL FOUR of the reference's communication backends (SURVEY §2.6:
v1 pserver epoll RPC, Go pserver/master, fluid gRPC send/recv, NCCL ops) with
XLA collectives over a ``jax.sharding.Mesh``:

* data parallel  — MultiGradientMachine / pserver / NCCLAllReduce →
  batch-sharded ``pjit`` with psum'd gradients riding ICI.
* model parallel — ParallelNeuralNetwork's per-layer device placement →
  tensor-parallel PartitionSpecs on parameters (Megatron-style for fc).
* NEW capabilities beyond the reference (required by the rebuild spec):
  sequence/context parallelism incl. ring attention, pipeline and expert
  scaffolds.
"""
from .mesh import (MeshConfig, get_mesh, make_mesh, mesh_for_axes,
                   mesh_guard)
from .collective import (all_gather, all_reduce, broadcast, psum,
                         reduce_scatter, ppermute, barrier)
from .data_parallel import DataParallel, shard_batch
from .tensor_parallel import column_parallel_spec, row_parallel_spec, \
    shard_params
from .ring_attention import ring_attention
from .sharded import ShardedExecutor
from .embedding import sharded_lookup
from . import pipeline
from . import collective
from . import embedding
from . import moe
from .moe import moe_ffn
from . import local_sgd
from .local_sgd import make_local_sgd_step

__all__ = [
    "MeshConfig", "get_mesh", "make_mesh", "mesh_for_axes", "mesh_guard",
    "all_gather", "all_reduce", "broadcast", "psum", "reduce_scatter",
    "ppermute", "barrier", "DataParallel", "shard_batch",
    "column_parallel_spec", "row_parallel_spec", "shard_params",
    "ring_attention", "ShardedExecutor", "pipeline", "sharded_lookup",
    "embedding", "collective",
]
