"""Collective primitives over mesh axes.

The TPU-native replacement for the reference's entire communication stack:
NCCLAllReduce/Reduce/Bcast kernels (operators/nccl/nccl_op.cu.cc:41-153), the
v1 pserver gradient exchange (ParameterServer2::addGradient/sendParameter),
and fluid's gRPC send/recv ops.  Inside shard_map these lower to XLA
collectives scheduled on ICI; outside they are jnp no-ops so the same model
code runs single-chip.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import compat


def _in_spmd(axis_name) -> bool:
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def psum(x, axis_name="dp"):
    try:
        return lax.psum(x, axis_name)
    except NameError:
        return x


def all_reduce(x, axis_name="dp", op="sum"):
    try:
        if op == "sum":
            return lax.psum(x, axis_name)
        if op == "mean":
            return lax.pmean(x, axis_name)
        if op == "max":
            return lax.pmax(x, axis_name)
        if op == "min":
            return lax.pmin(x, axis_name)
    except NameError:
        return x
    raise ValueError(f"unknown all_reduce op {op}")


def all_gather(x, axis_name="tp", axis=0, tiled=True):
    try:
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    except NameError:
        return x


def reduce_scatter(x, axis_name="dp", axis=0):
    try:
        return lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)
    except NameError:
        return x


def ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm)


def broadcast(x, axis_name="dp", src=0):
    """Select src's value on every member (NCCLBcast analog)."""
    try:
        idx = lax.axis_index(axis_name)
    except NameError:
        return x
    n = compat.axis_size(axis_name)
    return lax.ppermute(x, axis_name, [(src, i) for i in range(n)])


def barrier(axis_name="dp"):
    """pserver synchronize() analog: a psum forces a rendezvous."""
    return psum(jnp.ones(()), axis_name)


def all_to_all(x, axis_name, split_axis, concat_axis):
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)
