"""Ring attention: sequence/context parallelism over the 'sp' mesh axis.

A NEW capability relative to the 2017 reference (SURVEY §2.6 confirms the
reference has no sequence parallelism — long sequences were handled by LoD
packing only).  Required by the rebuild spec for long-context scaling.

Blockwise ring attention (Liu et al.): each sp shard holds a query block and
circulates key/value blocks around the ring with ppermute, maintaining
numerically-stable streaming softmax statistics (m, l) so the result is exact
full attention.  Communication overlaps compute; memory is O(T/sp).
Use inside shard_map with sequences sharded on 'sp'.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import compat


def _block_attn(q, k, v, bias=None):
    """Stable block attention returning (out_unnorm, m, l)."""
    s = jnp.einsum("...qd,...kd->...qk", q, k)
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("...qk,...kd->...qd", p, v)
    return o, m, l


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   scale: float = None, use_flash=None, block_q: int = 256,
                   block_k: int = 256, interpret: bool = False):
    """Exact attention with K/V circulated around the sp ring.

    q,k,v: [B, T_local, H, D] (local sequence shard).  Returns [B,T_local,H,D].
    With ``causal``, blocks wholly in the future are skipped via masking
    (shapes stay static; the mask zeroes their contribution).

    ``use_flash`` (default: auto on TPU when block-divisible) computes each
    ring hop with the fused Pallas flash kernel via its (out, lse)
    residuals and merges hops by streaming-softmax — O(T_local) memory per
    hop instead of the [T_local, T_local] score matrix, composing the two
    long-context mechanisms (ring over ICI x flash in VMEM).
    """
    T_loc = q.shape[1]
    divisible = (T_loc % min(block_q, T_loc) == 0
                 and T_loc % min(block_k, T_loc) == 0)
    if use_flash is None:
        import jax as _jax
        from ..ops.pallas_kernels import _HAVE_PALLAS
        use_flash = (_HAVE_PALLAS and _jax.default_backend() == "tpu"
                     and divisible)
    # non-divisible local blocks always fall back to the exact jnp path —
    # same policy as the device-global wrapper, so forcing the kernel via
    # use_flash/interpret degrades instead of raising mid-training
    if (use_flash or interpret) and divisible:
        return _ring_attention_flash(q, k, v, axis_name, causal, scale,
                                     block_q, block_k, interpret)
    return _ring_attention_jnp(q, k, v, axis_name, causal, scale)


def _ring_attention_flash(q, k, v, axis_name, causal, scale, block_q,
                          block_k, interpret):
    from ..ops.pallas_kernels import flash_attention_with_lse

    n = compat.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5

    def flat(x):
        return jnp.moveaxis(x, 2, 1).reshape(B * H, T, x.shape[-1])

    q3, k3, v3 = flat(q), flat(k), flat(v)
    in_dtype = q.dtype
    perm = [(i, (i + 1) % n) for i in range(n)]

    # hop 0 is ALWAYS this device's own K/V block (the causal diagonal), so
    # the kernel's static causal flag is exact here; later hops are whole
    # past/future blocks — full kernel plus a merge-level mask
    out, lse = flash_attention_with_lse(q3, k3, v3, causal=causal,
                                        sm_scale=scale, block_q=block_q,
                                        block_k=block_k, interpret=interpret)
    # the streaming merge runs in f32 (lse is f32); cast back after the ring
    out = out.astype(jnp.float32)
    kc = lax.ppermute(k3, axis_name, perm)
    vc = lax.ppermute(v3, axis_name, perm)

    def step(carry, i):
        kc, vc, out, lse = carry
        src = (my - i) % n
        o_b, lse_b = flash_attention_with_lse(
            q3, kc, vc, causal=False, sm_scale=scale, block_q=block_q,
            block_k=block_k, interpret=interpret)
        if causal:
            # future blocks (src > my) contribute nothing: -inf lse zeroes
            # their merge weight while shapes stay static
            lse_b = jnp.where(src < my, lse_b, -jnp.inf)
        m = jnp.maximum(lse, lse_b)
        a = jnp.exp(lse - m)
        b = jnp.exp(lse_b - m)
        denom = jnp.maximum(a + b, 1e-38)
        out = (out * a + o_b.astype(jnp.float32) * b) / denom
        lse = m + jnp.log(denom)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (kc, vc, out, lse), None

    if n > 1:
        (_, _, out, _), _ = lax.scan(step, (kc, vc, out, lse),
                                     jnp.arange(1, n))
    out = out.astype(in_dtype)
    return jnp.moveaxis(out.reshape(B, H, T, v.shape[-1]), 1, 2)


def _ring_attention_jnp(q, k, v, axis_name, causal, scale):
    n = compat.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    q = q * scale
    # work in [B, H, T, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    T = qh.shape[2]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def bias_for(src_idx):
        if not causal:
            return None
        # global positions: my block rows, src block cols
        qpos = my * T + jnp.arange(T)[:, None]
        kpos = src_idx * T + jnp.arange(T)[None, :]
        return jnp.where(kpos <= qpos, 0.0, -1e30)

    def step(carry, i):
        kh_c, vh_c, o, m, l = carry
        src = (my - i) % n            # whose kv block we currently hold
        bias = bias_for(src)
        o_b, m_b, l_b = _block_attn(qh, kh_c, vh_c, bias)
        m_new = jnp.maximum(m, m_b)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_b - m_new)
        o = o * alpha + o_b * beta
        l = l * alpha + l_b * beta
        kh_n = lax.ppermute(kh_c, axis_name, perm)
        vh_n = lax.ppermute(vh_c, axis_name, perm)
        return (kh_n, vh_n, o, m_new, l), None

    o0 = jnp.zeros_like(qh)
    # derive from qh so the carries inherit its varying-manual-axes type
    # under shard_map (a constant init would fail lax.scan's carry check)
    m0 = jnp.full_like(qh[..., :1], -1e30)
    l0 = jnp.zeros_like(qh[..., :1])
    (_, _, o, m, l), _ = lax.scan(
        step, (kh, vh, o0, m0, l0), jnp.arange(n))
    out = o / jnp.maximum(l, 1e-20)
    return jnp.swapaxes(out, 1, 2)


def ring_attention_sharded(q, k, v, mesh, causal=False, axis_name: str = "sp",
                           scale=None, block_q: int = 1024,
                           block_k: int = 1024, use_flash=None,
                           interpret: bool = False):
    """Global-array entry point: partial-manual shard_map over ONLY the sp
    axis (dp/tp stay GSPMD-managed, mirroring pipeline_program.py), with
    :func:`ring_attention` inside.  q,k,v: global [B, T, H, D]; returns the
    same global shape, time axis sharded on ``axis_name``.

    This is what the ``flash_attention`` op lowering calls when the mesh has
    sp>1 — the first-class framework path to sequence parallelism: a
    Paddle-API user writes ``layers.flash_attention(...)`` (or
    ``nets.scaled_dot_product_attention``) and long sequences shard over the
    ring without touching shard_map themselves.
    """
    spec = P(None, axis_name)
    body = functools.partial(ring_attention, axis_name=axis_name,
                             causal=causal, scale=scale, block_q=block_q,
                             block_k=block_k, use_flash=use_flash,
                             interpret=interpret)
    return compat.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis_name}), check_vma=False)(q, k, v)


def sequence_parallel_attention(q, k, v, axis_name="sp", causal=False):
    """Ulysses-style all-to-all alternative: swap sequence sharding for head
    sharding, run full attention locally, swap back.  Prefer when head count
    is divisible by sp and sequence length is moderate."""
    # [B, T/s, H, D] -> all_to_all -> [B, T, H/s, D]
    qt = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kt = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vt = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    d = qt.shape[-1]
    s = jnp.einsum("bthd,bshd->bhts", qt * (d ** -0.5), kt)
    if causal:
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhts,bshd->bthd", p, vt)
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
