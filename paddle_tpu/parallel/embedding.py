"""Sharded embedding tables — the CTR/sparse machinery on a mesh.

Reference capability being replaced (SURVEY §2.5-2.6): row-sparse embedding
storage + prefetch (SparseRowCpuMatrix/SparsePrefetchRowCpuMatrix,
SparseRowMatrix.h:31,206), the SparseRemoteParameterUpdater fetching only
the rows a batch touches (RemoteParameterUpdater.h:265), and SelectedRows
gradients (selected_rows.h:19, lookup_table_op sparse grad path).

TPU-native design: the table lives vocab-sharded over a mesh axis
(P('tp', None)).  Two lookup strategies:

* GSPMD path (default): a plain gather on the sharded table — XLA partitions
  it into local gathers + collectives automatically.  Used by
  layers.embedding when the Parameter carries sharding=('tp', None).
* Manual shard_map path (``sharded_lookup``): each device resolves hits in
  its local vocab shard and psums partial rows — explicit control for use
  inside shard_map kernels (mirrors the reference's row-prefetch protocol,
  one all-reduce instead of a pserver round trip).

Gradients: the gather's vjp is a scatter-add, which GSPMD keeps sharded —
the SelectedRows update without any sparse-row bookkeeping.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import compat


def sharded_lookup(local_table, ids, axis_name="tp"):
    """Lookup into a vocab-sharded table inside shard_map.

    local_table: [V/n, D] this member's shard (row r holds global row
    ``offset + r``).  ids: int [...] global row ids (replicated).
    Returns [..., D] replicated — one psum over the axis.
    """
    n = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    vshard = local_table.shape[0]
    offset = idx * vshard
    local = ids - offset
    hit = (local >= 0) & (local < vshard)
    safe = jnp.clip(local, 0, vshard - 1)
    rows = local_table[safe]
    rows = jnp.where(hit[..., None], rows, jnp.zeros_like(rows))
    return lax.psum(rows, axis_name)


def sharded_lookup_grad_rows(ids, grad_out, vocab_size, axis_name="tp"):
    """Scatter-add grads back to this member's shard (SelectedRows apply).

    Utility for hand-rolled shard_map training loops; under jit+GSPMD this
    is derived automatically from sharded_lookup's vjp.
    """
    n = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    vshard = vocab_size // n
    offset = idx * vshard
    local = ids - offset
    hit = (local >= 0) & (local < vshard)
    safe = jnp.where(hit, local, 0)
    g = jnp.where(hit[..., None], grad_out, jnp.zeros_like(grad_out))
    shard = jnp.zeros((vshard, grad_out.shape[-1]), grad_out.dtype)
    return shard.at[safe.reshape(-1)].add(
        g.reshape(-1, grad_out.shape[-1]))
