"""Tensor (model) parallelism: parameter PartitionSpecs.

The reference's model parallelism is per-layer device placement
(ParallelNeuralNetwork.cpp, `--parallel_nn` Flags.cpp:30) — whole layers on
different GPUs with activations shipped between them.  The TPU-native version
shards *within* layers: fc/embedding weights get Megatron-style column/row
specs on the 'tp' mesh axis and XLA inserts the all-gather/reduce-scatter
pairs on ICI.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.program import Program
from ..core.scope import Scope


def column_parallel_spec():
    """fc weight [in, out] sharded on out — activations gather on 'tp'."""
    return P(None, "tp")


def row_parallel_spec():
    """fc weight [in, out] sharded on in — outputs psum on 'tp'."""
    return P("tp", None)


def embedding_parallel_spec():
    """vocab-sharded embedding [V, D] (the SelectedRows/CTR table analog —
    SparseRowMatrix.h:31 machinery becomes a sharded gather)."""
    return P("tp", None)


def shard_params(program: Program, scope: Scope, mesh: Mesh,
                 overrides: Optional[Dict[str, P]] = None):
    """Apply Parameter.sharding annotations (set via ParamAttr(sharding=...))
    or explicit overrides, placing scope arrays accordingly.  Un-annotated
    params replicate."""
    overrides = overrides or {}
    for p in program.all_parameters():
        if not scope.has(p.name):
            continue
        spec = overrides.get(p.name)
        if spec is None and p.sharding is not None:
            spec = P(*p.sharding)
        if spec is None:
            spec = P()
        scope.set(p.name, jax.device_put(
            scope.get(p.name), NamedSharding(mesh, spec)))
