"""Pipeline parallelism scaffold over the 'pp' mesh axis.

The reference's nearest ancestor is ParallelNeuralNetwork: whole layers
pinned to devices with queue-pipelined activations (SURVEY §2.6 "Model
parallelism (v1)").  The TPU-native version is GPipe-style microbatching
inside shard_map: each pp stage applies its layer stack, activations hop to
the next stage with ppermute, and a scan over (microbatches + stages - 1)
ticks keeps every stage busy after warmup.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_forward(stage_fn: Callable, params, x_microbatches,
                     axis_name: str = "pp"):
    """Run microbatches through a pipeline of stages.

    stage_fn(params, x) -> y is THIS stage's computation (same signature on
    every member; params differ per stage).  x_microbatches: [M, ...] stacked
    microbatches (only stage 0's input matters; others ignore it).
    Returns [M, ...] outputs valid on the LAST stage.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    ticks = M + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t (if in range); others use what arrived
        inject = jnp.where(t < M, t, M - 1)
        x0 = x_microbatches[inject]
        x = jnp.where(idx == 0, x0, buf)
        y = stage_fn(params, x)
        # last stage records its result at slot t-(n-1)
        slot = t - (n - 1)
        valid = (idx == n - 1) & (slot >= 0)
        slot_c = jnp.clip(slot, 0, M - 1)
        cur = lax.dynamic_index_in_dim(outs, slot_c, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, y, cur), slot_c, 0)
        buf_next = lax.ppermute(y, axis_name, perm)
        return (buf_next, outs), None

    buf0 = jnp.zeros_like(stage_fn(params, x_microbatches[0]))
    outs0 = jnp.zeros((M,) + buf0.shape, buf0.dtype)
    # carries become device-varying (ppermute / axis_index); mark the inits
    buf0 = lax.pvary(buf0, (axis_name,))
    outs0 = lax.pvary(outs0, (axis_name,))
    (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(ticks))
    # only the last stage holds real results; psum broadcasts them so the
    # output is replicated over pp (callers can use out_specs=P())
    return lax.psum(jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
