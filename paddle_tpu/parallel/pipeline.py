"""Pipeline parallelism over the 'pp' mesh axis — per-stage parameters,
GPipe microbatching, differentiable end to end.

The reference's nearest ancestor is ParallelNeuralNetwork.cpp: whole layers
pinned to devices with queue-pipelined activations (SURVEY §2.6 "Model
parallelism (v1)").  The TPU-native redesign:

* Stage parameters are STACKED on a leading [n_stages, ...] axis and sharded
  ``PartitionSpec('pp', ...)`` — each device physically holds only its own
  stage's weights (true model-memory scaling, not a replicated-weight
  scaffold).  Inside ``shard_map`` every device sees its [1, ...] slice.
* The forward is a lax.scan over (microbatches + stages - 1) ticks;
  activations hop stages with ppermute.  Every collective is differentiable,
  so ``jax.grad`` through the whole pipelined step yields per-stage gradients
  with the SAME 'pp' sharding — the backward pipeline falls out of autodiff
  rather than being hand-scheduled (contrast the reference's explicit
  backward activation queues).
* ``remat=True`` wraps each stage in jax.checkpoint: activation memory drops
  to O(microbatch) and the backward replays stage forwards — the GPipe
  recompute schedule.

Heterogeneous stacks (stages that cannot share one stacked pytree) can still
pipeline compute via ``switch_stage_fn`` (lax.switch on the stage index with
replicated params) — pipelined time, unsharded memory; a documented
tradeoff, with uniform stacked stages as the first-class path.
"""
from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import compat

__all__ = ["pipeline_forward", "pipeline_spmd_fn", "stack_stage_params",
           "place_stage_params", "make_pipeline_train_step",
           "switch_stage_fn"]


def stack_stage_params(*stages):
    """Stack S same-structure per-stage pytrees into one pytree whose leaves
    carry a leading [S, ...] stage axis (to be sharded P('pp', ...))."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *stages)


def place_stage_params(params, mesh, axis_name: str = "pp"):
    """device_put stacked stage params so the stage axis lives on ``pp``."""
    def put(x):
        spec = P(axis_name, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree.map(put, params)


def pipeline_forward(stage_fn: Callable, stage_params, x_microbatches,
                     axis_name: str = "pp"):
    """GPipe forward inside shard_map.

    stage_fn(params, x) -> y: one stage's computation.  ``stage_params`` is
    THIS device's slice of the stacked params — leaves [1, ...] (shard_map
    over P('pp', ...)); the leading axis is squeezed before stage_fn sees
    it.  x_microbatches: [M, ...] stacked microbatches (stage 0 injects
    them).  Returns [M, ...] last-stage outputs, replicated over the axis.
    """
    n = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    my = jax.tree.map(lambda x: x[0], stage_params)
    M = x_microbatches.shape[0]
    ticks = M + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]
    out_aval = jax.eval_shape(functools.partial(stage_fn, my),
                              x_microbatches[0])

    def tick(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t (if in range); others use what arrived
        inject = jnp.where(t < M, t, M - 1)
        x0 = x_microbatches[inject]
        x = jnp.where(idx == 0, x0, buf)
        y = stage_fn(my, x)
        # last stage records its result at slot t-(n-1)
        slot = t - (n - 1)
        valid = (idx == n - 1) & (slot >= 0)
        slot_c = jnp.clip(slot, 0, M - 1)
        cur = lax.dynamic_index_in_dim(outs, slot_c, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, y, cur), slot_c, 0)
        buf_next = lax.ppermute(y, axis_name, perm)
        return (buf_next, outs), None

    buf0 = jnp.zeros(out_aval.shape, out_aval.dtype)
    outs0 = jnp.zeros((M,) + buf0.shape, buf0.dtype)
    # carries become device-varying (ppermute / axis_index); mark the inits
    buf0 = compat.pvary(buf0, (axis_name,))
    outs0 = compat.pvary(outs0, (axis_name,))
    (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(ticks))
    # only the last stage holds real results; psum broadcasts them so the
    # output is replicated over pp (callers can use out_specs=P())
    return lax.psum(jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)


def pipeline_spmd_fn(stage_fn: Callable, loss_fn: Callable, mesh,
                     num_microbatches: int, axis_name: str = "pp",
                     remat: bool = False):
    """Build loss(params, x, y) running the stacked-params GPipe pipeline
    under shard_map — differentiable, so jax.grad(loss) yields gradients
    sharded P('pp', ...) exactly like the params.

    stage_fn(stage_params, x) -> y;  loss_fn(last_stage_out, labels) ->
    scalar per microbatch.  x: [B, ...] global batch with
    B % num_microbatches == 0; labels likewise.
    """
    from ..compat import shard_map

    sfn = jax.checkpoint(stage_fn) if remat else stage_fn

    def per_device(params, x, y):
        M = num_microbatches
        xs = x.reshape((M, x.shape[0] // M) + x.shape[1:])
        ys = y.reshape((M, y.shape[0] // M) + y.shape[1:])
        outs = pipeline_forward(sfn, params, xs, axis_name)
        losses = jax.vmap(loss_fn)(outs, ys)
        return jnp.mean(losses)

    def loss(params, x, y):
        param_specs = jax.tree.map(
            lambda v: P(axis_name, *([None] * (v.ndim - 1))), params)
        f = shard_map(per_device, mesh=mesh,
                      in_specs=(param_specs, P(), P()), out_specs=P())
        return f(params, x, y)

    return loss


def make_pipeline_train_step(stage_fn: Callable, loss_fn: Callable, mesh,
                             num_microbatches: int, learning_rate: float,
                             momentum: float = 0.0, axis_name: str = "pp",
                             remat: bool = False):
    """jitted (params, velocity, x, y) -> (params', velocity', loss): GPipe
    training step with SGD(+momentum) on the pp-sharded stage params
    (updates are elementwise, so they preserve the 'pp' placement)."""
    loss = pipeline_spmd_fn(stage_fn, loss_fn, mesh, num_microbatches,
                            axis_name, remat=remat)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, velocity, x, y):
        lval, grads = jax.value_and_grad(loss)(params, x, y)
        velocity = jax.tree.map(lambda v, g: momentum * v + g, velocity,
                                grads)
        params = jax.tree.map(lambda p, v: p - learning_rate * v, params,
                              velocity)
        return params, velocity, lval

    return step


def switch_stage_fn(stage_fns: Sequence[Callable], params_tuple,
                    axis_name: str = "pp"):
    """Adapter for HETEROGENEOUS stages: returns stage_fn(_, x) that
    lax.switches on this device's stage index over ``stage_fns`` with the
    matching pytree from ``params_tuple`` (closed over, passed REPLICATED —
    compute is pipelined, memory is not sharded).  Inter-stage activations
    must share one shape/dtype."""
    def fn(_, x):
        idx = lax.axis_index(axis_name)
        branches = [functools.partial(lambda f, p, xx: f(p, xx), f, p)
                    for f, p in zip(stage_fns, params_tuple)]
        return lax.switch(idx, branches, x)
    return fn
