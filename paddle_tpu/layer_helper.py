"""LayerHelper: shared parameter/bias/activation plumbing for layer functions
(reference: fluid/layer_helper.py:10)."""
from __future__ import annotations

from typing import Optional

from .core import unique_name
from .core.program import (Parameter, Variable, default_main_program,
                           default_startup_program)
from .initializer import (ConstantInitializer, Initializer,
                          XavierInitializer)
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return self.kwargs.get("main_program") or default_main_program()

    @property
    def startup_program(self):
        return self.kwargs.get("startup_program") or default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer: Optional[Initializer] = None
                         ) -> Parameter:
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        from .param_attr import WeightNormParamAttr
        if isinstance(attr, WeightNormParamAttr) and not is_bias:
            return self._create_weight_normed(attr, shape, dtype,
                                              default_initializer)
        suffix = "b" if is_bias else "w"
        name = attr.name or unique_name.generate(f"{self.name}.{suffix}")
        init = attr.initializer or default_initializer or (
            ConstantInitializer(0.0) if is_bias else XavierInitializer())
        shape = [int(s) for s in shape]
        # declare in main program (block 0) ...
        kw = ParamAttr(None, None, attr.learning_rate, attr.regularizer,
                       attr.trainable, attr.gradient_clip,
                       attr.sharding).to_kwargs()
        kw.pop("name", None)
        p = self.block.create_parameter(name=name, shape=shape, dtype=dtype,
                                        **kw)
        # ... and emit its initializer into the startup program
        sb = self.startup_program.global_block()
        sv = sb.create_var(name=name, shape=shape, dtype=dtype,
                           persistable=True)
        init(sv, sb)
        return p

    def _create_weight_normed(self, attr, shape, dtype,
                              default_initializer):
        """WeightNormParamAttr: trainable direction v and magnitude g with
        w = g * v/||v|| recomputed in-graph every step (fluid
        param_attr.py WeightNormParamAttr semantics)."""
        from .param_attr import ParamAttr as _PA
        base = _PA(name=attr.name, initializer=attr.initializer,
                   learning_rate=attr.learning_rate,
                   regularizer=attr.regularizer, trainable=attr.trainable,
                   gradient_clip=attr.gradient_clip, sharding=attr.sharding)
        v = self.create_parameter(base, shape, dtype,
                                  default_initializer=default_initializer)
        dim = attr.dim
        g_shape = [shape[dim]] if dim is not None else [1]
        g_attr = _PA(name=(attr.name + ".g") if attr.name else None,
                     initializer=ConstantInitializer(1.0),
                     learning_rate=attr.learning_rate,
                     trainable=attr.trainable)
        g = self.create_parameter(g_attr, g_shape, dtype)
        return _append_weight_norm_ops(self, v, g, dim, shape, dtype)

    def create_variable_for_type_inference(self, dtype, shape=None,
                                           lod_level=0) -> Variable:
        return self.block.create_var(
            name=unique_name.generate(f"{self.name}.tmp"), dtype=dtype,
            shape=shape, lod_level=lod_level)

    # fluid spelling
    create_tmp_variable = create_variable_for_type_inference

    def create_global_variable(self, shape, dtype, persistable=True,
                               name=None) -> Variable:
        gb = self.main_program.global_block()
        return gb.create_var(
            name=name or unique_name.generate(f"{self.name}.global"),
            shape=shape, dtype=dtype, persistable=persistable)

    def set_variable_initializer(self, var, initializer):
        sb = self.startup_program.global_block()
        sv = sb.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                           persistable=True)
        initializer(sv, sb)

    def append_op(self, **kwargs):
        return self.block.append_op(
            kwargs["type"], kwargs.get("inputs"), kwargs.get("outputs"),
            kwargs.get("attrs"))

    def append_bias_op(self, input_var: Variable, dim_start=1,
                       bias_attr=None, num_flatten_dims=None) -> Variable:
        bias_attr = self.kwargs.get("bias_attr", bias_attr)
        # reference parity: bias_attr=None means CREATE a default bias
        # (param_attr.py to_attr(None) -> ParamAttr()); only False disables
        if bias_attr is False:
            return input_var
        size = input_var.shape[-1] if input_var.shape else 1
        b = self.create_parameter(
            ParamAttr._to_attr(True if bias_attr is True else bias_attr),
            shape=[size], dtype=input_var.dtype, is_bias=True)
        out = self.create_variable_for_type_inference(
            input_var.dtype, input_var.shape,
            lod_level=input_var.lod_level)
        self.append_op(type="elementwise_add",
                       inputs={"X": [input_var], "Y": [b]},
                       outputs={"Out": [out]},
                       attrs={"axis": input_var.shape and len(input_var.shape) - 1 or -1})
        return out

    def append_activation(self, input_var: Variable, act=None) -> Variable:
        act = self.kwargs.get("act", act)
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        out = self.create_variable_for_type_inference(
            input_var.dtype, input_var.shape,
            lod_level=input_var.lod_level)
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [out]}, attrs=act)
        return out

    def input_dtype(self, input_param_name="input"):
        v = self.kwargs.get(input_param_name)
        if isinstance(v, (list, tuple)):
            v = v[0]
        return v.dtype


def _append_weight_norm_ops(helper, v, g, dim, shape, dtype):
    """Emit w = g * v / ||v|| (norm over all dims except ``dim``) into the
    main program; grads flow to v and g via autodiff (fluid emulated this
    with a chain of norm/elementwise ops too, param_attr.py WeightNormParamAttr)."""
    sq = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op(type="square", inputs={"X": [v]},
                     outputs={"Out": [sq]}, attrs={})
    reduce_dims = [i for i in range(len(shape)) if i != (dim or 0)] \
        if dim is not None else list(range(len(shape)))
    norm_shape = [shape[dim]] if dim is not None else [1]
    ssum = helper.create_variable_for_type_inference(dtype, tuple(norm_shape))
    helper.append_op(type="reduce_sum", inputs={"X": [sq]},
                     outputs={"Out": [ssum]},
                     attrs={"dim": reduce_dims, "keep_dim": False})
    norm = helper.create_variable_for_type_inference(dtype, tuple(norm_shape))
    helper.append_op(type="sqrt", inputs={"X": [ssum]},
                     outputs={"Out": [norm]}, attrs={})
    scale = helper.create_variable_for_type_inference(dtype, tuple(norm_shape))
    helper.append_op(type="elementwise_div", inputs={"X": [g], "Y": [norm]},
                     outputs={"Out": [scale]}, attrs={"axis": -1})
    w = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op(type="elementwise_mul", inputs={"X": [v], "Y": [scale]},
                     outputs={"Out": [w]},
                     attrs={"axis": dim if dim is not None else 0})
    return w
