"""paddle_tpu — a TPU-native deep-learning framework.

A from-scratch rebuild of the capabilities of PaddlePaddle (reference:
/root/reference, circa v0.10/v0.11) designed TPU-first on JAX/XLA:

* The *program-as-data* spine of the reference's Fluid generation
  (reference: paddle/framework/program_desc.h:28, executor.cc:73) is kept as
  the user-facing IR — a ``Program`` of ``Block``s of ``Op``s — but instead of
  a serial per-op C++ interpreter, the whole program is traced into a single
  XLA computation with ``jax.jit`` and compiled once per (program, feed-shape)
  signature.  The MXU sees one fused graph, not 170 kernel launches.
* Autograd does not reimplement per-op grad makers (reference:
  framework/backward.cc:353) — ``append_backward`` marks gradient variables
  and the executor derives them with ``jax.value_and_grad`` over the traced
  forward section.  Every op in the library is therefore differentiable for
  free.
* Distribution replaces the reference's four communication backends (v1
  pserver sockets, Go pserver/master, fluid gRPC send/recv, NCCL — SURVEY.md
  §2.6) with XLA collectives over a ``jax.sharding.Mesh`` (``paddle_tpu.parallel``).
* Variable-length sequences (the reference's LoD, lod_tensor.h:34-83) become
  padded-plus-length tensors with masked sequence ops — static shapes that XLA
  can tile onto the MXU.

Public API intentionally mirrors the reference's fluid Python surface
(python/paddle/v2/fluid/__init__.py): ``layers``, ``optimizer``, ``Executor``,
``Program``, ``default_main_program`` ...
"""

from . import compat
from . import core
from .core import (
    stack_feeds,
    Program,
    Block,
    Operator,
    Variable,
    Parameter,
    default_main_program,
    default_startup_program,
    program_guard,
    pipeline_stage,
    unique_name,
    Executor,
    Scope,
    global_scope,
    scope_guard,
    CPUPlace,
    TPUPlace,
)
from . import ops  # noqa: F401  (registers every op implementation)
from . import layers
from . import nets
from . import initializer
from . import optimizer
from . import regularizer
from . import clip
from . import backward
from .backward import append_backward
from . import evaluator
from . import metrics
from . import io
from .io import save_params, load_params, save_persistables, load_persistables, \
    save_inference_model, load_inference_model
from . import export_model
from .export_model import export_compiled_model, load_compiled_model
from .data_feeder import DataFeeder
from .param_attr import ParamAttr
from . import observability
from . import profiler
from . import parallel
from . import distributed
from . import reader
from . import dataset
from . import lr_decay
from . import net_drawer
from . import flags
from . import trainer
from . import image
from . import utils
from . import api
from . import models
from .trainer import infer
from . import framework  # compat alias namespace
from . import faults
from .faults import EXIT_PREEMPTED, Preempted, RetryPolicy
from . import train_state
from .train_state import TrainState
from . import testing

# NOTE: the version is folded into every compile-cache fingerprint
# (core/compile_cache.environment_key) — bump it whenever compiled-step
# calling conventions change (0.2.0: check_nan_inf variants stopped
# donating state buffers; older persisted executables still alias them)
__version__ = "0.2.0"

__all__ = [
    "Program", "Block", "Operator", "Variable", "Parameter",
    "default_main_program", "default_startup_program", "program_guard",
    "unique_name", "Executor", "Scope", "global_scope", "scope_guard",
    "CPUPlace", "TPUPlace", "layers", "nets", "initializer", "optimizer",
    "regularizer", "clip", "backward", "append_backward", "evaluator",
    "metrics", "io", "save_params", "load_params", "save_persistables",
    "load_persistables", "save_inference_model", "load_inference_model",
    "DataFeeder", "ParamAttr", "observability", "profiler", "parallel",
    "distributed",
    "reader", "dataset", "trainer", "models", "infer", "image", "utils",
    "compat", "stack_feeds",
    "faults", "EXIT_PREEMPTED", "Preempted", "RetryPolicy",
    "train_state", "TrainState", "testing",
]
