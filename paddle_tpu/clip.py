"""Gradient clipping (reference: fluid/clip.py — ErrorClip, ClipByValue,
ClipByNorm, ClipByGlobalNorm appended as grad-graph ops)."""
from __future__ import annotations

from .layer_helper import LayerHelper


class BaseGradientClipAttr:
    def create_operators(self, param, grad):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def create_operators(self, param, grad):
        helper = LayerHelper("clip_grad")
        out = helper.create_variable_for_type_inference(grad.dtype, grad.shape)
        helper.append_op(type="clip", inputs={"X": [grad]},
                         outputs={"Out": [out]},
                         attrs={"min": self.min, "max": self.max})
        return param, out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def create_operators(self, param, grad):
        helper = LayerHelper("clip_grad_norm")
        out = helper.create_variable_for_type_inference(grad.dtype, grad.shape)
        helper.append_op(type="clip_by_norm", inputs={"X": [grad]},
                         outputs={"Out": [out]},
                         attrs={"max_norm": self.clip_norm})
        return param, out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """scale = clip_norm / max(global_norm, clip_norm), applied to every grad."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def create_operators_group(self, params_grads):
        from . import layers
        helper = LayerHelper("global_norm_clip")
        sq_sums = []
        for _, g in params_grads:
            sq = helper.create_variable_for_type_inference(g.dtype)
            helper.append_op(type="squared_l2_norm", inputs={"X": [g]},
                             outputs={"Out": [sq]})
            sq_sums.append(sq)
        total = layers.sums(sq_sums) if len(sq_sums) > 1 else sq_sums[0]
        gnorm = layers.sqrt(total)
        clip_var = layers.fill_constant([1], "float32", self.clip_norm)
        denom = layers.elementwise_max(gnorm, clip_var)
        scale = layers.elementwise_div(clip_var, denom)
        out = []
        for p, g in params_grads:
            ng = layers.elementwise_mul(g, scale)
            out.append((p, ng))
        return out


ErrorClipByValue = GradientClipByValue  # forward-activation clip parity alias


def append_gradient_clip_ops(params_grads):
    """Global-norm clipping groups only the params annotated with it;
    per-param clips apply individually; unannotated grads pass through."""
    group = [(p, g) for p, g in params_grads
             if isinstance(getattr(p, "gradient_clip_attr", None),
                           GradientClipByGlobalNorm)]
    grouped = {}
    if group:
        gc = group[0][0].gradient_clip_attr
        grouped = {p.name: (p, ng)
                   for p, ng in gc.create_operators_group(group)}
    out = []
    for p, g in params_grads:
        clip = getattr(p, "gradient_clip_attr", None)
        if p.name in grouped:
            out.append(grouped[p.name])
        elif clip is None:
            out.append((p, g))
        else:
            out.append(clip.create_operators(p, g))
    return out


def set_gradient_clip(clip, param_list=None, program=None):
    from .core.program import default_main_program
    program = program or default_main_program()
    params = param_list or program.all_parameters()
    for p in params:
        if isinstance(p, str):
            p = program.global_block().var(p)
        p.gradient_clip_attr = clip
