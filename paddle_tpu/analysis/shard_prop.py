"""Sharding propagation over the Program IR.

The reference framework distributes by *infrastructure*: a transpiler
rewrites the program into pserver/trainer halves and hand-placed collectives
(PAPER.md §distributed).  GSPMD inverts that — one annotation set on inputs
is propagated by the compiler — but the compiler's propagation happens deep
inside XLA, *after* tracing, where a bad spec surfaces as a partitioner
error naming an HLO instruction.  This pass recovers the propagation
statically, over the same Program IR the shape verifier walks, so the
auto-sharding planner (:mod:`.planner`) can reason about a candidate spec
set without compiling anything:

* The abstract value is a **per-dim sharding spec**: a tuple with one entry
  per tensor dim — ``None`` (replicated) or a tuple of mesh axis names
  (PartitionSpec semantics).  Unknown vars carry no spec; specs only ever
  *refine* (``None`` entries may gain axes), mirroring GSPMD's merge rule.
* Per-op propagation rules are registered next to the lowerings via
  ``core.registry.register_shard_fn`` — the distributed companion of
  ``register_shape_fn``, with the same ``fn(op, ins, attrs)`` shape; the
  helper factories below keep the common families one-liners and attach a
  ``.backward`` sweep direction so annotations flow both ways (a sharded
  loss constraint reaches its producers, a sharded feed reaches consumers).
* Conflicts are *diagnostics*, not crashes (codes in analysis.diagnostics):

  - **PT041** (warning) two shardings meet at an op in a way its rule
    cannot realize without data movement — GSPMD will insert an
    all-gather/all-to-all there; the cost model charges for it.
  - **PT042** (warning) a sharded value flows into an op with no shard
    rule: a propagation blind spot — downstream is treated replicated
    (GSPMD may do better; the planner sees a pessimistic bound).
  - **PT040** (error, emitted by the spec lints) one mesh axis sharding
    two dims of the same tensor — GSPMD rejects this outright.

Propagation runs at planning/validation time only, never in the stepped
hot path.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from .diagnostics import ValidationReport, diag

#: normalized per-dim entry: None (replicated) or a tuple of axis names
Entry = Optional[Tuple[str, ...]]
#: normalized spec: one Entry per dim
Spec = Tuple[Entry, ...]


class ShardConflict(ValueError):
    """Raised by a shard rule when input shardings cannot meet at this op
    without a reshard (reported as PT041 at the op's graph location)."""


# ---------------------------------------------------------------------------
# Spec algebra
# ---------------------------------------------------------------------------
def _entry(e) -> Entry:
    if e is None:
        return None
    if isinstance(e, (list, tuple)):
        t = tuple(str(a) for a in e)
        return t or None
    return (str(e),)


def normalize_spec(spec, ndim: Optional[int] = None) -> Optional[Spec]:
    """PartitionSpec / tuple / list -> canonical per-dim entries, padded or
    truncated to ``ndim`` when the rank is known."""
    if spec is None:
        return None
    entries = tuple(_entry(e) for e in list(spec))
    if ndim is not None:
        entries = entries[:ndim] + (None,) * max(0, ndim - len(entries))
    return entries


def merge_entry(a: Entry, b: Entry, what: str) -> Entry:
    """GSPMD's merge: replicated yields to sharded; two different
    shardings on one dim cannot meet without a reshard."""
    if a is None:
        return b
    if b is None:
        return a
    if a == b:
        return a
    raise ShardConflict(f"{what}: {list(a)} vs {list(b)}")


def merge_specs(a: Optional[Spec], b: Optional[Spec], what: str
                ) -> Optional[Spec]:
    if a is None:
        return b
    if b is None:
        return a
    n = max(len(a), len(b))
    ea = a + (None,) * (n - len(a))
    eb = b + (None,) * (n - len(b))
    return tuple(merge_entry(x, y, f"{what} dim {i}")
                 for i, (x, y) in enumerate(zip(ea, eb)))


def spec_extent(spec: Optional[Spec], mesh_axes: Dict[str, int],
                dim: Optional[int] = None) -> int:
    """Product of mesh-axis sizes sharding ``spec`` (or one dim of it)."""
    if spec is None:
        return 1
    entries = spec if dim is None else spec[dim:dim + 1]
    ext = 1
    for e in entries:
        for ax in (e or ()):
            ext *= int(mesh_axes.get(ax, 1))
    return ext


def is_sharded(spec: Optional[Spec]) -> bool:
    return spec is not None and any(e for e in spec)


class ShardInfo:
    """Abstract (sharding spec, static shape) of one variable, as shard
    rules see their inputs.  ``spec`` is None while unknown; ``shape`` comes
    from the shape-inference pass (dims may be -1)."""

    __slots__ = ("spec", "shape")

    def __init__(self, spec: Optional[Spec] = None, shape=None):
        self.spec = spec
        self.shape = tuple(shape) if shape is not None else None

    @property
    def ndim(self) -> Optional[int]:
        if self.shape is not None:
            return len(self.shape)
        return None if self.spec is None else len(self.spec)

    def entry(self, i: int) -> Entry:
        """Entry for dim ``i`` (negative ok); None when unknown/oob."""
        if self.spec is None:
            return None
        n = len(self.spec)
        if -n <= i < n:
            return self.spec[i]
        return None

    def dim(self, i: int) -> int:
        if self.shape is None:
            return -1
        n = len(self.shape)
        return self.shape[i] if -n <= i < n else -1

    def __repr__(self):
        return f"ShardInfo({self.spec}, shape={self.shape})"


def first_in(ins: Dict[str, List[ShardInfo]], slot: str) -> ShardInfo:
    vals = ins.get(slot)
    return vals[0] if vals else ShardInfo()


#: rule return value meaning "replicated, rank taken from the declared
#: shape" — normalize_spec pads it with None entries
REPLICATED: Spec = ()


def squeeze_spec_ids(ids: ShardInfo) -> Spec:
    """The id-tensor convention mirrored from shape_infer.squeeze_ids:
    ``[..., 1]`` drops its trailing entry (lookup_table, one_hot)."""
    if ids.spec is None:
        return (None,)
    if ids.shape is not None and len(ids.shape) >= 2 and \
            ids.shape[-1] == 1:
        return ids.spec[:-1]
    return ids.spec


# ---------------------------------------------------------------------------
# Rule helper factories (imported by ops/*.py next to the lowerings)
# ---------------------------------------------------------------------------
def shard_same_as(slot: str = "X", out: str = "Out",
                  also: Tuple[str, ...] = ()):
    """Output(s) carry the input's sharding dim-for-dim (elementwise /
    shape-preserving ops); backward flows the output spec to the input."""

    def rule(op, ins, attrs):
        x = first_in(ins, slot)
        res = {out: x.spec}
        for extra in also:
            res[extra] = x.spec
        return res

    def backward(op, outs, ins, attrs):
        return {slot: first_in(outs, out).spec}

    rule.backward = backward
    return rule


def shard_elementwise(out: str = "Out"):
    """Broadcast-aware merge of X and Y: aligned dims must agree (size-1
    dims yield to the other side); honors the explicit ``axis`` attr the
    same way the lowering does."""

    def _align(x: ShardInfo, y: ShardInfo, attrs):
        nx, ny = x.ndim, y.ndim
        if nx is None or ny is None:
            return None
        n = max(nx, ny)
        axis = attrs.get("axis", -1)
        # explicit axis: y's dims map onto x's [axis, axis+ny); otherwise
        # numpy trailing alignment for BOTH operands
        explicit = axis not in (-1, None) and ny < nx
        entries: List[Entry] = []
        for i in range(n):
            jx = i if explicit else i - (n - nx)
            jy = (i - axis) if explicit else i - (n - ny)
            ex = x.entry(jx) if 0 <= jx < nx else None
            ey = y.entry(jy) if 0 <= jy < ny else None
            dx = x.dim(jx) if 0 <= jx < nx else 1
            dy = y.dim(jy) if 0 <= jy < ny else 1
            if dy == 1:
                entries.append(ex)
            elif dx == 1:
                entries.append(ey)
            else:
                entries.append(merge_entry(
                    ex, ey, f"elementwise operands dim {i}"))
        return tuple(entries)

    def rule(op, ins, attrs):
        x, y = first_in(ins, "X"), first_in(ins, "Y")
        if x.spec is None and y.spec is None:
            return {}
        spec = _align(x, y, attrs)
        return {} if spec is None else {out: spec}

    def backward(op, outs, ins, attrs):
        o = first_in(outs, out)
        if o.spec is None:
            return {}
        res = {}
        for slot in ("X", "Y"):
            v = first_in(ins, slot)
            n = v.ndim
            if n is None:
                continue
            # trailing alignment; broadcast (size-1) dims stay replicated
            spec = tuple(
                o.entry(len(o.spec) - n + i)
                if v.dim(i) != 1 and len(o.spec) - n + i >= 0 else None
                for i in range(n)) if o.spec else None
            res[slot] = spec
        return res

    rule.backward = backward
    return rule


def shard_reduce(out: str = "Out"):
    """reduce_op semantics on specs: reduced dims drop their sharding (the
    partial results all-reduce inside XLA — charged by the cost model)."""

    def rule(op, ins, attrs):
        x = first_in(ins, "X")
        if x.spec is None:
            return {}
        if attrs.get("reduce_all", False):
            keep = attrs.get("keep_dim", False)
            return {out: (None,) * len(x.spec) if keep else REPLICATED}
        dim = attrs.get("dim", [0])
        axes = tuple(dim) if isinstance(dim, (list, tuple)) else (int(dim),)
        nd = len(x.spec)
        axes = {a % nd for a in axes if -nd <= a < nd}
        if attrs.get("keep_dim", False):
            spec = tuple(None if i in axes else e
                         for i, e in enumerate(x.spec))
        else:
            spec = tuple(e for i, e in enumerate(x.spec) if i not in axes)
        return {out: spec}

    return rule


def shard_mirror(mapping: Dict[str, str], check_grad: bool = False):
    """Each output slot carries its named input slot's sharding — the
    optimizer-op family.  ``check_grad`` also merges Param vs Grad (a
    dp-reduced grad arrives with the param's layout; a mismatch means a
    reshard in the update step)."""

    def rule(op, ins, attrs):
        if check_grad:
            p, g = first_in(ins, "Param"), first_in(ins, "Grad")
            merge_specs(p.spec, g.spec, "Param vs Grad sharding")
        res = {}
        for out_slot, in_slot in mapping.items():
            if op.outputs.get(out_slot):
                res[out_slot] = first_in(ins, in_slot).spec
        return res

    def backward(op, outs, ins, attrs):
        res = {}
        for out_slot, in_slot in mapping.items():
            o = first_in(outs, out_slot)
            if o.spec is not None:
                res[in_slot] = o.spec
        return res

    rule.backward = backward
    return rule


def shard_replicated(*out_slots: str):
    """Outputs are replicated regardless of inputs (scalar reductions,
    side-effect ops, shape probes)."""
    slots = out_slots or ("Out",)

    def rule(op, ins, attrs):
        return {s: REPLICATED for s in slots}

    return rule


def shard_batch_only(slot: str = "X", out: str = "Out",
                     fallbacks: Tuple[str, ...] = (),
                     also: Tuple[str, ...] = ()):
    """Outputs follow the batch (dim 0) sharding of the first input slot
    that carries one; other dims replicate.  Covers loss heads
    ([B, ...] -> [B, 1]) and the whole batch-preserving reduction family
    (detection heads, NCE, CRF, index/selection ops) — ``fallbacks``
    lists further input slots to probe, ``also`` extra output slots
    (slots absent on a given op are ignored by the pass)."""

    def probe(ins):
        for s in (slot,) + tuple(fallbacks):
            x = first_in(ins, s)
            if x.spec is not None:
                return x
        return None

    def rule(op, ins, attrs):
        x = probe(ins)
        if x is None:
            return {}
        return {s: (x.entry(0),) for s in (out,) + tuple(also)}

    def backward(op, outs, ins, attrs):
        o = first_in(outs, out)
        if o.spec is None:
            return {}
        return {slot: (o.entry(0),)}

    rule.backward = backward
    return rule


def shard_noop():
    """Op is sharding-transparent or data-dependent: claim nothing about
    its outputs, but do not flag it as a blind spot (registering the noop
    IS the statement that replication is the intended treatment)."""

    def rule(op, ins, attrs):
        return {}

    return rule


def shard_mul():
    """``mul`` (the fc matmul): X flattened at x_num_col_dims, Y at
    y_num_col_dims.  Row dims follow X, col dims follow Y; the contraction
    dims must carry the SAME sharding on both sides (Megatron row-parallel:
    col-sharded activations meet row-sharded weights and XLA all-reduces
    the partial products) — one-sided contraction sharding is a reshard."""

    def rule(op, ins, attrs):
        x, y = first_in(ins, "X"), first_in(ins, "Y")
        if x.spec is None and y.spec is None:
            return {}
        xn = attrs.get("x_num_col_dims", 1)
        yn = attrs.get("y_num_col_dims", 1)
        cx = tuple((x.entry(i) for i in range(xn, len(x.spec)))) \
            if x.spec is not None else (None,)
        cy = tuple((y.entry(i) for i in range(yn))) \
            if y.spec is not None else (None,)
        kx = next((e for e in cx if e), None)
        ky = next((e for e in cy if e), None)
        if kx != ky:
            raise ShardConflict(
                f"mul contraction sharding mismatch: X[{xn}:] carries "
                f"{kx and list(kx)} vs Y[:{yn}] {ky and list(ky)}")
        rows = tuple(x.entry(i) for i in range(xn)) if x.spec is not None \
            else (None,) * xn
        cols = tuple(y.entry(i) for i in range(yn, len(y.spec))) \
            if y.spec is not None else (None,)
        return {"Out": rows + cols}

    def backward(op, outs, ins, attrs):
        o = first_in(outs, "Out")
        if o.spec is None:
            return {}
        xn = attrs.get("x_num_col_dims", 1)
        res = {}
        x, y = first_in(ins, "X"), first_in(ins, "Y")
        if x.ndim is not None:
            res["X"] = tuple(o.entry(i) if i < xn else None
                             for i in range(x.ndim))
        if y.ndim is not None:
            yn = attrs.get("y_num_col_dims", 1)
            res["Y"] = tuple(
                None if i < yn else o.entry(xn + (i - yn))
                for i in range(y.ndim))
        return res

    rule.backward = backward
    return rule


def shard_matmul():
    """matmul: batch dims merge elementwise; the contraction pair must
    agree (transpose attrs honored); Out last two dims follow X row / Y
    col."""

    def rule(op, ins, attrs):
        x, y = first_in(ins, "X"), first_in(ins, "Y")
        if x.spec is None and y.spec is None:
            return {}
        nx, ny = x.ndim, y.ndim
        if nx is None or ny is None or nx < 2 or ny < 2:
            return {}
        tx = attrs.get("transpose_X", False)
        ty = attrs.get("transpose_Y", False)
        x_row, x_k = (-1, -2) if tx else (-2, -1)
        y_k, y_col = (-1, -2) if ty else (-2, -1)
        kx, ky = x.entry(x_k), y.entry(y_k)
        if kx != ky and (kx or ky):
            raise ShardConflict(
                f"matmul contraction sharding mismatch: "
                f"{kx and list(kx)} vs {ky and list(ky)}")
        nb = max(nx, ny) - 2
        batch = []
        for i in range(nb):
            ex = x.entry(i - (nb - (nx - 2))) if i >= nb - (nx - 2) else None
            ey = y.entry(i - (nb - (ny - 2))) if i >= nb - (ny - 2) else None
            batch.append(merge_entry(ex, ey, f"matmul batch dim {i}"))
        return {"Out": tuple(batch) + (x.entry(x_row), y.entry(y_col))}

    return rule


def shard_conv2d(in_slot: str = "Input", filt_slot: str = "Filter",
                 out: str = "Output"):
    """conv2d family: Out batch follows Input batch, Out channels follow
    Filter dim 0; spatial sharding is a halo exchange this model does not
    attempt (conflict -> reshard); the channel contraction (Input C vs
    Filter I) must agree like mul's."""

    def rule(op, ins, attrs):
        x, w = first_in(ins, in_slot), first_in(ins, filt_slot)
        if x.spec is None and w.spec is None:
            return {}
        if x.spec is not None and any(x.entry(i) for i in (2, 3)):
            raise ShardConflict(
                "conv2d input spatially sharded: halo exchange required")
        kx, kw = x.entry(1), w.entry(1)
        if kx != kw and (kx or kw):
            raise ShardConflict(
                f"conv2d channel contraction sharding mismatch: "
                f"{kx and list(kx)} vs {kw and list(kw)}")
        return {out: (x.entry(0), w.entry(0), None, None)}

    def backward(op, outs, ins, attrs):
        o = first_in(outs, out)
        if o.spec is None:
            return {}
        res = {}
        x, w = first_in(ins, in_slot), first_in(ins, filt_slot)
        if x.ndim is not None:
            res[in_slot] = (o.entry(0),) + (None,) * (x.ndim - 1)
        if w.ndim is not None:
            res[filt_slot] = (o.entry(1),) + (None,) * (w.ndim - 1)
        return res

    rule.backward = backward
    return rule


# ---------------------------------------------------------------------------
# The propagation pass
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class PropagationResult:
    """Outcome of :func:`propagate_sharding`.

    ``specs`` maps var name -> normalized Spec for every var the sweeps
    reached; ``report`` carries PT041/PT042 findings; ``resharded`` lists
    (block_idx, op_idx, op_type, note) conflict sites for the cost model;
    ``blind_spots`` lists (block_idx, op_idx, op_type) uncovered ops a
    sharded value reached.
    """

    specs: Dict[str, Spec]
    report: ValidationReport
    resharded: List[Tuple[int, int, str, str]]
    blind_spots: List[Tuple[int, int, str]]


def _shapes_of(program, shapes):
    if shapes is not None:
        return shapes
    from .shape_infer import run_shape_inference
    return run_shape_inference(program, ValidationReport())


def propagate_sharding(program, seeds: Dict[str, Sequence],
                       report: Optional[ValidationReport] = None,
                       shapes=None, max_sweeps: int = 4
                       ) -> PropagationResult:
    """Propagate per-dim sharding annotations to a fixpoint.

    ``seeds`` maps var name -> spec (PartitionSpec / tuple of entries) —
    typically the planner's candidate ``param_specs`` + ``feed_specs`` plus
    any ``Parameter.sharding`` annotations.  Seeded entries are pinned: a
    sweep refining a seed's non-None entry to something else is a PT041
    conflict, and the seed wins.  ``shapes`` may pass a precomputed
    ``run_shape_inference`` result.

    Sub-block ops are skipped (their carries stay at their seeded specs);
    the single ``backward`` pseudo-op is special-cased — each declared
    ``<param>@GRAD`` carries its parameter's sharding, which is exactly
    what ``jax.value_and_grad`` under GSPMD produces.
    """
    from ..core.program import _sub_block_indices
    from ..core.registry import get_shard_fn

    report = report if report is not None else ValidationReport()
    all_shapes = _shapes_of(program, shapes)

    def var_shape(block_idx: int, name: str):
        info = all_shapes.get(block_idx, {}).get(name)
        if info is not None and info.shape is not None:
            return info.shape
        for b in program.blocks:
            v = b.vars.get(name)
            if v is not None:
                return v.shape
        return None

    def ndim_of(block_idx: int, name: str):
        s = var_shape(block_idx, name)
        return None if s is None else len(s)

    specs: Dict[str, Spec] = {}
    pinned: Dict[str, Spec] = {}
    for name, spec in (seeds or {}).items():
        nd = ndim_of(0, name)
        norm = normalize_spec(spec, nd)
        if norm is not None:
            specs[name] = norm
            pinned[name] = norm
    for b in program.blocks:
        for v in b.vars.values():
            sh = getattr(v, "sharding", None)
            if sh and v.name not in specs:
                norm = normalize_spec(sh, ndim_of(b.idx, v.name))
                specs[v.name] = norm
                pinned[v.name] = norm

    conflicts: Dict[Tuple[int, int, str, str], None] = {}
    blind: Dict[Tuple[int, int, str], None] = {}

    def info_for(block_idx: int, name: str) -> ShardInfo:
        return ShardInfo(specs.get(name), var_shape(block_idx, name))

    def bind(loc, names_specs) -> bool:
        """Merge new specs into the state; returns True on change."""
        changed = False
        for name, spec, nd in names_specs:
            norm = normalize_spec(spec, nd)
            if norm is None:
                continue
            old = specs.get(name)
            try:
                merged = merge_specs(old, norm, f"var {name!r}")
                # an axis landing on two dims of one var (e.g. two
                # differently-sharded operands merging elementwise) is a
                # reshard, not a legal spec — keep the first booking
                booked: Dict[str, int] = {}
                fixed = []
                for i, e in enumerate(merged):
                    kept = []
                    for ax in (e or ()):
                        if ax in booked:
                            raise ShardConflict(
                                f"var {name!r}: axis {ax!r} would shard "
                                f"both dim {booked[ax]} and dim {i}")
                        booked[ax] = i
                        kept.append(ax)
                    fixed.append(tuple(kept) or None)
                merged = tuple(fixed)
            except ShardConflict as e:
                conflicts.setdefault(loc + (str(e),))
                continue
            if name in pinned and merged != pinned[name]:
                try:
                    merged = merge_specs(pinned[name], merged, name)
                except ShardConflict as e:
                    conflicts.setdefault(loc + (str(e),))
                    merged = pinned[name]
            if merged != old:
                specs[name] = merged
                changed = True
        return changed

    def run_rule(block, op_idx, op, direction: str) -> bool:
        loc = (block.idx, op_idx, op.type)
        if op.type == "backward":
            params = op.attrs.get("params", [])
            grads = op.outputs.get("Grads", [])
            updates = []
            for p, g in zip(params, grads):
                if p in specs:
                    updates.append((g, specs[p], ndim_of(block.idx, g)))
            return bind(loc, updates)
        rule = get_shard_fn(op.type)
        ins = {slot: [info_for(block.idx, n) for n in names]
               for slot, names in op.inputs.items() if names}
        if rule is None:
            if any(is_sharded(i.spec) for vs in ins.values() for i in vs):
                blind.setdefault((block.idx, op_idx, op.type))
            return False
        outs = {slot: [info_for(block.idx, n) for n in names]
                for slot, names in op.outputs.items() if names}
        try:
            if direction == "forward":
                res = rule(op, ins, op.attrs) or {}
                slot_names = op.outputs
            else:
                bwd = getattr(rule, "backward", None)
                if bwd is None:
                    return False
                res = bwd(op, outs, ins, op.attrs) or {}
                slot_names = op.inputs
        except ShardConflict as e:
            conflicts.setdefault(loc + (str(e),))
            return False
        except Exception as e:  # noqa: BLE001 — a rule crashing on a
            # malformed program must degrade like shape rules do, not
            # take down the planner
            conflicts.setdefault(
                loc + (f"shard rule failed ({type(e).__name__}: {e})",))
            return False
        updates = []
        for slot, val in res.items():
            vals = val if isinstance(val, list) else [val]
            names = slot_names.get(slot, [])
            for i, name in enumerate(names):
                if i < len(vals) and vals[i] is not None:
                    updates.append((name, vals[i],
                                    ndim_of(block.idx, name)))
        return bind(loc, updates)

    for _ in range(max_sweeps):
        changed = False
        for block in program.blocks:
            for op_idx, op in enumerate(block.ops):
                if _sub_block_indices(op):
                    continue
                changed |= run_rule(block, op_idx, op, "forward")
        for block in reversed(program.blocks):
            for op_idx in range(len(block.ops) - 1, -1, -1):
                op = block.ops[op_idx]
                if _sub_block_indices(op):
                    continue
                changed |= run_rule(block, op_idx, op, "backward")
        if not changed:
            break

    resharded = []
    for (bi, oi, typ, note) in conflicts:
        resharded.append((bi, oi, typ, note))
        report.add(diag(
            "PT041",
            f"op {typ!r}: sharding conflict — {note}; GSPMD inserts a "
            f"reshard (all-gather/all-to-all) here", op=(bi, oi, typ)))
    blind_spots = []
    for (bi, oi, typ) in blind:
        blind_spots.append((bi, oi, typ))
        report.add(diag(
            "PT042",
            f"op {typ!r} has no register_shard_fn rule but receives a "
            f"sharded input — propagation treats its outputs as "
            f"replicated (planner blind spot)", op=(bi, oi, typ)))
    return PropagationResult(specs=specs, report=report,
                             resharded=resharded, blind_spots=blind_spots)
