"""Auto-sharding planner: propose and statically validate sharding specs.

The capstone of the static-analysis stack: where the reference shipped a
*distribute transpiler* (program surgery into pserver/trainer halves plus
hand-configured NCCL rings, PAPER.md §distributed), this module needs no
infrastructure at all — it reads the Program IR, enumerates candidate
GSPMD annotation sets for a given mesh, scores them with the static cost
model, checks each with the sharding propagation pass and the existing
PT030/PT031 spec lints, and hands the winner to ``ShardedExecutor`` as
plain ``param_specs``/``feed_specs``.  Pure static analysis: runs on a
chipless container.

Candidate enumeration (deliberately small — plans, not a search):

1. **dp** — data parallel only: every feed's batch dim on the batch axis,
   parameters replicated.  Always valid; always the fallback.
2. **megatron** — dp plus Megatron-style tensor splits over the ``tp``
   axis: along each fc chain the first eligible weight splits by columns
   ``(None, 'tp')`` and a consumer weight fed by the col-sharded
   activation splits by rows ``('tp', None)`` (the matched contraction
   XLA turns into one all-reduce); lstm/gru gate projections split on the
   gate dim, embedding tables split on the vocab dim.  A dim is eligible
   only when divisible by **128** (the TPU lane width — smaller shards
   pad the MXU) *and* by the axis size.
3. **column** — dp plus every eligible weight column-split (no row pairs:
   each activation all-gathers instead).  Kept as ranking pressure — the
   cost model should and does prefer megatron when chains exist.

A plan must pass ``run_sharding_lints`` with zero findings before it is
returned; candidates whose propagation reports PT040-class errors are
discarded.  Plans serialize to JSON (``Plan.to_dict``/``from_dict``) so a
committed ``plan.json`` can gate CI via ``paddle_tpu check --specs``.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

from .diagnostics import ValidationReport
from .cost_model import CostReport, estimate_cost
from .lints import run_sharding_lints
from .shard_prop import (PropagationResult, Spec, normalize_spec,
                         propagate_sharding)

#: TPU lane width: tensor-split dims must divide by this (and by the axis
#: size) to keep every shard MXU-aligned
SPLIT_ALIGN = 128


@dataclasses.dataclass
class Plan:
    """One concrete sharding assignment for (program, mesh)."""

    mesh_axes: Dict[str, int]
    batch_axis: str
    param_specs: Dict[str, Spec]
    feed_specs: Dict[str, Spec]
    candidate: str
    cost: Optional[CostReport] = None
    diagnostics: List[str] = dataclasses.field(default_factory=list)

    # -- serialization ------------------------------------------------------
    @staticmethod
    def _encode_spec(spec: Spec):
        return [list(e) if e else None for e in spec]

    @staticmethod
    def _decode_spec(entries) -> Spec:
        # reject null/garbage spec values here so the CLI's plan-file
        # loader can wrap the failure in its one-line error message
        # instead of a traceback deep inside the sharding lints
        if not isinstance(entries, (list, tuple)):
            raise TypeError(
                f"plan spec must be a list of per-dim entries, got "
                f"{type(entries).__name__}")
        return normalize_spec(entries)

    def to_dict(self) -> dict:
        d = {
            "version": 1,
            "mesh": dict(self.mesh_axes),
            "batch_axis": self.batch_axis,
            "candidate": self.candidate,
            "param_specs": {k: self._encode_spec(v)
                            for k, v in sorted(self.param_specs.items())},
            "feed_specs": {k: self._encode_spec(v)
                           for k, v in sorted(self.feed_specs.items())},
            "diagnostics": list(self.diagnostics),
        }
        if self.cost is not None:
            d["cost"] = self.cost.to_dict()
            d["per_device_peak_hbm_bytes"] = \
                self.cost.peak_hbm_bytes_per_device
        return d

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @staticmethod
    def from_dict(d: dict) -> "Plan":
        return Plan(
            mesh_axes={str(k): int(v) for k, v in d["mesh"].items()},
            batch_axis=d.get("batch_axis", "dp"),
            param_specs={k: Plan._decode_spec(v)
                         for k, v in d.get("param_specs", {}).items()},
            feed_specs={k: Plan._decode_spec(v)
                        for k, v in d.get("feed_specs", {}).items()},
            candidate=d.get("candidate", "?"),
            diagnostics=list(d.get("diagnostics", [])))

    @staticmethod
    def from_json(s: str) -> "Plan":
        return Plan.from_dict(json.loads(s))

    # -- executor handoff ---------------------------------------------------
    def as_partition_specs(self):
        """(param_specs, feed_specs) as jax PartitionSpec dicts — the exact
        kwargs ``ShardedExecutor`` takes."""
        from jax.sharding import PartitionSpec as P

        def conv(specs):
            return {k: P(*v) for k, v in specs.items()}

        return conv(self.param_specs), conv(self.feed_specs)

    def render(self) -> str:
        lines = [f"plan [{self.candidate}] over mesh "
                 f"{{{', '.join(f'{a}={s}' for a, s in self.mesh_axes.items())}}}"]
        lines.append("  feed_specs:")
        for k, v in sorted(self.feed_specs.items()):
            lines.append(f"    {k}: {self._encode_spec(v)}")
        lines.append("  param_specs:" if self.param_specs
                     else "  param_specs: (all replicated)")
        for k, v in sorted(self.param_specs.items()):
            lines.append(f"    {k}: {self._encode_spec(v)}")
        if self.cost is not None:
            c = self.cost
            lines.append(
                f"  cost: {c.flops_per_device / 1e9:.2f} GFLOP/device, "
                f"{c.hbm_bytes_per_device / 1e6:.2f} MB HBM traffic, "
                f"{(c.collective_bytes + c.reshard_bytes) / 1e6:.2f} MB "
                f"collectives, proxy {c.step_time_proxy_s * 1e3:.3f} ms")
            lines.append(
                f"  per-device peak HBM estimate: "
                f"{c.peak_hbm_bytes_per_device / 1e6:.2f} MB")
        for dmsg in self.diagnostics:
            lines.append(f"  note: {dmsg}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Candidate enumeration
# ---------------------------------------------------------------------------
def _feed_vars(program):
    out = []
    for b in program.blocks:
        for v in b.vars.values():
            if v.is_data and not v.name.endswith("@LEN") \
                    and not v.name.endswith("@LEN2"):
                out.append(v)
    return out


def _params(program):
    from ..core.program import Parameter
    return [v for v in program.global_block().vars.values()
            if isinstance(v, Parameter)]


def _splittable(dim: int, size: int) -> bool:
    return dim > 0 and dim % SPLIT_ALIGN == 0 and dim % size == 0


def _feed_specs_for(program, mesh_axes, batch_axis) -> Dict[str, Spec]:
    specs: Dict[str, Spec] = {}
    use_dp = int(mesh_axes.get(batch_axis, 1)) > 1
    for v in _feed_vars(program):
        if v.shape is None or len(v.shape) == 0:
            continue
        entries = ((batch_axis,) if use_dp else None,) + \
            (None,) * (len(v.shape) - 1)
        specs[v.name] = entries
    return specs


def _tensor_split_specs(program, mesh_axes, tp_axis: str,
                        megatron: bool) -> Dict[str, Spec]:
    """Megatron assignment over the global block, in program order.

    Tracks which activations are column-sharded: a ``mul`` whose X input
    derives from a col-split product gets its weight row-split (matched
    contraction -> one all-reduce); otherwise an eligible weight starts a
    new column split.  ``megatron=False`` gives the all-column variant.
    """
    size = int(mesh_axes.get(tp_axis, 1))
    if size <= 1:
        return {}
    param_names = {p.name for p in _params(program)}
    specs: Dict[str, Spec] = {}
    col_sharded: set = set()
    gb = program.global_block()
    for op in gb.ops:
        if op.type == "mul":
            ys = op.inputs.get("Y", [])
            xs = op.inputs.get("X", [])
            if ys and ys[0] in specs:
                # reused (tied) weight: its assigned split decides the
                # product — a column split keeps the chain col-sharded,
                # a row split consumes it
                if specs[ys[0]] == (None, (tp_axis,)):
                    col_sharded.update(op.output_names)
                continue
            if ys and ys[0] in param_names:
                w = gb._find_var_recursive(ys[0])
                if w is not None and w.shape is not None \
                        and len(w.shape) == 2:
                    rows, cols = w.shape
                    x_col = bool(xs) and xs[0] in col_sharded
                    if megatron and x_col and _splittable(rows, size):
                        # row-parallel consumer: contraction matches the
                        # col-sharded activation, out is unsharded again
                        specs[ys[0]] = ((tp_axis,), None)
                        continue
                    if not x_col and _splittable(cols, size):
                        specs[ys[0]] = (None, (tp_axis,))
                        col_sharded.update(op.output_names)
                        continue
            # ineligible weight (or non-param operand): the contraction
            # consumes any col-sharded activation, the chain ends here
            continue
        elif op.type == "lstm" or op.type == "gru":
            ws = op.inputs.get("Weight", [])
            if ws and ws[0] in param_names and ws[0] not in specs:
                w = gb._find_var_recursive(ws[0])
                if w is not None and w.shape is not None \
                        and len(w.shape) == 2 \
                        and _splittable(w.shape[1], size):
                    # gate-dim split rides with a col-split input
                    # projection (the fc producing [B,T,4H])
                    specs[ws[0]] = (None, (tp_axis,))
            continue
        elif op.type == "lookup_table":
            ws = op.inputs.get("W", [])
            if ws and ws[0] in param_names and ws[0] not in specs:
                w = gb._find_var_recursive(ws[0])
                if w is not None and w.shape is not None \
                        and len(w.shape) == 2 \
                        and _splittable(w.shape[0], size):
                    # vocab-parallel embedding (the SelectedRows/CTR
                    # analog): GSPMD lowers the gather to a masked
                    # partial lookup + all-reduce
                    specs[ws[0]] = ((tp_axis,), None)
            continue
        # col-shardedness flows through shape-preserving glue so the next
        # mul in the chain can see it
        if op.type in ("elementwise_add", "scale", "relu", "tanh",
                       "sigmoid", "gelu", "silu", "swish", "dropout",
                       "softmax", "layer_norm", "brelu", "leaky_relu"):
            ins = op.input_names
            if any(n in col_sharded for n in ins):
                col_sharded.update(op.output_names)
    return specs


def enumerate_candidates(program, mesh_axes: Dict[str, int],
                         batch_axis: str = "dp", tp_axis: str = "tp"
                         ) -> List[Tuple[str, Dict[str, Spec],
                                         Dict[str, Spec]]]:
    """[(name, param_specs, feed_specs)] — dp first, then tensor splits."""
    feeds = _feed_specs_for(program, mesh_axes, batch_axis)
    cands = [("dp", {}, feeds)]
    mega = _tensor_split_specs(program, mesh_axes, tp_axis, megatron=True)
    if mega:
        cands.append(("megatron", mega, feeds))
        col = _tensor_split_specs(program, mesh_axes, tp_axis,
                                  megatron=False)
        if col and col != mega:
            cands.append(("column", col, feeds))
    return cands


# ---------------------------------------------------------------------------
# Planning
# ---------------------------------------------------------------------------
def _score_candidates(program, mesh_axes: Dict[str, int],
                      batch_axis: str, tp_axis: str, assume_batch: int,
                      op_class_ratios: Optional[Dict[str, float]] = None):
    """Propagate + cost every candidate; returns the sorted scored list
    ``[(proxy_s, order, name, param_specs, feed_specs, prop, cost)]``
    best-first."""
    from .shape_infer import run_shape_inference

    shapes = run_shape_inference(program, ValidationReport())
    scored = []
    for name, param_specs, feed_specs in enumerate_candidates(
            program, mesh_axes, batch_axis, tp_axis):
        seeds = dict(param_specs)
        seeds.update(feed_specs)
        prop = propagate_sharding(program, seeds, shapes=shapes)
        cost = estimate_cost(program, mesh_axes, prop, shapes=shapes,
                             assume_batch=assume_batch,
                             batch_axis=batch_axis,
                             op_class_ratios=op_class_ratios)
        scored.append((cost.step_time_proxy_s, len(scored), name,
                       param_specs, feed_specs, prop, cost))
    scored.sort(key=lambda t: (t[0], t[1]))
    return scored


def rank_candidates(program, mesh_axes: Dict[str, int], *,
                    batch_axis: str = "dp", tp_axis: str = "tp",
                    assume_batch: int = 64,
                    op_class_ratios: Optional[Dict[str, float]] = None
                    ) -> List[Tuple[str, float]]:
    """``[(candidate_name, step_time_proxy_s)]`` best-first — exactly the
    scoring :func:`plan` ranks on, exposed so calibration effects
    (``op_class_ratios`` from the opprof table) are inspectable and
    testable: a class correction that flips the ranking here flips the
    shipped plan."""
    mesh_axes = {str(k): int(v) for k, v in (mesh_axes or {}).items()}
    return [(name, proxy) for proxy, _, name, *_ in _score_candidates(
        program, mesh_axes, batch_axis, tp_axis, assume_batch,
        op_class_ratios)]


def plan(program, mesh_axes: Dict[str, int], *, batch_axis: str = "dp",
         tp_axis: str = "tp", assume_batch: int = 64,
         op_class_ratios: Optional[Dict[str, float]] = None) -> Plan:
    """Propose the cheapest statically-valid sharding plan.

    Every candidate is (1) propagated through the IR (PT041/PT042 sites
    feed the cost model's reshard terms), (2) scored by the static cost
    model — with ``op_class_ratios`` (the opprof per-op-class
    calibration, ``attribution.load_op_class_ratios``) folded in when
    given, so measured op-class corrections rank plans instead of the
    nominal constants alone — and (3) the winner is re-checked against
    the PT030/PT031 spec lints — a plan that fails them is discarded and
    the next-best is taken, so the returned plan always validates clean
    (the ``dp`` fallback cannot fail: batch dims are symbolic).
    """
    mesh_axes = {str(k): int(v) for k, v in (mesh_axes or {}).items()}
    scored = _score_candidates(program, mesh_axes, batch_axis, tp_axis,
                               assume_batch, op_class_ratios)

    last_err = None
    for _, _, name, param_specs, feed_specs, prop, cost in scored:
        report = ValidationReport()
        run_sharding_lints(program, mesh_axes, report,
                           param_specs=param_specs, feed_specs=feed_specs)
        if report.errors:
            last_err = report
            continue
        notes = [str(d) for d in prop.report]
        if op_class_ratios:
            notes.append(
                f"ranked with op-class calibration "
                f"({len(op_class_ratios)} class(es): "
                f"{', '.join(sorted(op_class_ratios))})")
        return Plan(mesh_axes=mesh_axes, batch_axis=batch_axis,
                    param_specs=dict(param_specs),
                    feed_specs=dict(feed_specs), candidate=name,
                    cost=cost, diagnostics=notes)
    raise ValueError(
        "auto-sharding planner: no candidate passed the sharding lints"
        + ("\n" + last_err.render() if last_err else ""))


def plan_for_mesh(program, mesh, **kw) -> Plan:
    """Convenience: accept a jax Mesh / axis->size dict like validate()."""
    from .lints import mesh_axes_of
    return plan(program, mesh_axes_of(mesh) or {}, **kw)
