"""Graph lints: dead code, retrace hazards, sharding-spec consistency.

These are the checks the reference scattered across its runtime — pruning
(framework/prune.cc:51) implicitly defined deadness, recompilation never
existed (per-op kernels), and sharding had no analog at all.  In the
one-big-jit world each has a build-time answer:

* **PT020** (warning) *dead op*: unreachable from any fetch target, any
  persistable-state write, and any side-effect op.  A dead tail still
  costs trace time and XLA may or may not DCE it; in either case it is
  graph noise the author should see.  Runs only when fetch targets are
  known (``Program.validate(fetch_list=...)`` or the Executor paths).
* **PT021** (warning) *feed-signature instability*: a feed (``is_data``)
  var whose declared shape cannot pin a stable compiled signature — no
  static shape at all, or symbolic ``-1`` dims beyond the batch/sequence
  prefix the feeder controls.  Every novel concrete shape means a fresh
  trace+compile per step (the retrace hazard compile_cache's telemetry
  detects at runtime; this catches it before the first step).
* **PT022** (warning) *persistable rebound*: an op overwrites persistable
  state without reading it.  State written per step from fresh values
  defeats buffer donation and (when its shape/dtype drifts) invalidates
  the step signature — the reference had no such hazard because scope
  vars were host objects.  Input-less writers are exempt: that is the
  normal startup-program initializer pattern.
* **PT030/PT031** (error) *sharding-spec consistency* for
  ``ShardedExecutor``: every axis a ``Parameter.sharding`` spec (or a
  ``param_specs``/``feed_specs`` override) names must exist on the mesh,
  and every sharded dim must divide by the product of its axis sizes —
  GSPMD otherwise fails deep inside jit with a partitioner error naming
  an HLO instruction instead of the parameter.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from ..core.program import LEN2_SUFFIX, LEN_SUFFIX, _sub_block_indices
from .diagnostics import ValidationReport, diag
from .verifier import SIDE_EFFECT_OPS

# feeds may carry -1 in the batch dim plus one dynamic dim per lod level
# (the padded time dims the DataFeeder buckets); anything else retraces
_DYNAMIC_PREFIX_BASE = 1


# ---------------------------------------------------------------------------
# PT020: dead ops
# ---------------------------------------------------------------------------
def run_dead_op_lint(program, fetch_names: Sequence[str],
                     report: ValidationReport):
    """Backward reachability from fetches + persistable writes + side
    effects.  Deliberately NOT shared with ``Program.prune``'s walk
    (core/program.py): prune computes the minimal fetch slice, while this
    lint's liveness is broader — state updates stay live (an optimizer op
    IS the point of a train program) and so do side-effect ops — so the
    two would disagree by design."""
    block = program.global_block()
    persistable: Set[str] = {
        v.name for b in program.blocks for v in b.vars.values()
        if v.persistable}

    needed: Set[str] = set(fetch_names)
    # length companions ride with their base fetch — in both directions:
    # fetching a base keeps its @LEN/@LEN2 alive, and fetching a companion
    # alone (a supported executor pattern) must reach the base's producer,
    # whose output_names contain only the base
    for n in list(needed):
        needed.add(n + LEN_SUFFIX)
        needed.add(n + LEN2_SUFFIX)
        while n.endswith(LEN_SUFFIX) or n.endswith(LEN2_SUFFIX):
            n = n[:-len(LEN2_SUFFIX)] if n.endswith(LEN2_SUFFIX) \
                else n[:-len(LEN_SUFFIX)]
            needed.add(n)
    live: List[bool] = [False] * len(block.ops)
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        out_names = set(op.output_names)
        is_live = (
            bool(out_names & needed)
            or op.type in SIDE_EFFECT_OPS
            or bool(out_names & persistable)
        )
        if not is_live:
            continue
        live[idx] = True
        needed.update(op.input_names)
        for n in op.input_names:
            needed.add(n + LEN_SUFFIX)
            needed.add(n + LEN2_SUFFIX)
        # a live op keeps everything its sub-blocks read live too —
        # TRANSITIVELY, so a doubly-nested body (rnn inside rnn) still
        # pins its global-block producers
        stack = list(_sub_block_indices(op))
        seen: Set[int] = set()
        while stack:
            bi = stack.pop()
            if bi in seen or bi >= len(program.blocks):
                continue
            seen.add(bi)
            for sop in program.blocks[bi].ops:
                needed.update(sop.input_names)
                stack.extend(_sub_block_indices(sop))
    for idx, op in enumerate(block.ops):
        if not live[idx]:
            report.add(diag(
                "PT020",
                f"op {op.type!r} (outputs {sorted(op.output_names)}) is "
                f"unreachable from fetch targets "
                f"{sorted(set(fetch_names))}, state writes and side "
                f"effects — dead code", op=(0, idx, op.type)))


# ---------------------------------------------------------------------------
# PT021 / PT022: retrace hazards
# ---------------------------------------------------------------------------
def run_retrace_lints(program, report: ValidationReport):
    for b in program.blocks:
        for v in b.vars.values():
            if not v.is_data:
                continue
            if v.shape is None:
                report.add(diag(
                    "PT021",
                    f"feed var {v.name!r} declares no static shape: every "
                    f"novel feed shape compiles a new step variant",
                    var=v.name))
                continue
            allowed_prefix = _DYNAMIC_PREFIX_BASE + v.lod_level
            bad = [i for i, d in enumerate(v.shape)
                   if d == -1 and i >= allowed_prefix]
            if bad:
                report.add(diag(
                    "PT021",
                    f"feed var {v.name!r} shape {list(v.shape)} has "
                    f"symbolic dims at position(s) {bad} beyond the "
                    f"batch/sequence prefix — each distinct concrete "
                    f"shape retraces and recompiles", var=v.name))

    persistable: Set[str] = {
        v.name for b in program.blocks for v in b.vars.values()
        if v.persistable}
    block = program.global_block()
    for idx, op in enumerate(block.ops):
        if not op.inputs or not any(op.input_names):
            continue        # initializer pattern (startup program)
        if _sub_block_indices(op):
            continue        # loop carries legitimately rebind
        in_names = set(op.input_names)
        for name in op.output_names:
            if name in persistable and name not in in_names:
                report.add(diag(
                    "PT022",
                    f"op rebinds persistable var {name!r} without reading "
                    f"it — per-step state rebinding defeats donation and "
                    f"risks signature drift (retrace per step)",
                    op=(0, idx, op.type), var=name))


# ---------------------------------------------------------------------------
# PT030 / PT031: sharding-spec consistency
# ---------------------------------------------------------------------------
def _axes_of(entry) -> List[str]:
    if entry is None:
        return []
    if isinstance(entry, (list, tuple)):
        return [str(a) for a in entry]
    return [str(entry)]


def _spec_entries(spec) -> List:
    """PartitionSpec / tuple / list -> list of per-dim entries."""
    return list(spec)


def run_sharding_lints(program, mesh_axes: Optional[Dict[str, int]],
                       report: ValidationReport,
                       param_specs: Optional[Dict] = None,
                       feed_specs: Optional[Dict] = None):
    """Validate every sharding spec against the mesh.  ``mesh_axes`` maps
    axis name -> size; None skips the pass (no mesh context)."""
    if mesh_axes is None:
        return
    specs: Dict[str, tuple] = {}
    for b in program.blocks:
        for v in b.vars.values():
            sh = getattr(v, "sharding", None)
            if sh:
                specs[v.name] = ("parameter", sh, v.shape)
    for name, spec in (param_specs or {}).items():
        v = None
        for b in program.blocks:
            if name in b.vars:
                v = b.vars[name]
                break
        specs[name] = ("param_specs override", spec,
                       v.shape if v is not None else None)
    for name, spec in (feed_specs or {}).items():
        # feeds shard the batch dim (-1): only axis names are checkable
        specs[name] = ("feed_specs override", spec, None)

    for name, (origin, spec, shape) in sorted(specs.items()):
        entries = _spec_entries(spec)
        booked: Dict[str, int] = {}
        for dim_idx, entry in enumerate(entries):
            for ax in _axes_of(entry):
                if ax in booked:
                    # GSPMD rejects a spec that uses one mesh axis to shard
                    # two different dims of the same tensor
                    report.add(diag(
                        "PT040",
                        f"{origin} for {name!r}: mesh axis {ax!r} shards "
                        f"both dim {booked[ax]} and dim {dim_idx} — an "
                        f"axis can partition at most one dim", var=name))
                else:
                    booked[ax] = dim_idx
        if shape is not None and len(entries) > len(shape):
            report.add(diag(
                "PT031",
                f"{origin} for {name!r}: spec {entries} has more entries "
                f"than the var has dims ({list(shape)})", var=name))
        for dim_idx, entry in enumerate(entries):
            axes = _axes_of(entry)
            size = 1
            for ax in axes:
                if ax not in mesh_axes:
                    report.add(diag(
                        "PT030",
                        f"{origin} for {name!r}: axis {ax!r} is not a "
                        f"mesh axis (mesh has "
                        f"{sorted(mesh_axes)})", var=name))
                else:
                    size *= int(mesh_axes[ax])
            if size <= 1 or shape is None or dim_idx >= len(shape):
                continue
            d = shape[dim_idx]
            if d >= 0 and d % size != 0:
                report.add(diag(
                    "PT031",
                    f"{origin} for {name!r}: dim {dim_idx} (size {d}) is "
                    f"not divisible by the sharding extent {size} "
                    f"({_axes_of(entry)})", var=name))


def mesh_axes_of(mesh) -> Optional[Dict[str, int]]:
    """Normalize a jax Mesh / dict / None into {axis: size}."""
    if mesh is None:
        return None
    if isinstance(mesh, dict):
        return {str(k): int(v) for k, v in mesh.items()}
    try:
        return {str(a): int(mesh.shape[a]) for a in mesh.axis_names}
    except Exception as e:          # noqa: BLE001 — diagnostic context
        raise TypeError(
            f"mesh must be a jax.sharding.Mesh or an axis->size dict, got "
            f"{type(mesh).__name__}") from e
