"""Static shape & dtype inference over the Program IR.

The reference runs per-op ``InferShape`` inside its C++ desc layer the moment
an OpDesc is appended (op_desc.cc InferShape hooks, operator.h
InferShapeContext) — a malformed graph fails at *build* time with the op
named.  paddle_tpu traces programs straight into JAX, so without this pass a
shape bug surfaces as an XLA trace error deep inside ``Executor.run``.

This module recovers build-time checking TPU-natively:

* :class:`VarInfo` is the abstract value — a shape tuple whose dims may be
  ``-1`` (symbolic: the batch dim of feeds, or anything unknown), a numpy
  dtype, and the declared lod level.  ``None`` shape means fully unknown;
  unknowns propagate silently so partial programs never false-positive.
* Per-op rules are registered next to their lowerings via
  ``core.registry.register_shape_fn`` (rule helpers below keep them one-
  liners for the common families); ops that are genuinely dynamic (control
  flow interiors, beam search, detection post-processing) are enumerated in
  :data:`SHAPE_INFER_ALLOWLIST` — the explicit, tier-1-enforced remainder.
* :func:`run_shape_inference` walks each block in program order, applies
  rules, and reports (codes in analysis.diagnostics):

  - **PT010** the rule itself rejects the inputs (e.g. matmul contraction
    mismatch, elementwise broadcast impossibility);
  - **PT011** the inferred dtype contradicts the declared dtype (different
    numeric *kind*: float vs int vs bool — width-only drift is tolerated
    because AMP/x64 legitimately rewrite widths at trace time);
  - **PT012** the inferred shape contradicts the declared shape (a dim
    conflicts where both sides are concrete; ``-1`` matches anything).

Inference runs at validation time only — never inside the stepped hot path
(the executor memoizes per (program version, signature); see
tests/test_analysis.py::test_validation_runs_once).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.types import convert_dtype
from .diagnostics import ValidationReport, diag

# ---------------------------------------------------------------------------
# Abstract values
# ---------------------------------------------------------------------------


class ShapeError(ValueError):
    """Raised by a shape rule when the op's inputs are statically
    incompatible (reported as PT010 at the op's graph location)."""


class VarInfo:
    """Abstract (shape, dtype, lod_level) of one variable.

    ``shape`` is ``None`` (unknown) or a tuple of ints where ``-1`` marks a
    symbolic/unknown dim; ``dtype`` is ``None`` or a numpy dtype.
    """

    __slots__ = ("shape", "dtype", "lod_level")

    def __init__(self, shape=None, dtype=None, lod_level: int = 0):
        self.shape = tuple(int(s) for s in shape) if shape is not None \
            else None
        self.dtype = convert_dtype(dtype) if dtype is not None else None
        self.lod_level = int(lod_level)

    @property
    def known(self) -> bool:
        return self.shape is not None

    @property
    def ndim(self) -> Optional[int]:
        return None if self.shape is None else len(self.shape)

    def with_shape(self, shape) -> "VarInfo":
        return VarInfo(shape, self.dtype, self.lod_level)

    def with_dtype(self, dtype) -> "VarInfo":
        return VarInfo(self.shape, dtype, self.lod_level)

    def __repr__(self):
        dt = self.dtype.name if self.dtype is not None else "?"
        return f"VarInfo({list(self.shape) if self.known else '?'}, {dt})"


def UNKNOWN() -> VarInfo:
    return VarInfo(None, None)


# ---------------------------------------------------------------------------
# Dim / shape algebra (-1 = unknown, matches anything)
# ---------------------------------------------------------------------------
def dim_ok(a: int, b: int) -> bool:
    return a < 0 or b < 0 or a == b


def unify_dim(a: int, b: int) -> int:
    """Prefer the concrete dim; two concrete dims must already agree."""
    return b if a < 0 else a


def shapes_compatible(a, b) -> bool:
    """Used for declared-vs-inferred comparison.  Ranks must agree (with a
    size-1 escape hatch: () vs (1,) style scalars compare equal — jnp
    reductions produce rank-0 where the reference declares [1]) and every
    concrete dim pair must match."""
    if a is None or b is None:
        return True
    if len(a) != len(b):
        return _all_ones(a) and _all_ones(b)
    return all(dim_ok(x, y) for x, y in zip(a, b))


def _all_ones(s) -> bool:
    return all(d == 1 for d in s)


def numpy_broadcast(a, b, what: str = "operands"):
    """NumPy-style trailing broadcast of two shapes; raises ShapeError."""
    if a is None or b is None:
        return None
    out = []
    for i in range(1, max(len(a), len(b)) + 1):
        da = a[-i] if i <= len(a) else 1
        db = b[-i] if i <= len(b) else 1
        # a -1 against a 1 stays UNKNOWN (the runtime result is whatever
        # the -1 turns out to be), never collapses to the 1
        if da == 1:
            out.append(db)
        elif db == 1:
            out.append(da)
        elif dim_ok(da, db):
            out.append(unify_dim(da, db))
        else:
            raise ShapeError(
                f"cannot broadcast {what}: {list(a)} vs {list(b)}")
    return tuple(reversed(out))


def prod_dims(dims: Sequence[int]) -> int:
    p = 1
    for d in dims:
        if d < 0:
            return -1
        p *= d
    return p


def conv_out_dim(size: int, k: int, pad: int, stride: int,
                 dilation: int = 1, ceil_mode: bool = False) -> int:
    if size < 0:
        return -1
    eff = dilation * (k - 1) + 1
    num = size + 2 * pad - eff
    if num < 0:
        raise ShapeError(
            f"window (k={k}, dilation={dilation}) larger than padded input "
            f"dim {size}+2*{pad}")
    if ceil_mode:
        return -(-num // stride) + 1
    return num // stride + 1


def first(ins: Dict[str, List[VarInfo]], slot: str) -> VarInfo:
    vals = ins.get(slot)
    return vals[0] if vals else UNKNOWN()


# ---------------------------------------------------------------------------
# Rule helper factories (imported by ops/*.py next to the lowerings)
# ---------------------------------------------------------------------------
def same_as(slot: str = "X", out: str = "Out", dtype=None,
            also: Tuple[str, ...] = ()):
    """Output(s) copy the first input of ``slot``'s shape; optional dtype
    override; ``also`` lists extra output slots with the same info."""

    def rule(op, ins, attrs):
        x = first(ins, slot)
        o = x if dtype is None else x.with_dtype(dtype)
        res = {out: o}
        for extra in also:
            res[extra] = o
        return res

    return rule


def elementwise(out: str = "Out", dtype=None):
    """Describes the ``math_ops._bcast`` lowering exactly: equal shapes
    short-circuit before any axis check; axis -1/None is FULL numpy
    broadcasting of X and Y (Y rank may exceed X's); an explicit axis
    right-pads Y with 1s so it matches a contiguous run of X's dims
    starting at ``axis``, then numpy-broadcasts.  Out shape is the
    broadcast result (not necessarily X's: X dims of 1 widen)."""

    def rule(op, ins, attrs):
        x, y = first(ins, "X"), first(ins, "Y")
        axis = attrs.get("axis", -1)
        out_shape = None
        if x.shape is not None and y.shape is not None:
            if axis in (-1, None) or tuple(x.shape) == tuple(y.shape):
                out_shape = numpy_broadcast(x.shape, y.shape,
                                            f"{op.type} X/Y")
            else:
                trailing = len(x.shape) - axis - len(y.shape)
                if len(y.shape) > len(x.shape) or trailing < 0:
                    raise ShapeError(
                        f"elementwise: bad axis {axis} for shapes "
                        f"{list(x.shape)} {list(y.shape)}")
                y_padded = (1,) * axis + tuple(y.shape) + (1,) * trailing
                out_shape = numpy_broadcast(
                    x.shape, y_padded,
                    f"{op.type} X/Y at axis {axis}")
        o = x if dtype is None else x.with_dtype(dtype)
        if out_shape is not None:
            o = o.with_shape(out_shape)
        return {out: o}

    return rule


def reduce_rule(out: str = "Out"):
    """reduce_op.cc semantics: dim/keep_dim/reduce_all attrs."""

    def rule(op, ins, attrs):
        x = first(ins, "X")
        if x.shape is None:
            return {out: x}
        keep = attrs.get("keep_dim", False)
        if attrs.get("reduce_all", False):
            shape = (1,) * len(x.shape) if keep else ()
            return {out: x.with_shape(shape)}
        dim = attrs.get("dim", [0])
        axes = tuple(dim) if isinstance(dim, (list, tuple)) else (int(dim),)
        nd = len(x.shape)
        for a in axes:
            if not -nd <= a < nd:
                raise ShapeError(
                    f"reduce axis {a} out of range for rank {nd}")
        axes = {a % nd for a in axes}
        if keep:
            shape = tuple(1 if i in axes else d
                          for i, d in enumerate(x.shape))
        else:
            shape = tuple(d for i, d in enumerate(x.shape)
                          if i not in axes)
        return {out: x.with_shape(shape)}

    return rule


def mirror(mapping: Dict[str, str]):
    """Each output slot copies the info of a named input slot — the
    optimizer-op family (ParamOut <- Param, MomentOut <- Moment, ...)."""

    def rule(op, ins, attrs):
        res = {}
        for out_slot, in_slot in mapping.items():
            if op.outputs.get(out_slot):
                res[out_slot] = first(ins, in_slot)
        return res

    return rule


def filled_from_attrs(out: str = "Out", default_dtype="float32"):
    """fill_constant / *_random family: shape + dtype attrs."""

    def rule(op, ins, attrs):
        shape = tuple(int(s) for s in attrs.get("shape", ()))
        dt = attrs.get("dtype", default_dtype)
        return {out: VarInfo(shape, dt)}

    return rule


def passthrough(*slots, out: str = "Out"):
    """First present input slot forwards to ``out`` (feed/fetch/print)."""

    def rule(op, ins, attrs):
        for s in slots:
            if ins.get(s):
                return {out: ins[s][0]}
        return {}

    return rule


def no_outputs():
    """Side-effect-only ops (save/load/assert): nothing to infer."""

    def rule(op, ins, attrs):
        return {}

    return rule


def squeeze_ids(ids: VarInfo) -> Optional[Tuple[int, ...]]:
    """The id-tensor convention: [..., 1] squeezes its trailing 1
    (lookup_table, one_hot)."""
    if ids.shape is None:
        return None
    s = ids.shape
    if len(s) >= 2 and s[-1] == 1:
        s = s[:-1]
    return s


# ---------------------------------------------------------------------------
# Explicit remainder: ops with NO static rule.  Every entry is here for a
# reason; tier-1 asserts registered_ops() == rules ∪ this list exactly.
# ---------------------------------------------------------------------------
SHAPE_INFER_ALLOWLIST = frozenset({
    # control flow: outputs are whatever the sub-block carries bind
    "while", "conditional_block", "rnn", "recurrent",
    # tensor-array writes allocate their buffer from runtime env state
    "write_to_array",
    # beam search: output layout depends on decode-time trace-back
    "beam_search", "beam_search_decode",
    # lowered specially by the executor (jax.value_and_grad section);
    # its Grads outputs are declared by append_backward with param shapes
    "backward",
    # (the detection post-processing family — roi_pool, prior_box,
    # box_coder, ssd_loss, multiclass_nms, detection_output — moved OFF
    # this list: their static-shape TPU lowerings have exact rules in
    # ops/detection_ops.py, unlike the reference's ragged LoD outputs)
})


# ---------------------------------------------------------------------------
# The inference pass
# ---------------------------------------------------------------------------
def _declared_info(block, name: str) -> Optional[VarInfo]:
    v = block._find_var_recursive(name)
    if v is None:
        return None
    return VarInfo(v.shape, v.dtype, v.lod_level)


def _kind(dt: np.dtype) -> str:
    # bool is its own kind; (u)int collapse; float16/bf16/32/64 collapse
    if dt == np.dtype(np.bool_):
        return "b"
    return "f" if dt.kind == "f" or dt.name == "bfloat16" else "iu"


def _sub_block_op(op) -> bool:
    from ..core.program import _sub_block_indices
    return bool(_sub_block_indices(op))


def run_shape_inference(program, report: ValidationReport) -> Dict[int, Dict[str, VarInfo]]:
    """Infer shapes/dtypes per block; append PT010/PT011/PT012 findings.

    Returns {block_idx: {var name: VarInfo}} (inspectable by tests).
    Sub-blocks are walked leniently: their binder vars (loop carries, step
    inputs) are seeded from declarations, and unknowns stay silent.
    """
    from ..core.registry import get_shape_fn
    all_known: Dict[int, Dict[str, VarInfo]] = {}
    for block in program.blocks:
        known: Dict[str, VarInfo] = {}
        all_known[block.idx] = known

        def lookup(name: str, _known=known, _block=block) -> VarInfo:
            if name in _known:
                return _known[name]
            # parent block values inferred earlier in program order
            b = _block.parent_block
            while b is not None:
                parent_known = all_known.get(b.idx)
                if parent_known and name in parent_known:
                    return parent_known[name]
                b = b.parent_block
            dec = _declared_info(_block, name)
            return dec if dec is not None else UNKNOWN()

        for op_idx, op in enumerate(block.ops):
            rule = get_shape_fn(op.type)
            outs: Dict[str, List[VarInfo]] = {}
            if rule is not None and not _sub_block_op(op):
                ins = {slot: [lookup(n) for n in names]
                       for slot, names in op.inputs.items() if names}
                try:
                    res = rule(op, ins, op.attrs) or {}
                except ShapeError as e:
                    report.add(diag(
                        "PT010",
                        f"op {op.type!r}: {e}", op=(block.idx, op_idx,
                                                    op.type)))
                    res = {}
                except Exception as e:  # noqa: BLE001 — malformed programs
                    # are exactly the input under validation: a rule that
                    # unpacks a wrong-rank shape or indexes a missing attr
                    # must degrade to a diagnostic, never crash the
                    # verifier with the opaque trace it exists to replace
                    report.add(diag(
                        "PT010",
                        f"op {op.type!r}: shape rule failed on its inputs "
                        f"({type(e).__name__}: {e})",
                        op=(block.idx, op_idx, op.type)))
                    res = {}
                for slot, val in res.items():
                    outs[slot] = val if isinstance(val, list) else [val]
            # bind outputs: inferred info wins; declarations fill the gaps
            for slot, names in op.outputs.items():
                vals = outs.get(slot, [])
                for i, name in enumerate(names):
                    inferred = vals[i] if i < len(vals) else None
                    dec = _declared_info(block, name)
                    if inferred is None or not (inferred.known or
                                                inferred.dtype is not None):
                        known[name] = dec if dec is not None else UNKNOWN()
                        continue
                    if dec is not None:
                        _check_against_declared(
                            report, block, op_idx, op, name, inferred, dec)
                        # lod level is declaration-owned metadata
                        inferred = VarInfo(inferred.shape, inferred.dtype,
                                           dec.lod_level)
                    known[name] = inferred
    return all_known


def _check_against_declared(report, block, op_idx, op, name,
                            inferred: VarInfo, dec: VarInfo):
    loc = (block.idx, op_idx, op.type)
    if inferred.dtype is not None and dec.dtype is not None and \
            _kind(inferred.dtype) != _kind(dec.dtype):
        report.add(diag(
            "PT011",
            f"op {op.type!r} produces dtype {inferred.dtype.name} for "
            f"var {name!r} declared {dec.dtype.name}", op=loc, var=name))
    if inferred.known and dec.known and \
            not shapes_compatible(inferred.shape, dec.shape):
        report.add(diag(
            "PT012",
            f"op {op.type!r} produces shape {list(inferred.shape)} for "
            f"var {name!r} declared {list(dec.shape)}", op=loc, var=name))


def coverage() -> Tuple[int, int]:
    """(ops with a rule, total registered ops) — the README number and the
    tier-1 floor (>= 80%)."""
    from ..core.registry import registered_ops, registered_shape_fns
    total = registered_ops()
    return len(registered_shape_fns()), len(total)
