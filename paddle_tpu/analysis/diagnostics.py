"""Structured diagnostics for the static program verifier.

The reference validates graphs inside its C++ desc layer — InferShape
hard-CHECKs (operator.cc RunImpl -> InferShapeContext), OpDesc attribute
checking (op_desc.cc), PADDLE_ENFORCE formatting (enforce.h) — and a failed
check aborts with a C++ stack trace.  paddle_tpu's model-as-data IR
(core/program.py) deliberately dropped that layer, so this module supplies
its replacement: every verification pass emits :class:`Diagnostic` records
with *stable* ``PT0xx`` codes instead of raising mid-walk, and a
:class:`ValidationReport` renders them as a readable, greppable report.

Code registry (frozen — new checks take new codes, existing codes never
change meaning):

========  ========  =====================================================
code      severity  meaning
========  ========  =====================================================
PT001     error     op input names a variable declared nowhere
PT002     error     use-before-def: input is never produced before use
PT003     warning   op output name is not declared in any visible block
PT004     warning   duplicate writers: var rebound by a non-reading op
PT005     error     op type has no registered lowering
PT006     error     orphaned companion: @GRAD/@LEN var without a base
PT007     error     dependency cycle among ops (via non-in-place defs)
PT010     error     shape inference: op inputs are incompatible
PT011     error     inferred dtype contradicts the declared dtype
PT012     error     inferred shape contradicts the declared shape
PT020     warning   dead op: unreachable from any fetch/state/effect
PT021     warning   retrace hazard: feed signature cannot stay stable
PT022     warning   retrace hazard: persistable var rebound per step
PT030     error     sharding spec names an axis the mesh does not have
PT031     error     sharded dim not divisible by its mesh axis size
PT040     error     sharding spec double-books a mesh axis across dims
PT041     warning   sharding conflict at an op: a reshard is required
PT042     warning   sharding propagation blind spot: op has no shard rule
PT050     warning   shared attribute written both under and outside a lock
PT051     error     static lock-acquisition-order cycle
PT052     warning   blocking call while holding a lock
PT053     error     Condition.wait outside a while-predicate loop
PT054     error     lock acquisition reachable from a signal handler
PT055     warning   framework thread without a registered pt- name prefix
========  ========  =====================================================

The PT05x family is emitted by :mod:`.concurrency` — an AST pass over the
*host source tree* (the threaded runtime itself), not the Program IR, so
its diagnostics locate findings as ``path:line`` in the message and leave
``op`` empty.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

ERROR = "error"
WARNING = "warning"

#: code -> (default severity, one-line description)
CODES = {
    "PT001": (ERROR, "undefined input variable"),
    "PT002": (ERROR, "variable used before any producer"),
    "PT003": (WARNING, "output variable not declared"),
    "PT004": (WARNING, "duplicate writers of a variable"),
    "PT005": (ERROR, "unregistered op type"),
    "PT006": (ERROR, "orphaned @GRAD/@LEN companion"),
    "PT007": (ERROR, "dependency cycle among ops"),
    "PT010": (ERROR, "shape inference failed"),
    "PT011": (ERROR, "dtype mismatch vs declaration"),
    "PT012": (ERROR, "shape mismatch vs declaration"),
    "PT020": (WARNING, "dead op unreachable from targets"),
    "PT021": (WARNING, "retrace hazard: unstable feed signature"),
    "PT022": (WARNING, "retrace hazard: persistable var rebound"),
    "PT030": (ERROR, "sharding spec names unknown mesh axis"),
    "PT031": (ERROR, "sharded dim not divisible by axis size"),
    "PT040": (ERROR, "mesh axis double-booked across dims of one spec"),
    "PT041": (WARNING, "sharding conflict at an op (reshard required)"),
    "PT042": (WARNING, "sharding propagation blind spot (no shard rule)"),
    "PT050": (WARNING, "shared attribute written both under and outside "
                       "a lock (guard inconsistency)"),
    "PT051": (ERROR, "static lock-acquisition-order cycle"),
    "PT052": (WARNING, "blocking call while holding a lock"),
    "PT053": (ERROR, "Condition.wait outside a while-predicate loop"),
    "PT054": (ERROR, "lock acquisition reachable from a signal handler"),
    "PT055": (WARNING, "framework thread without a registered pt- name "
                       "prefix"),
}


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: stable code + severity + graph location + message.

    ``op`` locates the finding as ``(block_idx, op_idx, op_type)`` — the
    Program-IR analog of the reference's per-op PADDLE_ENFORCE context
    (enforce.h formats the op type and the failing check) — or ``None``
    for program-level findings (e.g. a bad sharding spec on a parameter).
    ``var`` names the variable involved when there is one.
    """

    code: str
    severity: str
    message: str
    op: Optional[Tuple[int, int, str]] = None
    var: Optional[str] = None

    def __post_init__(self):
        if self.code not in CODES:
            raise ValueError(f"unknown diagnostic code {self.code!r}")
        if self.severity not in (ERROR, WARNING):
            raise ValueError(f"bad severity {self.severity!r}")

    @property
    def is_error(self) -> bool:
        return self.severity == ERROR

    def render(self) -> str:
        loc = ""
        if self.op is not None:
            b, i, t = self.op
            loc = f" at block {b} op #{i} ({t})"
        var = f" [var {self.var!r}]" if self.var else ""
        return f"{self.code} {self.severity}{loc}{var}: {self.message}"

    def __str__(self):
        return self.render()


def diag(code: str, message: str, op=None, var: Optional[str] = None,
         severity: Optional[str] = None) -> Diagnostic:
    """Build a Diagnostic with the code's default severity unless overridden."""
    sev = severity or CODES[code][0]
    op_loc = None
    if op is not None:
        # accept a core.program.Operator (located via its block) or a tuple
        if isinstance(op, tuple):
            op_loc = op
        else:
            block = op.block
            try:
                idx = block.ops.index(op)
            except ValueError:
                idx = -1
            op_loc = (block.idx, idx, op.type)
    return Diagnostic(code=code, severity=sev, message=message, op=op_loc,
                      var=var)


class ProgramVerificationError(ValueError):
    """Raised when a program fails validation with error-severity findings.

    Carries the full :class:`ValidationReport` so callers (and tests) can
    inspect individual codes instead of parsing the rendered text.
    """

    def __init__(self, report: "ValidationReport"):
        self.report = report
        super().__init__(report.render())


class ValidationReport:
    """Ordered collection of diagnostics from one validation run."""

    def __init__(self, diagnostics: Optional[List[Diagnostic]] = None):
        self.diagnostics: List[Diagnostic] = list(diagnostics or [])

    # -- building ---------------------------------------------------------
    def add(self, d: Diagnostic):
        self.diagnostics.append(d)

    def extend(self, ds):
        self.diagnostics.extend(ds)

    # -- queries ----------------------------------------------------------
    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.is_error]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if not d.is_error]

    @property
    def ok(self) -> bool:
        return not self.errors

    def codes(self) -> List[str]:
        return [d.code for d in self.diagnostics]

    def __len__(self):
        return len(self.diagnostics)

    def __iter__(self):
        return iter(self.diagnostics)

    def __bool__(self):
        # truthiness == "has findings", so `if report:` reads naturally
        return bool(self.diagnostics)

    def raise_on_error(self) -> "ValidationReport":
        if self.errors:
            raise ProgramVerificationError(self)
        return self

    def render(self) -> str:
        if not self.diagnostics:
            return "program verifier: OK (0 diagnostics)"
        lines = [f"program verifier: {len(self.errors)} error(s), "
                 f"{len(self.warnings)} warning(s)"]
        lines += [f"  {d.render()}" for d in self.diagnostics]
        return "\n".join(lines)

    def __str__(self):
        return self.render()

    def __repr__(self):
        return (f"ValidationReport(errors={len(self.errors)}, "
                f"warnings={len(self.warnings)})")
