"""Static program verification for the Program IR.

The build-time analog of the reference's C++ desc-layer validation
(InferShape in op_desc.cc/operator.h, OpDesc attr checking against the
OpInfoMap, PADDLE_ENFORCE context in enforce.h) — run over paddle_tpu's
model-as-data ``Program`` *before* it is traced into XLA, so a malformed
graph fails with a stable ``PT0xx`` diagnostic naming the op, not a JAX
stack trace from inside ``Executor.run``.

Four passes (each its own module):

1. :mod:`.verifier` — well-formedness: dangling/undefined inputs,
   def-after-use cycles, unregistered op types, duplicate writers,
   orphaned ``@GRAD``/``@LEN`` companions (PT001-PT007).
2. :mod:`.shape_infer` — shape & dtype inference through per-op rules
   registered alongside the lowerings (``register_shape_fn``), with
   ``-1``-batch symbolic dims (PT010-PT012).
3. :mod:`.lints` — dead ops, retrace hazards, sharding-spec consistency
   for ``ShardedExecutor`` meshes (PT020-PT022, PT030-PT031, PT040).
4. :mod:`.diagnostics` — the stable code registry and report rendering.

On top of the verification passes sits the auto-sharding stack (one module
each, same IR, still chip-free): :mod:`.shard_prop` propagates per-dim
sharding annotations through per-op ``register_shard_fn`` rules
(PT041/PT042 conflicts), :mod:`.cost_model` prices a plan statically
(FLOPs/bytes/collectives/peak-HBM), and :mod:`.planner` enumerates, scores
and validates candidate ``param_specs``/``feed_specs`` for a mesh —
consumed by ``ShardedExecutor(auto_shard=True)`` and the
``python -m paddle_tpu plan`` CLI.

Entry points: :func:`validate_program` here, ``Program.validate()``,
``Executor(validate=True)`` / the ``validate`` flag
(``PADDLE_TPU_VALIDATE=1``), ``Trainer.train(validate=True)``, and the
CLI ``python -m paddle_tpu check prog.json``.  The Executor validates
*before* compile-cache fingerprinting, so an invalid program can never be
installed in (or persisted to) the compilation cache, and memoizes per
(program version, signature) so validation cost is never in the stepped
hot path.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from .diagnostics import (CODES, Diagnostic, ProgramVerificationError,
                          ValidationReport, diag)
from .lints import (mesh_axes_of, run_dead_op_lint, run_retrace_lints,
                    run_sharding_lints)
from .shape_infer import (SHAPE_INFER_ALLOWLIST, ShapeError, VarInfo,
                          coverage, run_shape_inference)
from .shard_prop import (PropagationResult, ShardConflict, ShardInfo,
                         propagate_sharding)
from .verifier import run_verifier

__all__ = [
    "CODES", "Diagnostic", "ProgramVerificationError", "ValidationReport",
    "ShapeError", "VarInfo", "SHAPE_INFER_ALLOWLIST", "coverage",
    "validate_program", "diag", "propagate_sharding", "PropagationResult",
    "ShardConflict", "ShardInfo",
]


def validate_program(program,
                     fetch_list: Optional[Sequence] = None,
                     mesh=None,
                     param_specs: Optional[Dict] = None,
                     feed_specs: Optional[Dict] = None) -> ValidationReport:
    """Run all static verification passes over ``program``.

    ``fetch_list`` (Variables or names) enables the dead-op lint — without
    targets deadness is undefined, so PT020 is skipped.  ``mesh`` (a
    ``jax.sharding.Mesh`` or an axis->size dict) enables the sharding
    checks, with optional ``param_specs``/``feed_specs`` overrides exactly
    as ``ShardedExecutor`` takes them.

    Returns a :class:`ValidationReport`; call ``.raise_on_error()`` to turn
    error-severity findings into :class:`ProgramVerificationError`.  Each
    invocation bumps the ``validations`` counter in
    ``profiler.compile_stats()`` — the telemetry the zero-steady-state-
    overhead test pins.
    """
    from ..core import compile_cache
    compile_cache.stats().bump("validations")

    report = ValidationReport()
    run_verifier(program, report)
    run_shape_inference(program, report)
    if fetch_list is not None:
        fetch_names = [getattr(v, "name", None) or str(v)
                       for v in fetch_list]
        run_dead_op_lint(program, fetch_names, report)
    run_retrace_lints(program, report)
    run_sharding_lints(program, mesh_axes_of(mesh), report,
                       param_specs=param_specs, feed_specs=feed_specs)
    return report
