"""Static cost & memory model over the Program IR.

Ranks candidate sharding plans *analytically* — no trial compilation, no
chip.  The reference framework had no analog (plans were hand-written
cluster configs); the closest ancestor is the roofline arithmetic in
benchmark/roofline_rnn.py, promoted here to a per-op pass:

* **FLOPs** per op from the shapes the verifier already infers
  (analysis.shape_infer): matmul-family ops count ``2*M*K*N``, convs count
  ``2 * out_elems * Cin * kh * kw``, recurrences unroll over T, everything
  else falls back to one op per output element (bandwidth-bound anyway).
* **Bytes** per op: inputs read + outputs written, each divided by its
  sharding extent (a dp8-sharded activation moves 1/8 of its bytes per
  device).
* **Collectives**: the dp gradient all-reduce (``2*(E-1)/E * bytes`` per
  ring all-reduce), the row-parallel partial-sum all-reduce where a
  matched sharded contraction meets (Megatron's f/g), and a reshard charge
  for every PT041 conflict site the propagation pass reported.
* **Peak HBM** per device: persistable state + a liveness walk over the
  global block (a var is live from its producer to its last consumer; with
  a ``backward`` pseudo-op every forward intermediate is pinned live until
  the backward — XLA holds activations for the VJP).

The absolute numbers use nominal TPU constants (PEAK_FLOPS / HBM_GBPS /
ICI_GBPS below) and a caller-supplied batch assumption for symbolic ``-1``
dims; they are *ranking* quantities — two plans compared under the same
constants — not predictions of wall-clock.  Symbolic dims that are not the
batch dim also resolve to the batch assumption (documented caveat).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from .diagnostics import ValidationReport
from .shard_prop import PropagationResult, spec_extent

# nominal single-chip constants (TPU v4-class, bf16): only plan *ranking*
# depends on them, so order-of-magnitude fidelity is enough
PEAK_FLOPS = 275e12
HBM_GBPS = 1.2e12
ICI_GBPS = 4.5e10


def _numel(shape, assume: int) -> int:
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= assume if d < 0 else int(d)
    return n


def _itemsize(info) -> int:
    if info is None or info.dtype is None:
        return 4
    return int(np.dtype(info.dtype).itemsize)


@dataclasses.dataclass
class OpCost:
    loc: Tuple[int, int, str]
    flops: float
    bytes: float
    collective_bytes: float = 0.0


@dataclasses.dataclass
class CostReport:
    """Per-device static cost of one (program, plan) pair."""

    mesh_axes: Dict[str, int]
    flops_total: float = 0.0
    flops_per_device: float = 0.0
    hbm_bytes_per_device: float = 0.0
    collective_bytes: float = 0.0          # structural (all-reduces)
    reshard_bytes: float = 0.0             # PT041 conflict charges
    peak_hbm_bytes_per_device: float = 0.0
    op_costs: List[OpCost] = dataclasses.field(default_factory=list)
    # per-op-CLASS calibrated proxy (measured/predicted ratios from the
    # opprof profiler applied per op type); None = nominal constants only
    calibrated_step_time_s: Optional[float] = None

    @property
    def step_time_proxy_s(self) -> float:
        if self.calibrated_step_time_s is not None:
            return self.calibrated_step_time_s
        return (self.flops_per_device / PEAK_FLOPS
                + self.hbm_bytes_per_device / HBM_GBPS
                + (self.collective_bytes + self.reshard_bytes) / ICI_GBPS)

    def to_dict(self) -> dict:
        return {
            "mesh_axes": dict(self.mesh_axes),
            "flops_total": self.flops_total,
            "flops_per_device": self.flops_per_device,
            "hbm_bytes_per_device": self.hbm_bytes_per_device,
            "collective_bytes": self.collective_bytes,
            "reshard_bytes": self.reshard_bytes,
            "peak_hbm_bytes_per_device": self.peak_hbm_bytes_per_device,
            "step_time_proxy_s": self.step_time_proxy_s,
            "calibrated": self.calibrated_step_time_s is not None,
            "top_ops": [
                {"op": t, "block": b, "index": i,
                 "flops": c.flops, "bytes": c.bytes}
                for c in sorted(self.op_costs, key=lambda c: -c.flops)[:8]
                for (b, i, t) in [c.loc]],
        }


# ---------------------------------------------------------------------------
# Per-op FLOPs (full, unsharded; sharding divides afterwards)
# ---------------------------------------------------------------------------
def _mul_flops(op, shp, attrs, assume):
    x, y = shp("X"), shp("Y")
    if x is None or y is None:
        return 0.0
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    m = _numel(x[:xn], assume)
    k = _numel(x[xn:], assume)
    n = _numel(y[yn:], assume)
    return 2.0 * m * k * n


def _matmul_flops(op, shp, attrs, assume):
    x, y = shp("X"), shp("Y")
    if x is None or y is None or len(x) < 2 or len(y) < 2:
        return 0.0
    xs, ys = list(x), list(y)
    if attrs.get("transpose_X", False):
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if attrs.get("transpose_Y", False):
        ys[-1], ys[-2] = ys[-2], ys[-1]
    batch = _numel(xs[:-2], assume) or 1
    return 2.0 * batch * _numel([xs[-2], xs[-1], ys[-1]], assume)


def _conv2d_flops(op, shp, attrs, assume):
    x, w = shp("Input"), shp("Filter")
    out = shp.out("Output")
    if x is None or w is None or out is None or len(w) < 4:
        return 0.0
    return 2.0 * _numel(out, assume) * _numel(w[1:], assume)


def _lstm_flops(op, shp, attrs, assume):
    x = shp("Input")
    if x is None or len(x) != 3:
        return 0.0
    b, t, h4 = x
    h = max(1, (assume if h4 < 0 else h4) // 4)
    return 2.0 * _numel([b, t], assume) * h * (4 * h)


def _gru_flops(op, shp, attrs, assume):
    x = shp("Input")
    if x is None or len(x) != 3:
        return 0.0
    b, t, h3 = x
    h = max(1, (assume if h3 < 0 else h3) // 3)
    return 2.0 * _numel([b, t], assume) * h * (3 * h)


_FLOPS = {
    "mul": _mul_flops,
    "matmul": _matmul_flops,
    "conv2d": _conv2d_flops,
    "depthwise_conv2d": _conv2d_flops,
    "conv2d_transpose": _conv2d_flops,
    "lstm": _lstm_flops,
    "gru": _gru_flops,
}


class _ShapeView:
    """shp("X") -> first input shape of slot X; shp.out("Out") likewise."""

    def __init__(self, op, lookup):
        self.op = op
        self.lookup = lookup

    def _get(self, names):
        if not names:
            return None
        info = self.lookup(names[0])
        return None if info is None else info.shape

    def __call__(self, slot):
        return self._get(self.op.inputs.get(slot, []))

    def out(self, slot):
        return self._get(self.op.outputs.get(slot, []))


def estimate_cost(program, mesh_axes: Dict[str, int],
                  prop: Optional[PropagationResult] = None,
                  shapes=None, assume_batch: int = 64,
                  batch_axis: str = "dp",
                  op_class_ratios: Optional[Dict[str, float]] = None
                  ) -> CostReport:
    """Static per-device cost of one training/inference step under the
    sharding assignment in ``prop`` (replicated everywhere when None).

    ``op_class_ratios`` — measured/predicted correction factors per op
    TYPE (the opprof calibration table,
    ``observability.attribution.load_op_class_ratios``): when given, a
    calibrated proxy replaces the nominal one — each op's compute+HBM
    term scales by its class ratio (default 1.0), collective/reshard
    terms stay physical (the ICI model is not what the eager profile
    measured).  This is the per-op-class successor of the PR 10
    program-wide scalar ratio."""
    from .shape_infer import run_shape_inference

    mesh_axes = {k: int(v) for k, v in (mesh_axes or {}).items()}
    if shapes is None:
        shapes = run_shape_inference(program, ValidationReport())
    specs = prop.specs if prop is not None else {}
    gb = program.global_block()
    block_shapes = shapes.get(0, {})

    def lookup(name):
        info = block_shapes.get(name)
        if info is not None and info.shape is not None:
            return info
        v = gb._find_var_recursive(name)
        if v is None:
            return None
        from .shape_infer import VarInfo
        return VarInfo(v.shape, v.dtype)

    def var_bytes(name, per_device=True) -> float:
        info = lookup(name)
        if info is None or info.shape is None:
            return 0.0
        b = _numel(info.shape, assume_batch) * _itemsize(info)
        if per_device:
            b /= max(1, spec_extent(specs.get(name), mesh_axes))
        return float(b)

    def out_extent(op) -> int:
        exts = [spec_extent(specs.get(n), mesh_axes)
                for n in op.output_names if n in specs]
        return max(exts) if exts else 1

    dp_ext = int(mesh_axes.get(batch_axis, 1))
    # the batch axis only costs/saves anything when some value actually
    # shards over it (prop carries the candidate's feed seeds forward)
    dp_active = any(
        any(batch_axis in (e or ()) for e in sp)
        for sp in specs.values())
    report = CostReport(mesh_axes=mesh_axes)
    fwd_flops = 0.0
    fwd_flops_per_dev = 0.0
    for op_idx, op in enumerate(gb.ops):
        shp = _ShapeView(op, lookup)
        coll = 0.0
        if op.type == "backward":
            # the VJP replays the forward under the same sharding
            flops = 2.0 * fwd_flops
            per_dev_flops = 2.0 * fwd_flops_per_dev
            # the dp gradient all-reduce: every param grad not itself
            # sharded over the batch axis rides a ring all-reduce
            if dp_ext > 1 and dp_active:
                grad_bytes = sum(
                    var_bytes(p) for p in op.attrs.get("params", []))
                coll += 2.0 * (dp_ext - 1) / dp_ext * grad_bytes
        else:
            fn = _FLOPS.get(op.type)
            if fn is not None:
                flops = fn(op, shp, op.attrs, assume_batch)
            else:
                flops = float(sum(
                    _numel(getattr(lookup(n), "shape", None), assume_batch)
                    for n in op.output_names))
            fwd_flops += flops
            # contraction extent: a matched sharded contraction (Megatron
            # row-parallel) computes 1/ext of the work per device, then
            # all-reduces the partial outputs
            ext = out_extent(op)
            k_ext = 1
            if op.type in ("mul", "matmul"):
                y = op.inputs.get("Y", [])
                if y and y[0] in specs:
                    sp = specs[y[0]]
                    if op.type == "mul":
                        k_entries = sp[:op.attrs.get("y_num_col_dims", 1)]
                    elif op.attrs.get("transpose_Y", False):
                        # transposed Y contracts on its LAST dim — mirror
                        # shard_matmul's axis selection
                        k_entries = sp[-1:]
                    else:
                        k_entries = sp[-2:-1]
                    k_ext = max(1, spec_extent(tuple(k_entries), mesh_axes))
                    if k_ext > 1:
                        out_b = sum(var_bytes(n) for n in op.output_names)
                        coll += 2.0 * (k_ext - 1) / k_ext * out_b
            per_dev_flops = flops / max(1, ext * k_ext)
            fwd_flops_per_dev += per_dev_flops
        byts = sum(var_bytes(n) for n in op.input_names) + \
            sum(var_bytes(n) for n in op.output_names)
        report.op_costs.append(OpCost(
            loc=(0, op_idx, op.type), flops=per_dev_flops, bytes=byts,
            collective_bytes=coll))
        report.flops_total += flops
        report.flops_per_device += per_dev_flops
        report.hbm_bytes_per_device += byts
        report.collective_bytes += coll

    # reshard charges from the propagation conflict sites: the moved
    # tensor is the op's largest input
    for (bi, oi, typ, _note) in (prop.resharded if prop else []):
        if bi != 0 or oi >= len(gb.ops):
            continue
        op = gb.ops[oi]
        moved = max((var_bytes(n, per_device=False)
                     for n in op.input_names), default=0.0)
        report.reshard_bytes += moved

    if op_class_ratios:
        t = 0.0
        for c in report.op_costs:
            ratio = float(op_class_ratios.get(c.loc[2], 1.0))
            t += ratio * (c.flops / PEAK_FLOPS + c.bytes / HBM_GBPS) \
                + c.collective_bytes / ICI_GBPS
        t += report.reshard_bytes / ICI_GBPS
        report.calibrated_step_time_s = t

    report.peak_hbm_bytes_per_device = _peak_hbm(
        program, lookup, specs, mesh_axes, assume_batch)
    return report


def _peak_hbm(program, lookup, specs, mesh_axes, assume_batch) -> float:
    """Persistable state + activation liveness over the global block."""
    gb = program.global_block()
    persistable = {v.name for b in program.blocks
                   for v in b.vars.values() if v.persistable}

    def vb(name) -> float:
        info = lookup(name)
        if info is None or info.shape is None:
            return 0.0
        return (_numel(info.shape, assume_batch) * _itemsize(info)
                / max(1, spec_extent(specs.get(name), mesh_axes)))

    state_bytes = sum(vb(n) for n in persistable)

    backward_idx = next((i for i, op in enumerate(gb.ops)
                         if op.type == "backward"), None)
    last_use: Dict[str, int] = {}
    for i, op in enumerate(gb.ops):
        for n in op.input_names:
            last_use[n] = i
    produced_at: Dict[str, int] = {}
    for i, op in enumerate(gb.ops):
        for n in op.output_names:
            produced_at.setdefault(n, i)
    if backward_idx is not None:
        # XLA keeps forward activations alive for the VJP
        for n, born in produced_at.items():
            if born < backward_idx and n not in persistable:
                last_use[n] = max(last_use.get(n, born), backward_idx)

    live: Dict[str, float] = {}
    peak = 0.0
    for i, op in enumerate(gb.ops):
        for n in op.output_names:
            if n not in persistable and n not in live:
                live[n] = vb(n)
        peak = max(peak, sum(live.values()))
        dead = [n for n in live if last_use.get(n, i) <= i]
        for n in dead:
            del live[n]
    return state_bytes + peak
