"""Static concurrency verifier over the paddle_tpu host code (PT05x).

The framework is a fleet of cooperating threads — serving batchers and
dispatchers, reader pipeline workers, sparse prefetch/async-push workers,
the checkpoint writer, pserver selector loops, elastic heartbeat daemons —
and every recent concurrency bug (the push-seq lock-split race, the
cache-fill-vs-push race, the signal-handler lock deadlock) was found
post-hoc.  The reference framework dodged this class with a
single-threaded event loop per pserver; we chose threads, so this module
supplies the tooling: an AST pass over ``paddle_tpu/`` that builds a
per-class model of locks, conditions, queues and shared mutable
attributes, and emits frozen ``PT05x`` diagnostics
(:mod:`.diagnostics`) — the same static-pass treatment PR 4/PR 7 gave the
Program IR, aimed at our own host code.

Rules (one stable code each, severities pinned in ``diagnostics.CODES``):

========  ===========================================================
PT050     shared ``self.attr`` written both under a class lock and
          outside any lock (guard inconsistency); ``__init__`` writes
          are construction-time and exempt
PT051     static lock-acquisition-order cycle: ``with A: with B`` in
          one place and ``with B: with A`` in another (lock identity
          aggregates by *class attribute*, lockdep-style; one level of
          intra-class ``self.method()`` call expansion)
PT052     blocking call while holding a lock: socket
          send/recv/accept/connect, ``queue.get``/``put`` without a
          timeout, subprocess ``wait``/``communicate``, bare thread
          ``.join()``
PT053     ``Condition.wait`` outside a while-predicate loop (lost
          wakeup / spurious wakeup hazard); ``wait_for`` is exempt
PT054     lock/condition acquisition reachable from a registered
          signal handler (the PR 13 deadlock class: the interrupted
          thread may already hold the lock)
PT055     ``threading.Thread(...)`` without a ``name=`` that begins
          with a prefix registered in the frozen
          ``observability.metrics.THREAD_NAME_PREFIXES`` table
========  ===========================================================

The pass is import-free (pure ``ast`` over source text), so it also
covers flag-gated or lazily imported modules, exactly like the
``tests/test_repo_lint.py`` gates.  Current-tree findings that are
*accepted by design* live in :data:`BASELINE` — a frozen per-file,
per-code allowlist with a one-line justification each, tier-1-enforced
in both directions (new findings fail; stale entries must be ratcheted
out).  Surfaces: ``python -m paddle_tpu check --concurrency`` and the
``tests/test_repo_lint.py`` gate.  The runtime twin (an instrumented
lock that fails deterministically on an order cycle instead of
deadlocking) is :mod:`paddle_tpu.testing.lockwatch`.
"""
from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .diagnostics import CODES, Diagnostic, diag

__all__ = [
    "Finding", "BASELINE", "THREAD_FACTORY_NAMES",
    "LOCK_FACTORIES", "RLOCK_FACTORIES", "COND_FACTORIES",
    "QUEUE_FACTORIES", "EVENT_FACTORIES", "BLOCKING_SOCKET_METHODS",
    "BLOCKING_PROC_METHODS",
    "analyze_source", "analyze_package", "apply_baseline",
    "package_root", "thread_name_prefixes", "render_report",
]

# ---------------------------------------------------------------------------
# Pattern tables.  Every name here must resolve against the real stdlib
# object it models — tests/test_concurrency_analysis.py pins that (the
# dis/AST agreement check), so a typo cannot silently disable a rule.
# ---------------------------------------------------------------------------
#: factory callables whose result is a mutex (module.attr or bare name)
LOCK_FACTORIES = ("Lock", "make_lock")
RLOCK_FACTORIES = ("RLock", "make_rlock")
COND_FACTORIES = ("Condition", "make_condition")
QUEUE_FACTORIES = ("Queue", "LifoQueue", "PriorityQueue", "SimpleQueue")
EVENT_FACTORIES = ("Event",)
THREAD_FACTORY_NAMES = ("Thread",)

#: method names that block on a socket (PT052)
BLOCKING_SOCKET_METHODS = ("recv", "recv_into", "recvfrom", "accept",
                           "connect", "sendall")
#: method names that block on a child process (PT052); ``wait`` only
#: fires on process-like receivers (see ``_looks_like_process``) so it
#: cannot collide with Condition.wait (PT053's domain)
BLOCKING_PROC_METHODS = ("wait", "communicate")

#: receiver-name fragments that identify a process handle for the
#: ``wait``/``communicate`` rules
_PROC_NAME_HINTS = ("proc", "popen", "child")

#: methods exempt from __init__-style construction-time write analysis
_CONSTRUCTION_METHODS = ("__init__", "__new__", "__set_name__")

#: method-name suffix meaning "caller holds the class lock" — writes in
#: ``_foo_locked()`` count as guarded for PT050 (the repo-wide naming
#: convention; the analyzer trusts the name because it cannot see the
#: caller's critical section interprocedurally)
_LOCKED_SUFFIX = "_locked"


# ---------------------------------------------------------------------------
# Frozen baseline: (relpath, code) -> (count, one-line justification).
# The tier-1 gate (tests/test_repo_lint.py) enforces BOTH directions:
# findings above the count fail (fix them), and counts above the actual
# findings fail (ratchet the entry down).  Never add entries for new
# code — fix the finding instead.
# ---------------------------------------------------------------------------
BASELINE: Dict[Tuple[str, str], Tuple[int, str]] = {
    # lockwatch's _WatchedCondition.wait() is the wait PRIMITIVE itself:
    # it delegates to threading.Condition.wait, and the while-predicate
    # loop the rule demands lives (correctly) at its CALLERS, which the
    # pass checks separately.
    ("paddle_tpu/testing/lockwatch.py", "PT053"): (
        1, "condition-wrapper delegate: the predicate loop belongs to "
           "the caller, which the pass checks at each call site"),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One PT05x finding located in host source."""

    code: str
    path: str          # repo-relative, forward slashes
    line: int
    symbol: str        # lock/attr/thread symbol involved
    message: str

    def render(self) -> str:
        sev = CODES[self.code][0]
        return (f"{self.code} {sev} {self.path}:{self.line} "
                f"[{self.symbol}]: {self.message}")

    def to_diagnostic(self) -> Diagnostic:
        return diag(self.code, f"{self.path}:{self.line}: {self.message}",
                    var=self.symbol)


def package_root() -> str:
    """Absolute path of the paddle_tpu package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def thread_name_prefixes() -> Tuple[str, ...]:
    """Registered thread-name prefixes, parsed from the frozen
    ``THREAD_NAME_PREFIXES`` literal in observability/metrics.py WITHOUT
    importing it (same contract as the metric-name lint gate)."""
    path = os.path.join(package_root(), "observability", "metrics.py")
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "THREAD_NAME_PREFIXES"
                for t in node.targets):
            rows = ast.literal_eval(node.value)
            return tuple(prefix for prefix, _help in rows)
    raise AssertionError(
        "THREAD_NAME_PREFIXES literal not found in observability/"
        "metrics.py")


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------
def _call_tail(func: ast.expr) -> Optional[str]:
    """Terminal name of a call target: ``a.b.C(...)`` -> ``C``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _recv_tail(func: ast.expr) -> Optional[str]:
    """Terminal name of a method call's RECEIVER: ``a.b.m(...)`` -> ``b``."""
    if not isinstance(func, ast.Attribute):
        return None
    v = func.value
    if isinstance(v, ast.Attribute):
        return v.attr
    if isinstance(v, ast.Name):
        return v.id
    return None


def _factory_kind(value: ast.expr) -> Optional[str]:
    """'lock' | 'rlock' | 'cond' | 'queue' | 'event' for a factory call."""
    if not isinstance(value, ast.Call):
        return None
    tail = _call_tail(value.func)
    if tail in LOCK_FACTORIES:
        return "lock"
    if tail in RLOCK_FACTORIES:
        return "rlock"
    if tail in COND_FACTORIES:
        return "cond"
    if tail in QUEUE_FACTORIES:
        return "queue"
    if tail in EVENT_FACTORIES:
        return "event"
    return None


def _self_attr_target(node: ast.expr) -> Optional[str]:
    """``self.X`` -> ``X``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _looks_like_process(name: Optional[str]) -> bool:
    if not name:
        return False
    low = name.lower()
    return any(h in low for h in _PROC_NAME_HINTS)


def _looks_like_socket(name: Optional[str]) -> bool:
    if not name:
        return False
    low = name.lower()
    return "sock" in low or "conn" in low


# ---------------------------------------------------------------------------
# Per-module model
# ---------------------------------------------------------------------------
class _ClassModel:
    def __init__(self, name: str):
        self.name = name
        self.attr_kinds: Dict[str, str] = {}   # attr -> factory kind

    def attrs_of(self, *kinds: str) -> Set[str]:
        return {a for a, k in self.attr_kinds.items() if k in kinds}


class _ModuleModel:
    """Names resolved over one source file: class attribute kinds,
    module-level primitives, and string constants (for thread-name
    prefix resolution)."""

    def __init__(self, tree: ast.Module, path: str):
        self.path = path
        self.classes: Dict[str, _ClassModel] = {}
        self.module_kinds: Dict[str, str] = {}   # module var -> kind
        self.constants: Dict[str, str] = {}      # module var -> str value
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tname = node.targets[0].id
                kind = _factory_kind(node.value)
                if kind:
                    self.module_kinds[tname] = kind
                elif isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    self.constants[tname] = node.value.value
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                cm = _ClassModel(node.name)
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        targets = (sub.targets
                                   if isinstance(sub, ast.Assign)
                                   else [sub.target])
                        value = sub.value
                        kind = _factory_kind(value) if value else None
                        if not kind:
                            continue
                        for t in targets:
                            attr = _self_attr_target(t)
                            if attr:
                                cm.attr_kinds[attr] = kind
                self.classes[node.name] = cm
        # attr name -> kind, merged over all classes (for resolving
        # attribute access on non-self receivers, e.g. ``rt.cond``)
        self.attr_kind_index: Dict[str, str] = {}
        for cm in self.classes.values():
            for a, k in cm.attr_kinds.items():
                self.attr_kind_index.setdefault(a, k)

    def kind_of_expr(self, node: ast.expr) -> Optional[str]:
        """Resolve a lock-ish expression to its primitive kind."""
        if isinstance(node, ast.Name):
            return self.module_kinds.get(node.id)
        if isinstance(node, ast.Attribute):
            return self.attr_kind_index.get(node.attr)
        return None

    def token_of_expr(self, node: ast.expr,
                      cls: Optional[str]) -> Optional[str]:
        """Lockdep-style lock-class token for a lock expression.

        Instance locks aggregate by (owning class, attribute); module
        locks by (module, name).  ``None`` when the expression does not
        resolve to a known lock/condition."""
        if isinstance(node, ast.Name):
            if self.module_kinds.get(node.id) in ("lock", "rlock", "cond"):
                return f"{self.path}::{node.id}"
            return None
        if isinstance(node, ast.Attribute):
            attr = node.attr
            kind = self.attr_kind_index.get(attr)
            if kind not in ("lock", "rlock", "cond"):
                return None
            owner = None
            if _self_attr_target(node) is not None and cls is not None \
                    and attr in self.classes[cls].attr_kinds:
                owner = cls
            else:
                owners = [c.name for c in self.classes.values()
                          if c.attr_kinds.get(attr) in
                          ("lock", "rlock", "cond")]
                owner = owners[0] if owners else None
            if owner is None:
                return None
            return f"{self.path}::{owner}.{attr}"
        return None


# ---------------------------------------------------------------------------
# The analyzer
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class _Write:
    attr: str
    method: str
    line: int
    guarded: bool


class _FunctionWalker:
    """Walk one function/method body tracking the lexically-held lock
    set, the enclosing-loop flag, and locally-created primitives."""

    def __init__(self, analyzer: "_Analyzer", mm: _ModuleModel,
                 cls: Optional[str], func_name: str):
        self.an = analyzer
        self.mm = mm
        self.cls = cls
        self.func = func_name
        self.local_kinds: Dict[str, str] = {}   # local var -> kind
        self.writes: List[_Write] = []
        self.acquired: Set[str] = set()         # tokens (for call expand)
        self.thread_calls: List[ast.Call] = []

    # -- resolution -------------------------------------------------------
    def _kind_of(self, node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Name) and node.id in self.local_kinds:
            return self.local_kinds[node.id]
        return self.mm.kind_of_expr(node)

    def _class_lock_held(self, held: Tuple[str, ...]) -> bool:
        if self.cls is None:
            return False
        want = f"::{self.cls}."
        return any(want in t for t in held)

    # -- entry ------------------------------------------------------------
    def walk(self, body: Sequence[ast.stmt]):
        self._visit_block(body, held=(), inloop=False)

    # -- statement dispatch ----------------------------------------------
    def _visit_block(self, body, held, inloop):
        for stmt in body:
            self._visit_stmt(stmt, held, inloop)

    def _visit_stmt(self, stmt, held, inloop):
        if isinstance(stmt, ast.With):
            new = list(held)
            for item in stmt.items:
                tok = self.mm.token_of_expr(item.context_expr, self.cls)
                if tok is not None and tok not in new:
                    self.an.note_acquire(self.mm.path, stmt.lineno,
                                         tuple(new), tok)
                    self.acquired.add(tok)
                    new.append(tok)
                else:
                    self._visit_expr(item.context_expr, tuple(held), inloop)
            self._visit_block(stmt.body, tuple(new), inloop)
            return
        if isinstance(stmt, ast.While):
            self._visit_expr(stmt.test, held, inloop)
            self._visit_block(stmt.body, held, inloop=True)
            self._visit_block(stmt.orelse, held, inloop)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.iter, held, inloop)
            self._visit_block(stmt.body, held, inloop=True)
            self._visit_block(stmt.orelse, held, inloop)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, on its own thread/stack: fresh
            # held set, but it shares the module model and local kinds
            inner = _FunctionWalker(self.an, self.mm, self.cls,
                                    f"{self.func}.{stmt.name}")
            inner.local_kinds.update(self.local_kinds)
            inner.walk(stmt.body)
            self.writes.extend(inner.writes)
            self.acquired |= inner.acquired
            self.thread_calls.extend(inner.thread_calls)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            value = stmt.value
            if value is not None:
                self._visit_expr(value, held, inloop)
                kind = _factory_kind(value)
                if kind:
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self.local_kinds[t.id] = kind
                # track process handles: p = subprocess.Popen(...)
                if isinstance(value, ast.Call) \
                        and _call_tail(value.func) == "Popen":
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self.local_kinds[t.id] = "process"
            for t in targets:
                attr = _self_attr_target(t)
                if attr is not None:
                    self.writes.append(_Write(
                        attr=attr, method=self.func, line=stmt.lineno,
                        guarded=self._class_lock_held(held)))
                else:
                    self._visit_expr(t, held, inloop)
            return
        if isinstance(stmt, (ast.If,)):
            self._visit_expr(stmt.test, held, inloop)
            self._visit_block(stmt.body, held, inloop)
            self._visit_block(stmt.orelse, held, inloop)
            return
        if isinstance(stmt, ast.Try):
            self._visit_block(stmt.body, held, inloop)
            for h in stmt.handlers:
                self._visit_block(h.body, held, inloop)
            self._visit_block(stmt.orelse, held, inloop)
            self._visit_block(stmt.finalbody, held, inloop)
            return
        if isinstance(stmt, (ast.Return, ast.Expr, ast.Raise,
                             ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._visit_expr(child, held, inloop)
            return
        # anything else: walk expressions conservatively
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._visit_expr(child, held, inloop)
            elif isinstance(child, ast.stmt):
                self._visit_stmt(child, held, inloop)

    # -- expressions ------------------------------------------------------
    def _visit_expr(self, node, held, inloop):
        for call in [n for n in ast.walk(node)
                     if isinstance(n, ast.Call)]:
            self._visit_call(call, held, inloop)

    def _visit_call(self, call: ast.Call, held, inloop):
        tail = _call_tail(call.func)
        if tail in THREAD_FACTORY_NAMES:
            self.thread_calls.append(call)
        recv = _recv_tail(call.func)
        recv_kind = None
        if isinstance(call.func, ast.Attribute):
            recv_kind = self._kind_of(call.func.value)

        # PT053: Condition.wait must sit in a while-predicate loop
        if tail == "wait" and recv_kind == "cond":
            if not inloop:
                self.an.add(Finding(
                    "PT053", self.mm.path, call.lineno,
                    symbol=recv or "?",
                    message=f"Condition.wait on {recv!r} outside a "
                            f"while-predicate loop in {self.func}() — a "
                            f"spurious or stolen wakeup proceeds on a "
                            f"false predicate; re-test the condition in "
                            f"a while loop (or use wait_for)"))
            return

        # the interprocedural PT051 edge: self.method() under a lock
        if held and isinstance(call.func, ast.Attribute) \
                and _self_attr_target(call.func) is not None \
                and self.cls is not None:
            self.an.note_self_call(self.mm.path, call.lineno, held,
                                   self.cls, call.func.attr)

        # PT052: blocking calls under a lock
        if not held:
            return
        if tail in BLOCKING_SOCKET_METHODS or (
                tail == "send" and _looks_like_socket(recv)):
            self.an.add(Finding(
                "PT052", self.mm.path, call.lineno, symbol=tail,
                message=f"socket .{tail}() while holding "
                        f"{_short(held[-1])} in {self.func}() — a slow "
                        f"or dead peer stalls every thread contending "
                        f"for the lock"))
            return
        if tail in ("get", "put") and recv_kind == "queue":
            if _kwarg(call, "timeout") is not None:
                return
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and call.args[0].value is False:
                return          # .get(False) / .put(x) never blocks
            if tail == "put" and len(call.args) >= 2 \
                    and isinstance(call.args[1], ast.Constant) \
                    and call.args[1].value is False:
                return
            self.an.add(Finding(
                "PT052", self.mm.path, call.lineno, symbol=tail,
                message=f"queue .{tail}() without a timeout while "
                        f"holding {_short(held[-1])} in {self.func}() — "
                        f"backpressure (or an empty queue) parks the "
                        f"lock holder indefinitely"))
            return
        if tail in BLOCKING_PROC_METHODS and (
                recv_kind == "process" or _looks_like_process(recv)):
            if tail == "wait" and (_kwarg(call, "timeout") is not None
                                   or call.args):
                return
            self.an.add(Finding(
                "PT052", self.mm.path, call.lineno, symbol=tail,
                message=f"subprocess .{tail}() while holding "
                        f"{_short(held[-1])} in {self.func}() — child "
                        f"exit time is unbounded"))
            return
        if tail == "join" and not call.args \
                and _kwarg(call, "timeout") is None \
                and recv_kind not in ("queue",):
            # str.join always takes a positional; queue.join is also
            # unbounded but queues are drained by workers we control
            self.an.add(Finding(
                "PT052", self.mm.path, call.lineno, symbol="join",
                message=f"bare .join() while holding {_short(held[-1])} "
                        f"in {self.func}() — if the joined thread needs "
                        f"this lock, this is a deadlock"))


def _short(token: str) -> str:
    return token.split("::", 1)[-1]


class _Analyzer:
    def __init__(self, thread_prefixes: Sequence[str]):
        self.prefixes = tuple(thread_prefixes)
        self.findings: List[Finding] = []
        # token -> token -> first (path, line) seeing that edge
        self.edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
        # (path, class, method) -> acquired tokens (for call expansion)
        self.method_acquires: Dict[Tuple[str, str, str], Set[str]] = {}
        # deferred interprocedural edges: (path, line, held, cls, method)
        self.self_calls: List[Tuple[str, int, Tuple[str, ...], str,
                                    str]] = []

    def add(self, f: Finding):
        self.findings.append(f)

    # -- PT051 graph ------------------------------------------------------
    def note_acquire(self, path: str, line: int,
                     held: Tuple[str, ...], new: str):
        for h in held:
            if h != new:
                self.edges.setdefault(h, {}).setdefault(new, (path, line))

    def note_self_call(self, path: str, line: int, held: Tuple[str, ...],
                       cls: str, method: str):
        self.self_calls.append((path, line, held, cls, method))

    def expand_self_calls(self):
        for path, line, held, cls, method in self.self_calls:
            acq = self.method_acquires.get((path, cls, method), set())
            for tok in acq:
                self.note_acquire(path, line, held, tok)

    def order_cycles(self) -> List[List[str]]:
        """Elementary cycles via SCC decomposition (each SCC with more
        than one node is reported once, as a representative path)."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v):
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            for w in self.edges.get(v, {}):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

        for v in sorted(set(self.edges)
                        | {w for d in self.edges.values() for w in d}):
            if v not in index:
                strongconnect(v)
        return sccs

    def emit_cycles(self):
        for comp in self.order_cycles():
            a = comp[0]
            b = next(w for w in self.edges.get(a, {}) if w in comp)
            path, line = self.edges[a][b]
            names = " -> ".join(_short(t) for t in comp + [comp[0]])
            self.add(Finding(
                "PT051", path, line, symbol=_short(a),
                message=f"lock-acquisition-order cycle: {names} — two "
                        f"threads taking these locks in opposite order "
                        f"deadlock; pick one global order (or split the "
                        f"critical sections)"))


def _iter_defs(tree: ast.Module):
    """(class_name_or_None, FunctionDef) for every top-level function and
    every method of every class (nested defs are handled by the
    walker)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    yield node.name, sub


def _resolve_thread_name(call: ast.Call, mm: _ModuleModel,
                         local_consts: Dict[str, str]) -> Tuple[str, bool]:
    """(static name prefix, resolvable) for a Thread(...) call."""
    name = _kwarg(call, "name")
    if name is None:
        return "", False
    if isinstance(name, ast.Constant) and isinstance(name.value, str):
        return name.value, True
    if isinstance(name, ast.JoinedStr) and name.values:
        first = name.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value,
                                                          str):
            return first.value, True
        if isinstance(first, ast.FormattedValue) \
                and isinstance(first.value, ast.Name):
            v = mm.constants.get(first.value.id,
                                 local_consts.get(first.value.id))
            if v is not None:
                return v, True
    if isinstance(name, ast.Name):
        v = mm.constants.get(name.id, local_consts.get(name.id))
        if v is not None:
            return v, True
    return "", False


def _check_threads(an: _Analyzer, mm: _ModuleModel,
                   walkers: List[_FunctionWalker]):
    for w in walkers:
        for call in w.thread_calls:
            name, ok = _resolve_thread_name(call, mm, {})
            if not ok:
                kw = _kwarg(call, "name")
                why = ("has no name= argument" if kw is None else
                       "name is not statically resolvable to a literal "
                       "prefix")
                an.add(Finding(
                    "PT055", mm.path, call.lineno, symbol="Thread",
                    message=f"framework thread in {w.func}() {why} — "
                            f"name it with a prefix frozen in "
                            f"observability.metrics.THREAD_NAME_PREFIXES "
                            f"so the conftest leak fixture and operators "
                            f"can attribute it"))
                continue
            if not any(name == p or name.startswith(p + "-")
                       or name.startswith(p) for p in an.prefixes):
                an.add(Finding(
                    "PT055", mm.path, call.lineno, symbol=name,
                    message=f"thread name {name!r} does not begin with "
                            f"a prefix registered in observability."
                            f"metrics.THREAD_NAME_PREFIXES"))


def _check_pt050(an: _Analyzer, mm: _ModuleModel,
                 per_class: Dict[str, List[_Write]]):
    for cls, writes in per_class.items():
        cm = mm.classes.get(cls)
        if cm is None:
            continue
        lockish = cm.attrs_of("lock", "rlock", "cond", "event", "queue")
        by_attr: Dict[str, List[_Write]] = {}
        for wr in writes:
            if wr.attr in lockish:
                continue
            by_attr.setdefault(wr.attr, []).append(wr)
        for attr, ws in sorted(by_attr.items()):
            guarded = [w for w in ws if w.guarded]
            naked = [w for w in ws if not w.guarded
                     and w.method.split(".")[0]
                     not in _CONSTRUCTION_METHODS
                     and not w.method.split(".")[0]
                     .endswith(_LOCKED_SUFFIX)]
            if guarded and naked:
                g = guarded[0]
                n = naked[0]
                an.add(Finding(
                    "PT050", mm.path, n.line, symbol=f"{cls}.{attr}",
                    message=f"self.{attr} is written under a class lock "
                            f"in {g.method}() (line {g.line}) but "
                            f"without any lock in {n.method}() — either "
                            f"every write takes the lock or the guard "
                            f"is theater"))


def _handler_targets(call: ast.Call) -> List[ast.expr]:
    """Handler expressions from signal.signal(sig, handler) calls."""
    if _call_tail(call.func) != "signal":
        return []
    # skip signal.signal(sig, old)-style RESTORES of a saved handler:
    # restoring a variable is not registering framework code
    if len(call.args) >= 2:
        return [call.args[1]]
    return []


def _check_pt054(an: _Analyzer, mm: _ModuleModel, tree: ast.Module,
                 acquires_by_method: Dict[Tuple[str, str], Set[str]],
                 acquires_by_func: Dict[str, Set[str]]):
    """Lock acquisition reachable from a registered signal handler."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        for handler in _handler_targets(node):
            toks: Set[str] = set()
            hname = "?"
            if isinstance(handler, ast.Lambda):
                hname = "<lambda>"
                w = _FunctionWalker(_Analyzer(()), mm, None, hname)
                w._visit_expr(handler.body, (), False)
                toks |= w.acquired
                for sub in ast.walk(handler.body):
                    if isinstance(sub, ast.With):
                        for item in sub.items:
                            t = mm.token_of_expr(item.context_expr, None)
                            if t:
                                toks.add(t)
                    if isinstance(sub, ast.Call) \
                            and _call_tail(sub.func) == "acquire":
                        rt = _recv_tail(sub.func)
                        if rt and mm.attr_kind_index.get(rt) in (
                                "lock", "rlock", "cond"):
                            toks.add(rt)
            elif isinstance(handler, ast.Name):
                hname = handler.id
                toks |= acquires_by_func.get(handler.id, set())
            elif isinstance(handler, ast.Attribute):
                hname = handler.attr
                for (cls, meth), acq in acquires_by_method.items():
                    if meth == handler.attr:
                        toks |= acq
            if toks:
                tok = sorted(toks)[0]
                an.add(Finding(
                    "PT054", mm.path, node.lineno, symbol=hname,
                    message=f"signal handler {hname!r} acquires "
                            f"{_short(str(tok))} — the interrupted "
                            f"thread may already hold it (the PR 13 "
                            f"deadlock class); set a flag/Event in the "
                            f"handler and do the work on a normal "
                            f"thread"))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def analyze_source(src: str, path: str, *,
                   thread_prefixes: Optional[Sequence[str]] = None,
                   _an: Optional[_Analyzer] = None) -> List[Finding]:
    """Run every PT05x rule over one source file."""
    prefixes = (tuple(thread_prefixes) if thread_prefixes is not None
                else thread_name_prefixes())
    an = _an if _an is not None else _Analyzer(prefixes)
    tree = ast.parse(src, filename=path)
    mm = _ModuleModel(tree, path)

    walkers: List[_FunctionWalker] = []
    per_class_writes: Dict[str, List[_Write]] = {}
    acquires_by_method: Dict[Tuple[str, str], Set[str]] = {}
    acquires_by_func: Dict[str, Set[str]] = {}
    for cls, fn in _iter_defs(tree):
        w = _FunctionWalker(an, mm, cls, fn.name)
        w.walk(fn.body)
        walkers.append(w)
        an.method_acquires[(path, cls or "", fn.name)] = set(w.acquired)
        if cls is not None:
            per_class_writes.setdefault(cls, []).extend(w.writes)
            acquires_by_method.setdefault((cls, fn.name),
                                          set()).update(w.acquired)
        else:
            acquires_by_func.setdefault(fn.name, set()).update(w.acquired)

    _check_threads(an, mm, walkers)
    _check_pt050(an, mm, per_class_writes)
    _check_pt054(an, mm, tree, acquires_by_method, acquires_by_func)

    if _an is None:          # single-file mode: close the graph locally
        an.expand_self_calls()
        an.emit_cycles()
        return sorted(an.findings, key=lambda f: (f.path, f.line, f.code))
    return an.findings


def analyze_package(root: Optional[str] = None, *,
                    thread_prefixes: Optional[Sequence[str]] = None
                    ) -> List[Finding]:
    """Run the pass over every ``paddle_tpu/**.py`` source file."""
    root = root or package_root()
    prefixes = (tuple(thread_prefixes) if thread_prefixes is not None
                else thread_name_prefixes())
    an = _Analyzer(prefixes)
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            full = os.path.join(dirpath, f)
            rel = os.path.relpath(
                full, os.path.join(root, os.pardir)).replace(os.sep, "/")
            with open(full) as fh:
                analyze_source(fh.read(), rel,
                               thread_prefixes=prefixes, _an=an)
    an.expand_self_calls()
    an.emit_cycles()
    return sorted(an.findings, key=lambda f: (f.path, f.line, f.code))


def apply_baseline(findings: Sequence[Finding],
                   baseline: Optional[Dict] = None):
    """Split findings against the frozen baseline.

    Returns ``(new, suppressed, stale)``: findings beyond each
    (path, code) budget; the count suppressed per baselined key; and
    baseline keys whose budget exceeds today's findings (must be
    ratcheted down)."""
    baseline = BASELINE if baseline is None else baseline
    by_key: Dict[Tuple[str, str], List[Finding]] = {}
    for f in findings:
        by_key.setdefault((f.path, f.code), []).append(f)
    new: List[Finding] = []
    suppressed: Dict[Tuple[str, str], int] = {}
    for key, fs in sorted(by_key.items()):
        allowed = baseline.get(key, (0, ""))[0]
        if allowed:
            suppressed[key] = min(allowed, len(fs))
        if len(fs) > allowed:
            new.extend(fs[allowed:])
    stale = sorted(key for key, (allowed, _why) in baseline.items()
                   if len(by_key.get(key, [])) < allowed)
    return new, suppressed, stale


def render_report(findings: Sequence[Finding],
                  baseline: Optional[Dict] = None) -> str:
    """Human-readable report with the baseline applied."""
    new, suppressed, stale = apply_baseline(findings, baseline)
    lines = [f"concurrency verifier: {len(findings)} finding(s), "
             f"{sum(suppressed.values())} baselined, {len(new)} new"]
    lines += [f"  {f.render()}" for f in new]
    for (path, code), n in sorted(suppressed.items()):
        why = (BASELINE if baseline is None else baseline)[(path,
                                                            code)][1]
        lines.append(f"  baselined {code} x{n} in {path}: {why}")
    for key in stale:
        lines.append(f"  STALE baseline entry {key}: fewer findings "
                     f"remain than budgeted — ratchet it down")
    return "\n".join(lines)
