"""Well-formedness pass over the Program IR.

The reference enforces graph well-formedness structurally: OpDesc
construction cross-checks the OpInfoMap proto (op_desc.cc, op_registry.h
148-290), block/var lookups hard-fail (block_desc.h), and grad-var pairing
is guaranteed by the GradOpDescMaker machinery (backward.cc:353-415).
paddle_tpu's Python-native IR has none of those guard rails, so this pass
recovers them as explicit checks:

* **PT001** an op input names a variable that is declared nowhere and
  produced by nothing — a dangling reference (typo, or a dropped var).
* **PT002** the input is declared but *no op ever produces it* and it is
  not feedable (``is_data``) or persistable (startup-initialized) — the
  producing op was dropped.
* **PT007** the only producers run *after* the consumer (def-after-use;
  two such edges form a dependency cycle — the reference's topological
  OpDesc order makes this unrepresentable, our op list does not).
* **PT003** (warning) an op writes a variable that is not declared in any
  visible block — executes fine (the trace env auto-binds) but the IR no
  longer round-trips through ``Program.to_dict``.
* **PT004** (warning) two ops write the same variable and the later one
  does not read it — a rebind that silently shadows the earlier value
  (in-place update chains, which *do* read the var, are exempt).
* **PT005** the op type has no registered lowering (``core.registry``).
* **PT006** an orphaned ``@GRAD``/``@LEN`` companion: a gradient var read
  without any ``backward`` op producing it, or a length companion whose
  base var is missing or not a sequence (``lod_level == 0``).

Sub-blocks (while/rnn/beam bodies) are checked leniently — their inputs
may be bound by the parent op's lowering convention (loop carries, step
slices), which the verifier recognizes by collecting every variable name
reachable from the parent op's slots and string-valued attrs.
"""
from __future__ import annotations

from typing import Dict, List, Set

from ..core.program import (GRAD_SUFFIX, LEN2_SUFFIX, LEN_SUFFIX,
                            _sub_block_indices)
from ..core.registry import has_op
from .diagnostics import ValidationReport, diag

#: op types whose execution is a side effect (kept live by the dead-code
#: lint, and legitimate without consumers here)
SIDE_EFFECT_OPS = frozenset({
    "print", "assert", "save", "load", "feed", "fetch",
})

#: ops that target an EXISTING output var on purpose (they forward
#: metadata like @LEN companions rather than rebinding the value) —
#: exempt from the duplicate-writer check
_METADATA_OPS = frozenset({"copy_len"})


def _companion_base(name: str):
    """(base, kind) for ``X@GRAD`` / ``X@LEN`` / ``X@LEN2`` names."""
    for suffix in (LEN2_SUFFIX, LEN_SUFFIX, GRAD_SUFFIX):
        if name.endswith(suffix):
            return name[:-len(suffix)], suffix
    return None, None


def _attr_names(op) -> Set[str]:
    """Every string (or list-of-strings) attr value of ``op`` — the
    superset of the per-op sub-block binding conventions (token_name,
    step_inputs, mem_step_names, ...)."""
    out: Set[str] = set()
    for v in op.attrs.values():
        if isinstance(v, str):
            out.add(v)
        elif isinstance(v, (list, tuple)):
            out.update(x for x in v if isinstance(x, str))
    return out


def _initially_defined(program) -> Set[str]:
    """Names available before any op runs: feeds (plus their sequence
    companions) and persistable state the startup program owns."""
    defined: Set[str] = set()
    for b in program.blocks:
        for v in b.vars.values():
            if v.is_data:
                defined.add(v.name)
                if v.lod_level >= 1:
                    defined.add(v.name + LEN_SUFFIX)
                if v.lod_level >= 2:
                    defined.add(v.name + LEN2_SUFFIX)
            elif v.persistable:
                defined.add(v.name)
    return defined


def run_verifier(program, report: ValidationReport):
    """Append PT001-PT007 findings for ``program`` to ``report``."""
    # sub-block idx -> names bound by the referencing parent op
    sub_binders: Dict[int, Set[str]] = {}
    for b in program.blocks:
        for op in b.ops:
            for idx in _sub_block_indices(op):
                binds = sub_binders.setdefault(idx, set())
                binds.update(op.input_names)
                binds.update(op.output_names)
                binds.update(_attr_names(op))

    produced_anywhere: Set[str] = set()
    for b in program.blocks:
        for op in b.ops:
            produced_anywhere.update(op.output_names)
            for n in op.output_names:
                # sequence/length companions emitted via ctx.set_len
                produced_anywhere.add(n + LEN_SUFFIX)
                produced_anywhere.add(n + LEN2_SUFFIX)

    base_defined = _initially_defined(program)

    for block in program.blocks:
        _check_declared_companions(block, report)
        if block.idx == 0:
            _check_block_strict(program, block, base_defined, report)
        else:
            _check_block_lenient(program, block, base_defined,
                                 sub_binders.get(block.idx, set()),
                                 produced_anywhere, report)


def _producers(block) -> Dict[str, List[int]]:
    """var name -> indices of ops that CREATE it (in-place updates — the
    op also reads the name — do not count as creation)."""
    prods: Dict[str, List[int]] = {}
    for i, op in enumerate(block.ops):
        in_names = set(op.input_names)
        for n in op.output_names:
            if n not in in_names:
                prods.setdefault(n, []).append(i)
    return prods


def _check_block_strict(program, block, base_defined: Set[str],
                        report: ValidationReport):
    defined = set(base_defined)
    prods = _producers(block)
    writers_seen: Dict[str, int] = {}

    for idx, op in enumerate(block.ops):
        loc = (block.idx, idx, op.type)
        if not has_op(op.type):
            report.add(diag("PT005",
                            f"op type {op.type!r} has no registered "
                            f"lowering", op=loc))
        in_names = set(op.input_names)
        for name in op.input_names:
            if name in defined:
                continue
            base, kind = _companion_base(name)
            if kind in (LEN_SUFFIX, LEN2_SUFFIX):
                v = block._find_var_recursive(base)
                if v is None or v.lod_level == 0:
                    report.add(diag(
                        "PT006",
                        f"length companion {name!r} has no sequence base "
                        f"var ({base!r} "
                        f"{'missing' if v is None else 'is not lod>0'})",
                        op=loc, var=name))
                continue
            later = [i for i in prods.get(name, []) if i >= idx]
            if kind == GRAD_SUFFIX and not prods.get(name):
                report.add(diag(
                    "PT006",
                    f"gradient var {name!r} is consumed but no backward "
                    f"op produces it (orphaned @GRAD — was "
                    f"append_backward dropped?)", op=loc, var=name))
            elif later:
                report.add(diag(
                    "PT007",
                    f"op reads {name!r} produced only by later op(s) "
                    f"{later} — def-after-use (dependency cycle when "
                    f"mutual)", op=loc, var=name))
            elif block._find_var_recursive(name) is not None:
                report.add(diag(
                    "PT002",
                    f"var {name!r} is declared but never produced by any "
                    f"op, fed, or initialized", op=loc, var=name))
            else:
                report.add(diag(
                    "PT001",
                    f"op input names undeclared var {name!r} with no "
                    f"producer (dangling reference)", op=loc, var=name))

        has_sub = bool(_sub_block_indices(op))
        for name in op.output_names:
            if block._find_var_recursive(name) is None:
                report.add(diag(
                    "PT003",
                    f"op writes var {name!r} that no block declares",
                    op=loc, var=name))
            if not has_sub and name not in in_names and \
                    op.type not in _METADATA_OPS:
                prev = writers_seen.get(name)
                if prev is not None:
                    report.add(diag(
                        "PT004",
                        f"var {name!r} already written by op #{prev}; "
                        f"this op rebinds it without reading it",
                        op=loc, var=name))
                writers_seen[name] = idx
            defined.add(name)
            defined.add(name + LEN_SUFFIX)
            defined.add(name + LEN2_SUFFIX)


def _check_block_lenient(program, block, base_defined: Set[str],
                         binders: Set[str], produced_anywhere: Set[str],
                         report: ValidationReport):
    """Sub-block pass: parent lowerings bind loop carries/step slices, so
    only fully-dangling references (PT001) and unregistered ops (PT005)
    are decidable."""
    for idx, op in enumerate(block.ops):
        loc = (block.idx, idx, op.type)
        if not has_op(op.type):
            report.add(diag("PT005",
                            f"op type {op.type!r} has no registered "
                            f"lowering", op=loc))
        for name in op.input_names:
            if name in base_defined or name in binders or \
                    name in produced_anywhere:
                continue
            base, kind = _companion_base(name)
            if kind is not None and (base in base_defined or
                                     base in binders or
                                     base in produced_anywhere):
                continue
            if block._find_var_recursive(name) is None:
                report.add(diag(
                    "PT001",
                    f"op input names undeclared var {name!r} with no "
                    f"producer (dangling reference)", op=loc, var=name))


def _check_declared_companions(block, report: ValidationReport):
    """Declared ``X@GRAD``/``X@LEN`` vars must have a live base var (the
    @LEN base must be a sequence)."""
    for name, v in block.vars.items():
        base, kind = _companion_base(name)
        if kind is None:
            continue
        bv = block._find_var_recursive(base)
        if bv is None:
            report.add(diag(
                "PT006",
                f"declared companion {name!r} has no base var {base!r}",
                var=name))
        elif kind in (LEN_SUFFIX, LEN2_SUFFIX) and bv.lod_level == 0:
            report.add(diag(
                "PT006",
                f"declared length companion {name!r}: base {base!r} is "
                f"not a sequence (lod_level=0)", var=name))
