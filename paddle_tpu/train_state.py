"""Versioned training-loop state for full-fidelity checkpoint/resume.

A model checkpoint (scope vars: parameters, optimizer moments, evaluator
states — ``distributed.checkpoint.CheckpointManager``) is not enough to
*resume* a run bit-identically: the loop's own counters decide which
batch comes next and which PRNG keys every random op derives
(``Executor`` folds the step counter into the program seed, so the step
counter IS the RNG derivation state).  :class:`TrainState` captures that
remainder — step/pass/batch counters, the periodic-report cursor, an
optimizer-config fingerprint — and rides INSIDE the checkpoint as a
synthetic uint8 var (:data:`TRAIN_STATE_VAR`), so it shares the manager's
atomic tmp+rename commit, per-file md5 verification and corrupt-fallback
for free: a checkpoint either has a consistent (vars, TrainState) pair or
it is skipped entirely.

:class:`Checkpointer` is the trainer-side coordinator: periodic saves at
**dispatch boundaries** (the only points where the scope provably
reflects exactly the batches emitted so far — a K-step scan updates the
scope once per chunk), SIGTERM/SIGINT preemption handling (finish the
in-flight dispatch, commit an emergency checkpoint, exit
:data:`~paddle_tpu.faults.EXIT_PREEMPTED`), and restore-with-fallback on
resume.  The reference analog is the pserver checkpoint + etcd task
snapshot pair (go/pserver/service.go:120-227, go/master/service.go:207).
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import threading
from typing import Optional

import numpy as np

from .core.scope import Scope
from .distributed.checkpoint import (DEFAULT_CHUNK_BYTES,
                                     CheckpointManager, DeltaChainError)
from .faults import EXIT_PREEMPTED, Preempted  # noqa: F401  (re-export)
from .observability import emit_event, inc_counter

logger = logging.getLogger("paddle_tpu")

__all__ = ["TRAIN_STATE_VERSION", "TRAIN_STATE_VAR", "TrainState",
           "Checkpointer", "DeltaPolicy"]

TRAIN_STATE_VERSION = 1
# the synthetic scope var the loop state rides in (never a program var,
# so it can never thread into a compiled step)
TRAIN_STATE_VAR = "__train_state__"


@dataclasses.dataclass
class TrainState:
    """Everything the training loop needs beyond the scope vars to
    continue as if never interrupted.

    ``exe_step`` is ``Executor._step`` at the boundary — restoring it
    restores the per-step RNG stream exactly (keys derive from
    (program.random_seed, step)).  ``pass_id``/``batch_id`` name the NEXT
    batch to process; ``emitted`` counts batches completed across passes
    (the global step the checkpoint is labeled with); ``iters_done`` is
    the log_period cursor.  ``optimizer`` is a config fingerprint checked
    on resume (the optimizer's *moments* are scope vars and travel in the
    checkpoint proper)."""

    version: int = TRAIN_STATE_VERSION
    exe_step: int = 0
    pass_id: int = 0
    batch_id: int = 0
    emitted: int = 0
    iters_done: int = 0
    random_seed: int = 0
    optimizer: dict = dataclasses.field(default_factory=dict)
    emergency: bool = False
    # Master.state_dict() captured at the same boundary — commits
    # ATOMICALLY with the model (None when no master rides along)
    master: Optional[dict] = None
    # elastic-service position (distributed/elastic.py): slot, committed
    # task cursor + within-task batch offset, world size and the resize
    # epoch of the membership generation this state belongs to — the
    # durable half of a resize-boundary record (None outside elastic
    # runs; an optional field, so version stays 1 and old checkpoints
    # load unchanged)
    elastic: Optional[dict] = None

    def to_array(self) -> np.ndarray:
        payload = json.dumps(dataclasses.asdict(self), sort_keys=True)
        return np.frombuffer(payload.encode("utf-8"), dtype=np.uint8)

    @classmethod
    def from_array(cls, arr) -> "TrainState":
        d = json.loads(bytes(np.asarray(arr, dtype=np.uint8)).decode(
            "utf-8"))
        version = int(d.get("version", 0))
        if version > TRAIN_STATE_VERSION:
            raise ValueError(
                f"checkpoint TrainState version {version} is newer than "
                f"this runtime supports ({TRAIN_STATE_VERSION})")
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class DeltaPolicy:
    """When and how the Checkpointer commits incremental checkpoints.

    A delta commit writes only what changed since the previous commit
    (sparse dirty rows, dense chunk patches) and chains to it by content
    hash; restore replays the chain, so commit cost scales with the
    task's touched set, not model size.  Two thresholds force a full
    rebase (which re-anchors restore cost and lets retention free the
    old chain): ``max_chain`` — chain length a restore may have to
    replay — and ``rebase_fraction`` — cumulative delta bytes as a
    fraction of the last base's size (past it, deltas stop paying for
    themselves).  Deltas are single-process; multi-host runs silently
    keep full saves.  ``enabled=False`` restores the pre-delta behavior
    everywhere."""

    enabled: bool = True
    max_chain: int = 8
    rebase_fraction: float = 0.5
    chunk_bytes: int = DEFAULT_CHUNK_BYTES


class Checkpointer:
    """Trainer-side checkpoint/preemption coordinator (one per
    ``train(checkpoint_dir=...)`` call).

    The trainer reports every completed batch through
    :meth:`on_batch_done`; the coordinator detects dispatch boundaries by
    comparing the executor's step counter against batches emitted, takes
    periodic saves every ``save_every_n_steps`` completed batches, and —
    when a SIGTERM/SIGINT arrived — commits a blocking emergency
    checkpoint and raises :class:`~paddle_tpu.faults.Preempted`.
    """

    def __init__(self, checkpoint_dir: str, exe,
                 save_every_n_steps: Optional[int] = None,
                 master=None, max_to_keep: int = 3,
                 handle_signals: bool = True, extra_state=None,
                 state_vars=None, delta_source=None,
                 delta: Optional[DeltaPolicy] = None):
        if save_every_n_steps is not None and save_every_n_steps < 1:
            raise ValueError(f"save_every_n_steps must be >= 1, got "
                             f"{save_every_n_steps}")
        self.dir = checkpoint_dir
        self.exe = exe
        self.save_every = save_every_n_steps
        self.master = master
        self.delta = DeltaPolicy() if delta is None else delta
        self.manager = CheckpointManager(checkpoint_dir,
                                         max_to_keep=max_to_keep,
                                         chunk_bytes=self.delta.chunk_bytes)
        # delta_source: the sparse session's incremental-export surface
        # (export_delta/export_full returning (tokens, state);
        # commit_delta acks AFTER the durable write, retract_delta
        # re-dirties on writer failure).  When present it supersedes
        # ``state_vars`` — the token protocol snapshots the dirty set
        # atomically WITH the export, so rows pushed while the async
        # writer is serializing are never marked clean (they land in the
        # next delta).
        self._delta_source = delta_source if (
            delta_source is not None
            and getattr(delta_source, "supports_delta", False)) else None
        self.handle_signals = handle_signals
        # extra_state(): JSON-serializable dict captured at every save
        # into TrainState.elastic — the elastic worker's stream position
        # (cursor/offset), read back on resume.  Called AT the boundary,
        # so it sees the exact committed position.
        self._extra_state = extra_state
        # state_vars(): {name: np.ndarray} of ARRAY-valued rider state
        # captured at every save and committed as synthetic scope vars
        # (the TRAIN_STATE_VAR pattern, for state too big for JSON) —
        # the sparse parameter server's table rows ride here.  The
        # callable must return fresh copies: the async writer may still
        # be serializing them after this method returns.
        self._state_vars = state_vars
        self._old_handlers: dict = {}
        self._preempt_sig: Optional[int] = None
        self._save_requested = False
        self._base_step: Optional[int] = None
        self.emitted = 0
        self.iters_done = 0
        self.last_saved = 0
        self._scope: Optional[Scope] = None

    # -- lifecycle ----------------------------------------------------------
    def restore(self, scope: Scope,
                expect_seed: Optional[int] = None,
                expect_optimizer: Optional[dict] = None
                ) -> Optional[TrainState]:
        """Restore the newest intact checkpoint into ``scope`` and return
        its :class:`TrainState` (None when the directory holds no
        checkpoint — resume on a fresh directory starts fresh, which is
        what makes ``train(resume=True)`` idempotent under a supervisor).
        """
        if not self.manager.all_steps():
            return None
        step = self.manager.restore(scope=scope)
        if not scope.has(TRAIN_STATE_VAR):
            raise ValueError(
                f"checkpoint ckpt-{step} in {self.dir!r} carries no "
                f"TrainState — it was not written by "
                f"train(checkpoint_dir=...); restore it with "
                f"CheckpointManager.restore instead of resume=True")
        ts = TrainState.from_array(scope.get(TRAIN_STATE_VAR))
        scope.delete(TRAIN_STATE_VAR)
        if expect_seed is not None and ts.random_seed != expect_seed:
            logger.warning(
                "resume: checkpoint was written with program seed %s but "
                "this program uses %s — the RNG stream will NOT be "
                "bit-identical to the original run", ts.random_seed,
                expect_seed)
        if expect_optimizer is not None and ts.optimizer and \
                ts.optimizer != expect_optimizer:
            logger.warning(
                "resume: optimizer config changed across restarts "
                "(checkpoint %s vs current %s)", ts.optimizer,
                expect_optimizer)
        inc_counter("fault/checkpoint_restores")
        emit_event("fault", event="checkpoint_restore", step=ts.emitted,
                   index=step)
        return ts

    def begin(self, scope: Scope, state: Optional[TrainState],
              random_seed: int, optimizer_fp: dict):
        """Arm the coordinator at training-loop entry: record the
        dispatch-boundary base, adopt resumed counters, install signal
        handlers."""
        self._scope = scope
        self._seed = int(random_seed)
        self._opt_fp = dict(optimizer_fp)
        self._restored = state
        if state is not None:
            self.emitted = state.emitted
            self.iters_done = state.iters_done
            self.last_saved = state.emitted
        # boundary invariant: exe._step - base == emitted, exactly when
        # the scope reflects every emitted batch (no half-applied chunk)
        self._base_step = self.exe._step - self.emitted
        if self.handle_signals:
            self._install_signals()

    def close(self):
        """Flush pending async saves and restore signal handlers.

        Runs in the trainer's ``finally``: a write failure here is
        LOGGED, not raised — raising would mask the in-flight exception
        (a ``Preempted`` turned into a fatal status would stop the
        supervisor from relaunching).  On the success path any async
        failure already surfaced through the next blocking save's
        internal ``wait()`` (``final_save`` is blocking)."""
        try:
            self.manager.wait()
        except Exception as e:  # noqa: BLE001
            logger.error(
                "pending checkpoint write failed during shutdown "
                "(%s: %s); the latest checkpoint on disk is older than "
                "the counters suggest", type(e).__name__, e)
        finally:
            for sig, old in self._old_handlers.items():
                try:
                    signal.signal(sig, old)
                except (ValueError, OSError):   # non-main thread/teardown
                    pass
            self._old_handlers.clear()

    def _install_signals(self):
        if threading.current_thread() is not threading.main_thread():
            logger.warning("checkpointer: not on the main thread; "
                           "SIGTERM/SIGINT preemption handling disabled")
            return
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._old_handlers[sig] = signal.signal(
                    sig, self._on_signal)
            except (ValueError, OSError):
                logger.warning("checkpointer: cannot install handler for "
                               "signal %s", sig)

    def _on_signal(self, signum, frame):
        # async-signal context: just set the flag; the loop finishes the
        # in-flight dispatch and takes the emergency checkpoint at the
        # next boundary.  Only a REPEAT of the same signal escalates to
        # the previous handler (impatient operators keep Ctrl-C); a
        # different signal while one is pending must not kill the
        # process during the grace window (Ctrl-C followed by the
        # scheduler's routine SIGTERM would otherwise skip the save).
        if self._preempt_sig == signum:
            old = self._old_handlers.get(signum)
            if callable(old):
                old(signum, frame)
            elif old == signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)
            return
        if self._preempt_sig is None:
            self._preempt_sig = signum

    def resync(self):
        """Re-anchor the boundary base at a known-quiescent point (pass
        start): event handlers that run EXTRA executor work mid-pass
        (e.g. ``trainer.test()`` inside ``EndIteration``) advance the
        step counter past the loop's own dispatches, which suppresses
        boundary detection until this re-anchor — checkpoint cadence
        degrades to at-least-once-per-pass, never silently to zero."""
        self._base_step = self.exe._step - self.emitted

    def request_preempt(self, signum: int = signal.SIGTERM):
        """Programmatic preemption (the faultinject `preempt` action):
        behave exactly as if ``signum`` had arrived."""
        if self._preempt_sig is None:
            self._preempt_sig = signum

    @property
    def preempt_requested(self) -> bool:
        return self._preempt_sig is not None

    def request_save(self):
        """Ask for a BLOCKING checkpoint at the next dispatch boundary,
        independent of the periodic cadence — how the elastic worker
        commits at task boundaries (its ``task_finished`` report to the
        master waits on the commit, which is what anchors the stream's
        exactly-once contract to durable state)."""
        self._save_requested = True

    @property
    def save_pending(self) -> bool:
        """True while a :meth:`request_save` has not yet committed."""
        return self._save_requested

    # -- per-batch hook -----------------------------------------------------
    def on_batch_done(self, pass_id: int, batch_id: int,
                      step_now: Optional[int] = None):
        """Count one completed batch; at dispatch boundaries, honor a
        pending preemption (emergency save + raise Preempted) or the
        periodic save cadence.  ``step_now``: the executor step counter
        snapshotted before this batch's event handler ran (handler-side
        executor work must not blur boundary detection)."""
        self.emitted += 1
        self.iters_done += 1
        if step_now is None:
            step_now = self.exe._step
        if step_now - self._base_step != self.emitted:
            return                       # mid-chunk: scope is ahead of us
        if self._preempt_sig is not None:
            self._save(pass_id, batch_id + 1, emergency=True,
                       blocking=True)
            inc_counter("fault/preemptions")
            emit_event("fault", event="preemption", step=self.emitted,
                       action=f"signal {self._preempt_sig}")
            logger.warning(
                "preempted (signal %s): emergency checkpoint ckpt-%d "
                "committed in %r; exiting %d for the supervisor",
                self._preempt_sig, self.emitted, self.dir, EXIT_PREEMPTED)
            raise Preempted(self.emitted, self.dir)
        if self._save_requested:
            self._save(pass_id, batch_id + 1, blocking=True)
            self._save_requested = False
            return
        if self.save_every is not None and \
                self.emitted - self.last_saved >= self.save_every:
            self._save(pass_id, batch_id + 1)

    def final_save(self, num_passes: int):
        """Commit the end-of-training state (pass_id == num_passes), so a
        supervisor relaunch resumes into an empty pass range and exits 0
        immediately — completion is idempotent.  A relaunch that restored
        an already-final state and ran zero batches skips the re-commit:
        rewriting an identical checkpoint would briefly expose the only
        copy to a crash window for no benefit."""
        r = getattr(self, "_restored", None)
        if r is not None and r.pass_id >= num_passes \
                and self.emitted == r.emitted \
                and not self._save_requested:
            # a pending request_save still commits: a zero-batch tail
            # (e.g. the elastic stream's empty final tasks) advances
            # state the extra_state hook must see durable — dropping it
            # here would leave its task_finished reports forever gated
            # on save_pending
            return
        self._save(num_passes, 0, blocking=True)
        self._save_requested = False   # the final commit satisfies it

    # -- save ---------------------------------------------------------------
    def _save(self, next_pass: int, next_batch: int,
              emergency: bool = False, blocking: bool = False):
        # Task-queue position rides INSIDE the checkpoint (state_dict
        # captured here, committed by the same atomic tmp+rename) — a
        # separate snapshot file could be durably newer than the model
        # it belongs to, marking chunks done the restored model never
        # saw.  Remaining caveat, inherent to chunk-granular tracking
        # with a prefetching reader: records a finished chunk fed into
        # the pipeline but not yet trained at this boundary are lost on
        # resume — the reference's task-level at-least-once, not
        # record-level exactly-once.
        master_state = None
        if self.master is not None and hasattr(self.master, "state_dict"):
            master_state = self.master.state_dict()
        ts = TrainState(
            exe_step=self.exe._step, pass_id=next_pass,
            batch_id=next_batch, emitted=self.emitted,
            iters_done=self.iters_done, random_seed=self._seed,
            optimizer=self._opt_fp, emergency=emergency,
            master=master_state,
            elastic=self._extra_state() if self._extra_state is not None
            else None)
        scope = self._scope
        scope.set(TRAIN_STATE_VAR, ts.to_array())
        # incremental-commit policy: chain a delta while the chain is
        # alive and under both rebase thresholds; otherwise a full
        # rebase.  Emergency saves follow the same policy — a small
        # delta is exactly what makes the SIGTERM grace window cheap.
        kind = "full"
        if self.delta.enabled and self.manager.delta_supported():
            st = self.manager.chain_stats()
            if st["alive"] and st["len"] < self.delta.max_chain and \
                    (st["base_bytes"] <= 0
                     or st["bytes"] < self.delta.rebase_fraction
                     * st["base_bytes"]):
                kind = "delta"
        src = self._delta_source
        rider_keys: list = []

        def _set_riders(state):
            for k, v in state.items():
                scope.set(k, v)
                if k not in rider_keys:
                    rider_keys.append(k)

        tokens = None
        if src is not None:
            # the dirty set snapshots ATOMICALLY with the export (before
            # anything reaches the async writer); commit_delta only runs
            # on the durable ack, retract_delta re-dirties on failure —
            # rows pushed mid-serialization stay dirty for the next delta
            tokens, sv = (src.export_delta() if kind == "delta"
                          else src.export_full())
            _set_riders(sv)
        elif self._state_vars is not None:
            _set_riders(self._state_vars())

        def _attempt(k, tk):
            on_commit = on_fail = None
            if src is not None:
                on_commit = lambda info, t=tk: src.commit_delta(t)  # noqa: E731
                on_fail = lambda exc, t=tk: src.retract_delta(t)    # noqa: E731
            self.manager.save(self.emitted, scope, blocking=blocking,
                              kind=k, on_commit=on_commit,
                              on_fail=on_fail)

        try:
            try:
                _attempt(kind, tokens)
            except DeltaChainError:
                # the chain died between the policy check and the commit
                # (async writer failure, sparse layout change): retract
                # the delta snapshot and rebase with a full export
                if src is not None:
                    src.retract_delta(tokens)
                    tokens, sv = src.export_full()
                    _set_riders(sv)
                _attempt("full", tokens)
        except BaseException:
            # save() raised before this job could run (sticky failure of
            # an EARLIER write, barrier timeout): nothing durable holds
            # this snapshot — re-dirty it.  Idempotent vs the job's own
            # on_fail (the token pops once).
            if src is not None and tokens is not None:
                src.retract_delta(tokens)
            raise
        finally:
            scope.delete(TRAIN_STATE_VAR)
            for k in rider_keys:
                scope.delete(k)
        self.last_saved = self.emitted
        inc_counter("fault/checkpoint_saves")
        emit_event("fault", event="checkpoint_save", step=self.emitted,
                   action="emergency" if emergency else "periodic")
