"""ParamAttr: per-parameter configuration (reference: fluid/param_attr.py).

Adds one TPU-native field the reference lacks: ``sharding`` — a
PartitionSpec-like tuple naming mesh axes per dim, consumed by
paddle_tpu.parallel for tensor-parallel layouts.
"""
from __future__ import annotations

from typing import Optional

from .initializer import Initializer


class ParamAttr:
    def __init__(self, name: Optional[str] = None,
                 initializer: Optional[Initializer] = None,
                 learning_rate: float = 1.0,
                 regularizer=None,
                 trainable: bool = True,
                 gradient_clip=None,
                 sharding=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.sharding = sharding

    @staticmethod
    def _to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else None
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")

    def to_kwargs(self, with_initializer=False):
        kw = {
            "name": self.name,
            "optimize_attr": {"learning_rate": self.learning_rate},
            "regularizer": self.regularizer,
            "trainable": self.trainable,
            "gradient_clip_attr": self.gradient_clip,
            "sharding": self.sharding,
        }
        if with_initializer:
            kw["initializer"] = self.initializer
        return kw


WeightNormParamAttr = ParamAttr  # parity alias (weight-norm TODO)
