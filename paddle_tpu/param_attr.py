"""ParamAttr: per-parameter configuration (reference: fluid/param_attr.py).

Adds one TPU-native field the reference lacks: ``sharding`` — a
PartitionSpec-like tuple naming mesh axes per dim, consumed by
paddle_tpu.parallel for tensor-parallel layouts.
"""
from __future__ import annotations

from typing import Optional

from .initializer import Initializer


class ParamAttr:
    def __init__(self, name: Optional[str] = None,
                 initializer: Optional[Initializer] = None,
                 learning_rate: float = 1.0,
                 regularizer=None,
                 trainable: bool = True,
                 gradient_clip=None,
                 sharding=None,
                 initial_std: Optional[float] = None,
                 initial_mean: float = 0.0,
                 initial_max: Optional[float] = None,
                 initial_min: Optional[float] = None,
                 is_static: bool = False,
                 sparse_update: bool = False,
                 **_v1_kw):
        self.name = name
        # v1 trainer_config_helpers init spellings (ParameterAttribute,
        # attrs.py:131): gaussian via initial_std/mean, uniform via
        # initial_max/min; std==0 means "constant at the mean"
        if initializer is None and initial_std is not None:
            from .initializer import ConstantInitializer, NormalInitializer
            initializer = (ConstantInitializer(initial_mean)
                           if initial_std == 0.0 else
                           NormalInitializer(initial_mean, initial_std))
        elif initializer is None and initial_max is not None:
            from .initializer import UniformInitializer
            lo = initial_min if initial_min is not None else -initial_max
            initializer = UniformInitializer(lo, initial_max)
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable and not is_static
        self.gradient_clip = gradient_clip
        self.sharding = sharding
        self.sparse_update = sparse_update  # row-sparse hint (v1); XLA
        #                                     gathers make this a no-op

    @staticmethod
    def _to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else None
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")

    def to_kwargs(self, with_initializer=False):
        kw = {
            "name": self.name,
            "optimize_attr": {"learning_rate": self.learning_rate},
            "regularizer": self.regularizer,
            "trainable": self.trainable,
            "gradient_clip_attr": self.gradient_clip,
            "sharding": self.sharding,
        }
        if with_initializer:
            kw["initializer"] = self.initializer
        return kw


class WeightNormParamAttr(ParamAttr):
    """Weight normalization (fluid param_attr.py WeightNormParamAttr):
    the layer's weight is reparameterized as w = g * v/||v|| with the
    direction v and per-``dim`` magnitude g trained independently; the
    normalize runs in-graph every step (LayerHelper emits the ops)."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
