"""Program visualization (reference: fluid/net_drawer.py graphviz export).

Emits graphviz DOT text for a Program — data/parameter/op nodes with
dataflow edges; sub-blocks render as clusters.  No graphviz dependency:
the DOT string can be written to a file and rendered externally.
"""
from __future__ import annotations

from .core.program import Parameter, Program, default_main_program

_OP_STYLE = 'shape=box,style=filled,fillcolor="#BBDEFB"'
_PARAM_STYLE = 'shape=oval,style=filled,fillcolor="#C8E6C9"'
_DATA_STYLE = 'shape=oval,style=filled,fillcolor="#FFE0B2"'
_VAR_STYLE = 'shape=oval'


def draw_graph(program: Program = None, path: str = None) -> str:
    program = program or default_main_program()
    lines = ["digraph Program {", "  rankdir=TB;"]
    seen_vars = set()

    def var_node(b_idx, name, var):
        key = f"v_{b_idx}_{name}".replace(".", "_").replace("@", "_AT_")
        if key in seen_vars:
            return key
        seen_vars.add(key)
        if isinstance(var, Parameter):
            style = _PARAM_STYLE
        elif var is not None and getattr(var, "is_data", False):
            style = _DATA_STYLE
        else:
            style = _VAR_STYLE
        lines.append(f'  {key} [label="{name}",{style}];')
        return key

    for b in program.blocks:
        prefix = "" if b.idx == 0 else "  "
        if b.idx != 0:
            lines.append(f"  subgraph cluster_block{b.idx} {{ "
                         f'label="block {b.idx}";')
        for i, op in enumerate(b.ops):
            okey = f"op_{b.idx}_{i}"
            lines.append(f'{prefix}  {okey} [label="{op.type}",{_OP_STYLE}];')
            for n in op.input_names:
                v = b.vars.get(n) or program.global_block().vars.get(n)
                lines.append(f"{prefix}  {var_node(b.idx, n, v)} -> {okey};")
            for n in op.output_names:
                v = b.vars.get(n) or program.global_block().vars.get(n)
                lines.append(f"{prefix}  {okey} -> {var_node(b.idx, n, v)};")
        if b.idx != 0:
            lines.append("  }")
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
