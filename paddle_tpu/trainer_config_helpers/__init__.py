"""v1 config DSL compatibility layer (reference:
python/paddle/trainer_config_helpers/ — layers.py 137 layer functions,
activations.py, optimizers.py, poolings.py; consumed by
python/paddle/trainer/config_parser.py).

The reference's benchmark configs (benchmark/paddle/image/*.py,
benchmark/paddle/rnn/rnn.py) and v1 demos are plain Python files evaluated
with this DSL in scope.  Here each DSL call appends to the current
paddle_tpu default program directly — there is no TrainerConfig proto stage —
so a v1 config file "launches unchanged" via ``load_v1_config`` and trains on
TPU with the modern executor.

Covered surface = everything the shipped benchmarks/demos use: settings,
get_config_arg, define_py_data_sources2, data_layer, fc_layer,
img_conv_layer, img_pool_layer, img_cmrnorm_layer, batch_norm_layer,
dropout_layer, embedding_layer, concat_layer, addto_layer, simple_lstm,
lstmemory, last_seq, first_seq, classification_cost, cross_entropy(_cost),
regression_cost, outputs, activation/pooling/optimizer/regularization
objects.
"""
from __future__ import annotations

import copy
import math
from typing import Optional

import numpy as np

from .. import layers as L
from .. import optimizer as opt_mod
from .. import regularizer as reg_mod
from ..param_attr import ParamAttr

__all__ = [
    "settings", "get_config_arg", "define_py_data_sources2", "outputs",
    "inputs", "Inputs", "Outputs",
    "data_layer", "fc_layer", "img_conv_layer", "img_pool_layer",
    "img_cmrnorm_layer", "batch_norm_layer", "dropout_layer",
    "embedding_layer", "concat_layer", "addto_layer", "simple_lstm",
    "lstmemory", "last_seq", "first_seq", "max_pooling_seq",
    "classification_cost", "cross_entropy", "cross_entropy_cost",
    "regression_cost", "mse_cost",
    "img_conv_group", "conv_projection", "ExtraAttr",
    "ExtraLayerAttribute", "ParamAttr", "default_device",
    "LinearActivation", "ReluActivation", "SigmoidActivation",
    "TanhActivation", "SoftmaxActivation", "IdentityActivation",
    "STanhActivation", "ExpActivation", "AbsActivation",
    "SquareActivation", "BReluActivation", "SoftReluActivation",
    "MaxPooling", "AvgPooling", "SumPooling",
    "CudnnMaxPooling", "CudnnAvgPooling", "ExpandLevel", "AggregateLevel",
    "MomentumOptimizer", "AdamOptimizer", "AdaGradOptimizer",
    "RMSPropOptimizer", "AdaDeltaOptimizer",
    "L1Regularization", "L2Regularization", "ModelAverage",
    "load_v1_config", "V1Config",
    # sequence/generation DSL (sequence.py)
    "memory", "recurrent_group", "StaticInput", "GeneratedInput",
    "SubsequenceInput", "mixed_layer", "MixedLayerType",
    "full_matrix_projection", "trans_full_matrix_projection",
    "table_projection", "identity_projection", "dotmul_projection",
    "scaling_projection", "slice_projection", "recurrent_layer",
    "lstmemory_group",
    "grumemory", "gru_group", "simple_gru", "beam_search",
    "crf_layer", "crf_decoding_layer",
    "sum_evaluator", "chunk_evaluator", "seqtext_printer_evaluator",
    "classification_error_evaluator",
    "maxid_layer", "pooling_layer", "sequence_conv_pool",
    "bidirectional_lstm", "expand_layer", "scaling_layer",
    "simple_attention", "gru_step_layer",
    "power_layer", "slope_intercept_layer", "sum_to_one_norm_layer",
    "cos_sim", "trans_layer", "repeat_layer", "seq_reshape_layer",
    "print_layer",
]


# ---------------------------------------------------------------------------
# config-level state
# ---------------------------------------------------------------------------
class _ConfigState:
    def __init__(self):
        self.args = {}
        self.settings = {}
        self.outputs = []
        self.data_sources = None
        self.data_layers = {}
        self.named_layers = {}
        self.evaluators = []
        self.input_order = None
        self.defaults = {}      # default_momentum/default_decay_rate values
        # loader-declared sequence inputs (the v1 DataProvider's
        # *_sequence declarations, which configs never carried themselves):
        # data_layer names listed here build as lod_level-1 vars
        self.sequence_inputs = set()


_state = _ConfigState()


def get_config_arg(name, type_=str, default=None):
    """command-line config args (config_parser get_config_arg)."""
    v = _state.args.get(name, default)
    if v is None:
        return None
    if type_ is bool and isinstance(v, str):
        return v.lower() in ("1", "true", "yes")
    return type_(v)


def settings(batch_size=None, learning_rate=1e-3, learning_method=None,
             regularization=None, gradient_clipping_threshold=None,
             model_average=None, learning_rate_decay_a=0.0,
             learning_rate_decay_b=0.0, **kw):
    _state.settings = {
        "batch_size": batch_size,
        "learning_rate": learning_rate,
        "learning_method": learning_method,
        "regularization": regularization,
        "gradient_clipping_threshold": gradient_clipping_threshold,
        "model_average": model_average,
        "learning_rate_decay_a": learning_rate_decay_a,
        "learning_rate_decay_b": learning_rate_decay_b,
    }
    _state.settings.update(kw)


def define_py_data_sources2(train_list, test_list, module=None, obj=None,
                            args=None):
    """Recorded for the caller; the TPU runner feeds via reader/DataFeeder
    instead of the embedded PyDataProvider2."""
    _state.data_sources = {"train_list": train_list, "test_list": test_list,
                           "module": module, "obj": obj, "args": args}


def outputs(*vars_):
    flat = []
    for v in vars_:
        if isinstance(v, (list, tuple)):
            flat.extend(v)          # v1 allowed outputs([a, b])
        else:
            flat.append(v)
    _state.outputs = flat


def inputs(*layers):
    """v1 inputs(): fixes the data-layer feed order."""
    _state.input_order = [getattr(v, "name", v) for v in layers]


def Inputs(*names):
    """config_parser Inputs(): name-based variant used by .conf files."""
    _state.input_order = list(names)


def Outputs(*names):
    """config_parser Outputs(): resolve by layer name at config close."""
    _state.outputs = [_state.named_layers.get(n, n) for n in names]


def default_device(device_id):
    """v1 per-layer device placement hint: placement is owned by XLA on
    TPU; accepted for config compatibility."""


class ModelAverage:
    """v1 settings(model_average=...): recorded; the trainer applies
    parameter averaging over a trailing window when configured."""

    def __init__(self, average_window, max_average_window=None,
                 do_average_in_cpu=False):
        self.average_window = average_window
        self.max_average_window = max_average_window


# ---------------------------------------------------------------------------
# activation / pooling / optimizer / regularization objects
# ---------------------------------------------------------------------------
class _Act:
    act = None

    def __init__(self):
        pass


class LinearActivation(_Act):
    act = None


IdentityActivation = LinearActivation


class ReluActivation(_Act):
    act = "relu"


class SigmoidActivation(_Act):
    act = "sigmoid"


class TanhActivation(_Act):
    act = "tanh"


class SoftmaxActivation(_Act):
    act = "softmax"


class STanhActivation(_Act):
    act = "stanh"              # 1.7159 * tanh(2x/3), STanhActivation.cpp


class ExpActivation(_Act):
    act = "exp"


class AbsActivation(_Act):
    act = "abs"


class SquareActivation(_Act):
    act = "square"


class BReluActivation(_Act):
    act = "brelu"


class SoftReluActivation(_Act):
    act = "softrelu"


def _act_name(a):
    if a is None:
        return None
    if isinstance(a, str):
        return a
    return a.act


class MaxPooling:
    ptype = "max"


class AvgPooling:
    ptype = "avg"


class SumPooling:
    ptype = "sum"


CudnnMaxPooling = MaxPooling     # cudnn variants are layout hints on TPU
CudnnAvgPooling = AvgPooling


class ExpandLevel:
    """v1 expand_layer levels (layers.py ExpandLevel)."""
    FROM_NO_SEQUENCE = 0
    FROM_SEQUENCE = 1
    FROM_TIMESTEP = FROM_NO_SEQUENCE


class AggregateLevel:
    """v1 pooling/agg levels (layers.py AggregateLevel)."""
    TO_NO_SEQUENCE = 0
    TO_SEQUENCE = 1
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class MomentumOptimizer:
    def __init__(self, momentum=0.9, sparse=False):
        self.momentum = momentum

    def make(self, lr, reg):
        return opt_mod.Momentum(learning_rate=lr, momentum=self.momentum,
                                regularization=reg)


class AdamOptimizer:
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def make(self, lr, reg):
        return opt_mod.Adam(learning_rate=lr, beta1=self.beta1,
                            beta2=self.beta2, epsilon=self.epsilon,
                            regularization=reg)


class AdaGradOptimizer:
    def make(self, lr, reg):
        return opt_mod.Adagrad(learning_rate=lr, regularization=reg)


class RMSPropOptimizer:
    def make(self, lr, reg):
        return opt_mod.RMSProp(learning_rate=lr, regularization=reg)


class AdaDeltaOptimizer:
    def make(self, lr, reg):
        return opt_mod.Adadelta(learning_rate=lr, regularization=reg)


class L1Regularization:
    def __init__(self, rate):
        self.rate = rate

    def make(self):
        return reg_mod.L1Decay(self.rate)


class L2Regularization:
    def __init__(self, rate):
        self.rate = rate

    def make(self):
        return reg_mod.L2Decay(self.rate)


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------
def data_layer(name, size, height=None, width=None, depth=None,
               is_seq=False, lod_level=None, **kw):
    """v1 data_layer: flat ``size`` input.  Image configs pass height/width
    via img_conv_layer's num_channels; sequence configs treat size as the
    vocab.  The var records ``v1_size`` so embedding/conv can recover
    semantics.  ``is_seq``/``lod_level`` mark a dense-vector-sequence input
    (the role the v1 DataProvider's ``dense_vector_sequence`` declaration
    played — config-side here because providers are plain readers): the
    feed becomes padded [B, T, size] + ``name@LEN``, e.g. per-query
    document lists for lambda_cost."""
    if not is_seq and lod_level is None and name in _state.sequence_inputs:
        is_seq = True
    lod = 1 if is_seq else int(lod_level or 0)
    v = L.data(name, shape=[size], dtype="float32", lod_level=lod)
    v.v1_size = size
    _state.data_layers[name] = v
    return v


def _as_image(input, num_channels):
    """Reshape a flat v1 data layer to [C, H, W] (square images, the v1
    convention when height/width are unspecified)."""
    if input.shape is not None and len(input.shape) == 4:
        return input
    size = getattr(input, "v1_size", None) or int(np.prod(input.shape[1:]))
    hw = int(round(math.sqrt(size // num_channels)))
    return L.reshape(input, [-1, num_channels, hw, hw])


class ExtraLayerAttribute:
    """v1 ExtraLayerAttribute (drop_rate is the only knob the benchmark
    configs use)."""

    def __init__(self, drop_rate=None, **kw):
        self.drop_rate = drop_rate


ExtraAttr = ExtraLayerAttribute


def _apply_layer_attr(out, layer_attr):
    if layer_attr is not None and getattr(layer_attr, "drop_rate", None):
        out = L.dropout(out, layer_attr.drop_rate)
    return out


def _v1_named_attr(attr, pname):
    """v1 deterministic parameter naming (config_parser.py: an explicitly
    named layer owns parameters ``_<layer>.w<i>`` / ``_<layer>.wbias``) —
    what api.GradientMachine parameter sharing keys on across separately
    built machines (the GAN trainer's copy_shared_parameters idiom).
    Clones the attr (configs reuse one ParamAttr across layers); explicit
    attr names and disabled (False) attrs pass through untouched."""
    if attr is False or pname is None:
        return attr
    attr = ParamAttr._to_attr(attr)
    if attr is None or attr.name is not None:
        return attr
    attr = copy.copy(attr)
    attr.name = pname
    return attr


def fc_layer(input, size, act=None, name=None, param_attr=None,
             bias_attr=None, layer_attr=None, **kw):
    inputs = input if isinstance(input, (list, tuple)) else [input]
    flat = []
    for v in inputs:
        if v.shape is not None and len(v.shape) > 2 and v.lod_level == 0:
            v = L.reshape(v, [-1, int(np.prod(v.shape[1:]))])
        flat.append(v)
    nfd = 2 if flat[0].lod_level else 1
    if name is not None:
        if isinstance(param_attr, (list, tuple)):
            param_attr = [_v1_named_attr(a, f"_{name}.w{i}")
                          for i, a in enumerate(param_attr)]
        elif len(flat) > 1:
            param_attr = [_v1_named_attr(param_attr, f"_{name}.w{i}")
                          for i in range(len(flat))]
        else:
            param_attr = _v1_named_attr(param_attr, f"_{name}.w0")
        bias_attr = _v1_named_attr(bias_attr, f"_{name}.wbias")
    out = L.fc(flat if len(flat) > 1 else flat[0], size=size,
               num_flatten_dims=nfd, act=_act_name(act), name=name,
               param_attr=param_attr, bias_attr=bias_attr)
    return track_layer(name, _apply_layer_attr(out, layer_attr))


def img_conv_layer(input, filter_size, num_filters, name=None,
                   num_channels=None, act=None, groups=1, stride=1,
                   padding=0, bias_attr=None, param_attr=None,
                   shared_biases=True, layer_attr=None, filter_size_y=None,
                   stride_y=None, padding_y=None, trans=False, **kw):
    if num_channels is not None:
        input = _as_image(input, num_channels)
    fs = (filter_size, filter_size_y) if filter_size_y else filter_size
    st = (stride, stride_y) if stride_y else stride
    pd = (padding, padding_y) if padding_y else padding
    f = L.conv2d_transpose if trans else L.conv2d
    return f(input, num_filters=num_filters, filter_size=fs, stride=st,
             padding=pd, groups=groups, act=_act_name(act), name=name,
             param_attr=_v1_named_attr(param_attr, f"_{name}.w0"
                                       if name else None),
             bias_attr=_v1_named_attr(bias_attr, f"_{name}.wbias"
                                      if name else None))


def img_pool_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                   name=None, num_channels=None, ceil_mode=True,
                   pool_size_y=None, stride_y=None, padding_y=None, **kw):
    if num_channels is not None:
        input = _as_image(input, num_channels)
    ptype = pool_type.ptype if pool_type is not None else "max"
    ps = (pool_size, pool_size_y) if pool_size_y is not None else pool_size
    st = (stride, stride_y) if stride_y is not None else stride
    pd = (padding, padding_y) if padding_y is not None else padding
    return L.pool2d(input, pool_size=list(ps) if isinstance(ps, tuple)
                    else ps, pool_type=ptype,
                    pool_stride=list(st) if isinstance(st, tuple) else st,
                    pool_padding=list(pd) if isinstance(pd, tuple) else pd,
                    ceil_mode=ceil_mode, name=name)


def img_cmrnorm_layer(input, size=5, scale=0.0001, power=0.75, name=None,
                      num_channels=None, **kw):
    """v1 cross-map response norm == LRN (ImgCMRNormLayer)."""
    if num_channels is not None:
        input = _as_image(input, num_channels)
    return L.lrn(input, n=size, alpha=scale, beta=power, name=name)


def batch_norm_layer(input, act=None, name=None, num_channels=None,
                     bias_attr=None, param_attr=None, use_global_stats=None,
                     moving_average_fraction=0.9, **kw):
    if num_channels is not None and (input.shape is None or
                                     len(input.shape) != 4):
        input = _as_image(input, num_channels)
    return L.batch_norm(input, act=_act_name(act),
                        momentum=moving_average_fraction,
                        param_attr=_v1_named_attr(param_attr, f"_{name}.w0"
                                                  if name else None),
                        bias_attr=_v1_named_attr(bias_attr, f"_{name}.wbias"
                                                 if name else None),
                        moving_mean_name=f"_{name}.w1" if name else None,
                        moving_variance_name=f"_{name}.w2" if name else None,
                        use_global_stats=use_global_stats,
                        name=name)


def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type=None, param_attr=None, **kw):
    """trainer_config_helpers.networks img_conv_group."""
    from .. import nets
    if num_channels is not None:
        input = _as_image(input, num_channels)
    return nets.img_conv_group(
        input, conv_num_filter=list(conv_num_filter), pool_size=pool_size,
        conv_padding=conv_padding, conv_filter_size=conv_filter_size,
        conv_act=_act_name(conv_act),
        conv_with_batchnorm=conv_with_batchnorm,
        conv_batchnorm_drop_rate=conv_batchnorm_drop_rate,
        pool_stride=pool_stride,
        pool_type=pool_type.ptype if pool_type is not None else "max",
        # v1 PoolLayer sizes outputs with ceil (img_pool_layer's default
        # here too); light_mnist's 4-stage chain needs it to keep spatial
        # dims >= 1 (28 -> ... -> 1 instead of collapsing to 0)
        pool_ceil_mode=True,
        param_attr=param_attr)


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, **kw):
    """v1 conv projection (used inside MixedLayer/concat): plain conv here."""
    return img_conv_layer(input, filter_size=filter_size,
                          num_filters=num_filters,
                          num_channels=num_channels, stride=stride,
                          padding=padding)


def dropout_layer(input, dropout_rate, name=None):
    return L.dropout(input, dropout_prob=dropout_rate, name=name)


def embedding_layer(input, size, name=None, param_attr=None, **kw):
    vocab = getattr(input, "v1_size", None)
    if vocab is None:
        raise ValueError("embedding_layer input must be a data_layer with "
                         "its vocab as size")
    ids = input
    if ids.dtype != np.dtype("int64"):
        # v1 integer_value_sequence arrives as the same data layer; re-type
        ids.dtype = np.dtype("int64")
        ids.lod_level = 1
        ids.shape = (-1, -1)
    return L.embedding(ids, size=[vocab, size], param_attr=param_attr,
                       name=name)


def concat_layer(input, act=None, name=None, bias_attr=None, **kw):
    """v1 concat (axis 1 = features/channels).  Items may be layer outputs
    OR projections (ConcatenateLayer2 accepted projections directly)."""
    from .sequence import _Projection, track_layer
    items = [it.build(0) if isinstance(it, _Projection) else it
             for it in input]
    out = L.concat(items, axis=1, name=name)
    if bias_attr not in (None, False):
        from ..layer_helper import LayerHelper
        helper = LayerHelper("concat_bias")
        if out.shape and out.shape[1] and out.shape[1] > 0:
            csize = out.shape[1]
        else:
            # infer the concat width: sum of the inputs' concat-axis dims
            csize = sum(it.shape[1] for it in items)
        b = helper.create_parameter(
            bias_attr if isinstance(bias_attr, ParamAttr) else ParamAttr(),
            shape=[csize], dtype=out.dtype, is_bias=True)
        axis = 1 if (out.shape is not None and len(out.shape) == 4) else -1
        out = L.elementwise_add(out, b, axis=axis)
    a = _act_name(act)
    if a:
        out = getattr(L, a)(out)
    return track_layer(name, out)


def addto_layer(input, act=None, name=None, bias_attr=None, **kw):
    inputs = input if isinstance(input, (list, tuple)) else [input]
    out = inputs[0]
    for v in inputs[1:]:
        out = L.elementwise_add(out, v)
    a = _act_name(act)
    if a:
        out = getattr(L, a)(out)
    return out


def simple_lstm(input, size, name=None, reverse=False, act=None,
                gate_act=None, **kw):
    """trainer_config_helpers simple_lstm: fc(4*size) + lstmemory."""
    proj = L.fc(input, size=size * 4, num_flatten_dims=2)
    hid, _ = L.dynamic_lstm(proj, size=size * 4, is_reverse=reverse,
                            name=name)
    return hid


def lstmemory(input, name=None, reverse=False, act=None, gate_act=None,
              size=None, **kw):
    """v1 lstmemory: input must already be the 4x gate projection."""
    hid, _ = L.dynamic_lstm(input, size=input.shape[-1], is_reverse=reverse,
                            name=name)
    return hid


def last_seq(input, name=None, **kw):
    return track_layer(name, L.sequence_last_step(input, name=name))


def first_seq(input, name=None, **kw):
    return track_layer(name, L.sequence_first_step(input, name=name))


def max_pooling_seq(input, name=None, **kw):
    return track_layer(name, L.sequence_pool(input, "max", name=name))


def _label_layer(label):
    if getattr(label, "is_data", False) and \
            label.dtype != np.dtype("int64"):
        label.dtype = np.dtype("int64")
        if label.shape is not None and label.shape[-1] != 1:
            label.shape = (-1, 1)
    return label


def classification_cost(input, label, name=None, evaluator=None, **kw):
    label = _label_layer(label)
    return L.mean(L.cross_entropy(input, label), name=name)


def cross_entropy_cost(input, label, name=None, **kw):
    return classification_cost(input, label, name)


cross_entropy = cross_entropy_cost


def regression_cost(input, label, name=None, **kw):
    return L.mean(L.square_error_cost(input, label), name=name)


mse_cost = regression_cost


# ---------------------------------------------------------------------------
# sequence / generation DSL (recurrent_group, mixed_layer, beam_search, CRF)
# ---------------------------------------------------------------------------
from .sequence import (  # noqa: E402
    memory, recurrent_group, StaticInput, GeneratedInput, SubsequenceInput,
    mixed_layer, MixedLayerType, full_matrix_projection,
    trans_full_matrix_projection, table_projection, identity_projection,
    dotmul_projection, scaling_projection, recurrent_layer, lstmemory_group,
    grumemory, gru_group, simple_gru, beam_search, crf_layer,
    crf_decoding_layer, sum_evaluator, chunk_evaluator,
    seqtext_printer_evaluator, classification_error_evaluator, track_layer,
    slice_projection,
    maxid_layer, pooling_layer, sequence_conv_pool, bidirectional_lstm,
    expand_layer, scaling_layer, simple_attention, gru_step_layer,
    power_layer, slope_intercept_layer, sum_to_one_norm_layer, cos_sim,
    trans_layer, repeat_layer, seq_reshape_layer, print_layer)

# DSL tail (extra_layers.py) + networks composites (networks_extra.py):
# appended so load_v1_config's namespace carries the full reference surface
from .extra_layers import *        # noqa: E402,F401,F403
from .networks_extra import *      # noqa: E402,F401,F403
from .extra_layers import __all__ as _extra_all        # noqa: E402
from .networks_extra import __all__ as _networks_all   # noqa: E402
from . import layer_math           # noqa: E402  (vae_conf: layer_math.exp)
__all__ += [n for n in list(_extra_all) + list(_networks_all)
            if n not in __all__] + ["layer_math"]


# -- default_decorators.py shims (model_zoo configs call these) -------------
def default_momentum(m):
    """default_decorators.py: the momentum Settings('momentum') uses, and
    the fallback when settings() names no learning_method."""
    _state.defaults["momentum"] = m


def default_decay_rate(r):
    """default_decorators.py: weight decay applied when settings() names
    no regularization (consumed by make_optimizer)."""
    _state.defaults["decay_rate"] = r


def _default_noop(*a, **kw):
    return None


# initial_std/mean/strategy/smart map onto the global Xavier/defaults the
# initializer module already applies; batch-regularization and clipping
# are optimizer-level knobs read from settings()
default_initial_std = default_initial_mean = _default_noop
default_initial_strategy = default_initial_smart = _default_noop
default_num_batches_regularization = _default_noop
default_gradient_clipping_threshold = _default_noop

def Settings(algorithm="sgd", batch_size=None, learning_rate=1e-3,
             learning_method=None, **kw):
    """Raw config_parser Settings() (trainer/config_parser.py) — the
    pre-helpers API the model_zoo configs use; maps onto settings()."""
    method_map = {"adam": AdamOptimizer, "adagrad": AdaGradOptimizer,
                  "rmsprop": RMSPropOptimizer,
                  "adadelta": AdaDeltaOptimizer}
    method = learning_method
    if isinstance(method, str):
        if method in ("momentum", "sgd"):
            method = MomentumOptimizer(
                _state.defaults.get("momentum", 0.9))
        else:
            method = method_map.get(method, MomentumOptimizer)()
    settings(batch_size=batch_size, learning_rate=learning_rate,
             learning_method=method,
             **{k: v for k, v in kw.items()
                if k in ("regularization", "learning_rate_decay_a",
                         "learning_rate_decay_b", "gradient_clipping_threshold")})


__all__ += ["default_momentum", "default_decay_rate",
            "default_initial_std", "default_initial_mean",
            "default_initial_strategy", "default_initial_smart",
            "default_num_batches_regularization",
            "default_gradient_clipping_threshold", "Settings"]


# ---------------------------------------------------------------------------
# config loader
# ---------------------------------------------------------------------------
class V1Config:
    """Result of evaluating a v1 config file: the built program + metadata."""

    def __init__(self, main_program, startup_program, outputs, settings,
                 data_layers, data_sources, evaluators=None,
                 named_layers=None, input_order=None, defaults=None):
        self.main_program = main_program
        self.startup_program = startup_program
        self.outputs = outputs
        self.settings = settings
        self.data_layers = data_layers
        self.data_sources = data_sources
        self.evaluators = evaluators or []
        self.named_layers = named_layers or {}
        self.input_order = input_order
        self.defaults = dict(defaults or {})

    def make_optimizer(self):
        s = self.settings
        lr = s.get("learning_rate", 1e-3)
        decay_a = s.get("learning_rate_decay_a") or 0.0
        decay_b = s.get("learning_rate_decay_b") or 0.0
        if decay_a and decay_b:
            # v1 default LR schedule; builds on the step counter inside the
            # current program (make_optimizer runs under program_guard)
            from .. import lr_decay
            lr = lr_decay.v1_poly_decay(lr, decay_a, decay_b,
                                        s.get("batch_size") or 1)
        reg_obj = s.get("regularization")
        if reg_obj is None and self.defaults.get("decay_rate"):
            reg_obj = L2Regularization(self.defaults["decay_rate"])
        reg = reg_obj.make() if reg_obj is not None else None
        method = s.get("learning_method")
        if method is None:
            return opt_mod.SGD(learning_rate=lr, regularization=reg)
        return method.make(lr, reg)

    def minimize_outputs(self):
        """append_backward + optimizer on the first output (the cost)."""
        from ..core.program import program_guard
        with program_guard(self.main_program, self.startup_program):
            self.make_optimizer().minimize(self.outputs[0])
        return self.outputs[0]


def _install_import_shim():
    """Make ``from paddle.trainer_config_helpers import *`` resolve to THIS
    module so reference config files execute verbatim."""
    import sys
    import types
    this = sys.modules[__name__]
    if "paddle.trainer_config_helpers" in sys.modules:
        return
    pkg = sys.modules.get("paddle")
    if pkg is None:
        pkg = types.ModuleType("paddle")
        sys.modules["paddle"] = pkg
    pkg.trainer_config_helpers = this
    sys.modules["paddle.trainer_config_helpers"] = this


# reference networks.py:136 — text_conv_pool is sequence_conv_pool
text_conv_pool = sequence_conv_pool
__all__.append("text_conv_pool")


def load_v1_config(path, sequence_inputs=(), **config_args):
    """Evaluate a v1 config file (the config_parser.parse_config role,
    config_parser.py:126) against a fresh program pair.  Python-2-era
    configs work: ``xrange`` is aliased and the ``paddle`` import is
    shimmed.  ``sequence_inputs`` names data layers that the original
    DataProvider declared as sequences (e.g. dense_vector_sequence) —
    those build as lod_level-1 padded inputs."""
    import paddle_tpu as pt

    global _state
    _state = _ConfigState()
    _state.args = dict(config_args)
    _state.sequence_inputs = set(sequence_inputs)
    _install_import_shim()
    main, startup = pt.Program(), pt.Program()
    ns = {k: globals()[k] for k in __all__
          if k not in ("load_v1_config", "V1Config")}
    ns["__file__"] = path
    ns["xrange"] = range
    with pt.program_guard(main, startup):
        with open(path) as f:
            code = compile(f.read(), path, "exec")
        exec(code, ns)
    return V1Config(main, startup, list(_state.outputs),
                    dict(_state.settings), dict(_state.data_layers),
                    _state.data_sources, evaluators=list(_state.evaluators),
                    named_layers=dict(_state.named_layers),
                    input_order=_state.input_order,
                    defaults=dict(_state.defaults))
